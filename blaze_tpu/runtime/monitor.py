"""Resource accounting + live metrics service.

ROADMAP item 4 asks for `bytes_copied` as a first-class metric before
any zero-copy work starts (Zerrow's finding: "zero-copy" pipelines
silently copy at boundaries — you can't drive down what you don't
count), and ROADMAP item 1 needs per-query resource attribution as the
billing/SLO record of the future multi-tenant service. This module is
both: continuous byte accounting at every copy boundary, a background
sampler, and the exporters that make the numbers visible.

  accounting  `count_copy(boundary, nbytes, moved=...)` — called from
              the five copy boundaries of the engine:
                serde     frame encode/decode in columnar/serde.py
                          (copied = raw payload bytes built/rebuilt,
                          moved = compressed frame bytes crossing)
                ffi       host<->device transfers (serde.to_host pull,
                          host_sort.host_to_device upload) and the
                          native-ABI result payload (native_entry)
                shuffle   partition-split frames pushed into the
                          writer state / RSS writer (ops/shuffle.py),
                          plus reader-side fetches — one entry per
                          logical transfer: a socket stream is a copy,
                          a same-host mmap hit books moved-only
                          (shuffle_server.fetch_frames)
                spill     SpillFile write + re-read (runtime/memory.py)
                fallback  row-interpreter Arrow export (spark/fallback)
              Counts accumulate process-wide AND per query/stage: the
              query id comes from the trace context when tracing is on
              (the supervisor replays it on pool threads), else from
              the runner-registered active query. Disabled
              (conf.monitor_enabled=False) every call is one truthiness
              check at the call site.

  sampler     ResourceMonitor — a daemon thread recording MemManager
              usage (incl. pipeline_reserved + spill pages), pool
              occupancy, pipeline queue depths and compile-cache stats
              into a bounded time-series ring every
              conf.monitor_sample_ms.

  exporters   prometheus_text() — Prometheus text exposition format;
              MetricsServer serves it over stdlib http.server on
              conf.metrics_port (daemon thread, lazily started by the
              local runner). tools/blaze_top.py renders the same
              registry as a live console; per-query roll-ups merge
              into the run ledger and explain_analyze
              ("moved X MiB, copied Y MiB (Z%)" per stage).

  leak check  finish_query() — always-on telemetry (independent of
              monitor_enabled): live pipeline streams, pipeline
              reservations, or nonzero MemManager consumers at query
              end emit a `resource_leak` trace event and count in the
              run ledger (the soak-only checks of chaos_soak, promoted
              to every query).
"""

from __future__ import annotations

import http.server
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from blaze_tpu.config import conf
from blaze_tpu.runtime import trace

BOUNDARIES = ("serde", "ffi", "shuffle", "spill", "fallback")

_lock = threading.Lock()
_copied: Dict[str, int] = {b: 0 for b in BOUNDARIES}
_moved: Dict[str, int] = {b: 0 for b in BOUNDARIES}
_leaks_total = 0
# runner-registered active query: attribution fallback when tracing is
# off (the trace context stack is only populated by enabled spans)
_active_qid: Optional[str] = None
_queries: Dict[str, "_QueryAcct"] = {}


class _QueryAcct:
    """Per-query accumulator (popped at query_end into the roll-up)."""

    __slots__ = ("qid", "copied", "moved", "stage_copied", "stage_moved",
                 "t0", "spilled0", "spill_count0", "compile0",
                 "time_ns", "stage_time_ns", "zc0")

    def __init__(self, qid: str) -> None:
        self.qid = qid
        self.copied: Dict[str, int] = {}
        self.moved: Dict[str, int] = {}
        self.stage_copied: Dict[Any, int] = {}
        self.stage_moved: Dict[Any, int] = {}
        self.t0 = time.time()
        self.spilled0 = 0
        self.spill_count0 = 0
        self.compile0: Dict[str, int] = {}
        # boundary-time accounting (count_time): wall ns per critical-
        # path category, query-level and per stage
        self.time_ns: Dict[str, int] = {}
        self.stage_time_ns: Dict[Any, Dict[str, int]] = {}
        # zero-copy event watermark: query_end reports the delta, so the
        # run record carries mmap/dict evidence (lock-free snapshot —
        # constructors run both with and without _lock held)
        self.zc0 = {k: _zerocopy.get(k, 0) for k in ZEROCOPY_KEYS}


# -- copy/byte accounting ----------------------------------------------------


def count_copy(boundary: str, nbytes: int, moved: Optional[int] = None
               ) -> None:
    """Account one copy at `boundary`: `nbytes` bytes duplicated
    (bytes_copied), `moved` bytes crossing the boundary (bytes_moved,
    defaults to nbytes). Call sites gate on conf.monitor_enabled so the
    disabled hot path pays one truthiness check."""
    if not conf.monitor_enabled:
        return
    n = int(nbytes)
    m = n if moved is None else int(moved)
    if n <= 0 and m <= 0:
        return
    ctx = trace.current_context()
    sid = ctx.get("stage_id")
    with _lock:
        _copied[boundary] = _copied.get(boundary, 0) + n
        _moved[boundary] = _moved.get(boundary, 0) + m
        qid = ctx.get("query_id") or _active_qid
        q = _queries.get(qid) if qid else None
        if q is not None:
            q.copied[boundary] = q.copied.get(boundary, 0) + n
            q.moved[boundary] = q.moved.get(boundary, 0) + m
            if sid is not None:
                q.stage_copied[sid] = q.stage_copied.get(sid, 0) + n
                q.stage_moved[sid] = q.stage_moved.get(sid, 0) + m


# boundary-time categories (runtime/doctor.py critical-path terms):
# each lands in the run ledger as "<category>_ms" and on stage spans.
TIME_CATEGORIES = ("sched_queue", "serde_encode", "serde_decode",
                   "shuffle_io", "spill", "device_compute",
                   "host_compute", "retry_backoff")


def count_time(category: str, ns: int, qid: Optional[str] = None,
               sid: Optional[Any] = None) -> None:
    """Account `ns` wall nanoseconds of `category` work (serde encode,
    spill I/O, device compute, ...) against the attributed query/stage —
    the time-domain twin of count_copy, feeding the doctor's additive
    critical-path breakdown. Attribution follows count_copy (trace
    context, then the runner-registered active query) unless qid/sid are
    passed explicitly (the fair scheduler's workers have no trace
    context). Call sites gate on conf.monitor_enabled."""
    if not conf.monitor_enabled:
        return
    n = int(ns)
    if n <= 0:
        return
    if qid is None or sid is None:
        ctx = trace.current_context()
        if qid is None:
            qid = ctx.get("query_id")
        if sid is None:
            sid = ctx.get("stage_id")
    with _lock:
        qid = qid or _active_qid
        q = _queries.get(qid) if qid else None
        if q is None:
            return
        q.time_ns[category] = q.time_ns.get(category, 0) + n
        if sid is not None:
            st = q.stage_time_ns.setdefault(sid, {})
            st[category] = st.get(category, 0) + n


def count_move(boundary: str, nbytes: int) -> None:
    """Bytes that crossed `boundary` without a host-side duplication
    (bytes_moved only) — e.g. the native-ABI result payload."""
    count_copy(boundary, 0, moved=nbytes)


def copy_totals() -> Tuple[Dict[str, int], Dict[str, int]]:
    """(bytes_copied, bytes_moved) per boundary, process lifetime."""
    with _lock:
        return dict(_copied), dict(_moved)


# -- zero-copy event accounting ----------------------------------------------

# event counters for the zero-copy data plane: how often the cheap path
# actually ran (byte volumes live in _copied/_moved under "shuffle")
ZEROCOPY_KEYS = ("shuffle_mmap_hits", "shuffle_mmap_fallbacks",
                 "dict_cols_encoded")
_zerocopy: Dict[str, int] = {k: 0 for k in ZEROCOPY_KEYS}
# executor-side ship watermark (drain ships disjoint deltas, like
# drain_remote_deltas does for the per-query accumulators)
_zerocopy_shipped: Dict[str, int] = {k: 0 for k in ZEROCOPY_KEYS}


def count_zerocopy(key: str, n: int = 1) -> None:
    """Count one zero-copy data-plane event: a same-host mmap shuffle
    fetch served without streaming ("shuffle_mmap_hits"), a mmap attempt
    that fell back to the socket ("shuffle_mmap_fallbacks"), or a string
    column shipped dictionary-encoded ("dict_cols_encoded"). Call sites
    gate on conf.monitor_enabled; self-gated too for safety."""
    if not conf.monitor_enabled:
        return
    with _lock:
        _zerocopy[key] = _zerocopy.get(key, 0) + int(n)


def zerocopy_stats() -> Dict[str, int]:
    """Process-lifetime zero-copy event counters."""
    with _lock:
        return {k: _zerocopy.get(k, 0) for k in ZEROCOPY_KEYS}


def drain_zerocopy() -> Dict[str, int]:
    """Executor-side: zero-copy counter deltas since the last drain
    (empty when nothing new) — shipped in telemetry frames next to the
    per-query deltas and folded in driver-side by merge_zerocopy."""
    out: Dict[str, int] = {}
    with _lock:
        for k in ZEROCOPY_KEYS:
            d = _zerocopy.get(k, 0) - _zerocopy_shipped.get(k, 0)
            if d:
                out[k] = d
                _zerocopy_shipped[k] = _zerocopy.get(k, 0)
    return out


def merge_zerocopy(deltas: Dict[str, int]) -> None:
    """Driver-side ingest of executor zero-copy deltas."""
    if not deltas or not conf.monitor_enabled:
        return
    with _lock:
        for k, n in deltas.items():
            _zerocopy[k] = _zerocopy.get(k, 0) + int(n)


def leaks_total() -> int:
    with _lock:
        return _leaks_total


def reset() -> None:
    """Clear counters + per-query state (test/bench isolation)."""
    global _active_qid, _leaks_total
    with _lock:
        for b in list(_copied):
            _copied[b] = 0
        for b in list(_moved):
            _moved[b] = 0
        for k in list(_zerocopy):
            _zerocopy[k] = 0
        for k in list(_zerocopy_shipped):
            _zerocopy_shipped[k] = 0
        _queries.clear()
        _active_qid = None
        _leaks_total = 0
        _endpoint_requests.clear()


# -- per-query lifecycle -----------------------------------------------------


def begin_query(qid: str, manager=None) -> None:
    """Register `qid` as the active query (attribution fallback), reset
    the manager's peak-usage watermark, and snapshot the process
    counters the roll-up reports as deltas. Lazily starts the metrics
    endpoint + sampler when conf.metrics_port is set."""
    global _active_qid
    if conf.metrics_port:
        ensure_started()
    if conf.profile_enabled:
        from blaze_tpu.runtime import profiler

        profiler.ensure_started()
    if not conf.monitor_enabled:
        return
    acct = _QueryAcct(qid)
    if manager is not None:
        manager.reset_peak()
        acct.spilled0 = manager.spilled_bytes
        acct.spill_count0 = manager.spill_count
    acct.compile0 = _compile_snapshot()
    with _lock:
        _queries[qid] = acct
        _active_qid = qid


def ensure_query(qid: str) -> None:
    """Executor-side registration: create the per-query accumulator for
    a driver-issued qid WITHOUT making it the active query or touching
    the manager. Worker processes never call begin_query (the driver
    owns the query lifecycle); they still need an accumulator so
    count_copy/count_time attribute pooled work, which then drains into
    telemetry ships (drain_remote_deltas) instead of a local
    query_end."""
    if not conf.monitor_enabled or not qid:
        return
    with _lock:
        if qid not in _queries:
            _queries[qid] = _QueryAcct(qid)


def drain_remote_deltas() -> Dict[str, Dict[str, Any]]:
    """Pop-and-return every query accumulator's counters as a JSON-safe
    delta doc {qid: {copied, moved, time_ns, stage_copied, stage_moved,
    stage_time_ns}} — the executor-side half of counter federation. The
    accumulators stay registered (a task may still be appending); only
    the counts move, so repeated drains ship disjoint deltas."""
    out: Dict[str, Dict[str, Any]] = {}
    with _lock:
        for qid, q in _queries.items():
            d: Dict[str, Any] = {}
            for field in ("copied", "moved", "time_ns",
                          "stage_copied", "stage_moved", "stage_time_ns"):
                vals = getattr(q, field)
                if vals:
                    d[field] = vals
                    setattr(q, field, {})
            if d:
                out[qid] = d
    return out


def _stage_key(k: Any) -> Any:
    """Stage ids are ints driver-side but stringify over the JSON wire;
    convert back so remote deltas merge into the same buckets."""
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def merge_remote(deltas: Dict[str, Dict[str, Any]]) -> None:
    """Driver-side ingest of executor counter deltas (telemetry frames
    and sidecar recovery): fold into the process-lifetime totals AND the
    per-query accumulators, so query_end roll-ups, stage span attrs,
    /metrics and the perf-baseline gate see pooled work identically to
    in-process work. Deltas for a query already rolled up (late/
    recovered ship after query_end) still land in the process totals."""
    if not deltas or not conf.monitor_enabled:
        return
    with _lock:
        for qid, d in deltas.items():
            copied = d.get("copied") or {}
            moved = d.get("moved") or {}
            for b, n in copied.items():
                _copied[b] = _copied.get(b, 0) + int(n)
            for b, n in moved.items():
                _moved[b] = _moved.get(b, 0) + int(n)
            q = _queries.get(qid)
            if q is None:
                continue
            for b, n in copied.items():
                q.copied[b] = q.copied.get(b, 0) + int(n)
            for b, n in moved.items():
                q.moved[b] = q.moved.get(b, 0) + int(n)
            for cat, n in (d.get("time_ns") or {}).items():
                q.time_ns[cat] = q.time_ns.get(cat, 0) + int(n)
            for sk, n in (d.get("stage_copied") or {}).items():
                k = _stage_key(sk)
                q.stage_copied[k] = q.stage_copied.get(k, 0) + int(n)
            for sk, n in (d.get("stage_moved") or {}).items():
                k = _stage_key(sk)
                q.stage_moved[k] = q.stage_moved.get(k, 0) + int(n)
            for sk, cats in (d.get("stage_time_ns") or {}).items():
                st = q.stage_time_ns.setdefault(_stage_key(sk), {})
                for cat, n in cats.items():
                    st[cat] = st.get(cat, 0) + int(n)


def query_end(qid: str, manager=None) -> Dict[str, int]:
    """Pop `qid`'s accumulator; returns the flat-int roll-up merged into
    run_info (flat ints flow into the ledger's "counters" untouched)."""
    global _active_qid
    with _lock:
        acct = _queries.pop(qid, None)
        if _active_qid == qid:
            _active_qid = None
    if acct is None:
        return {}
    roll: Dict[str, int] = {}
    copied_total = moved_total = 0
    for b in BOUNDARIES:
        c = acct.copied.get(b, 0)
        m = acct.moved.get(b, 0)
        roll[f"bytes_copied_{b}"] = c
        roll[f"bytes_moved_{b}"] = m
        copied_total += c
        moved_total += m
    roll["bytes_copied_total"] = copied_total
    roll["bytes_moved_total"] = moved_total
    # zero-copy event deltas over the query's lifetime (process-global
    # counters diffed against the begin_query watermark: concurrent
    # queries share the plane, so treat these as attribution, not an
    # exact ledger — the doctor's serde_bound evidence reads them)
    with _lock:
        zc_now = {k: _zerocopy.get(k, 0) for k in ZEROCOPY_KEYS}
    for k in ZEROCOPY_KEYS:
        roll[k] = max(zc_now.get(k, 0) - acct.zc0.get(k, 0), 0)
    if manager is not None:
        roll["peak_mem_bytes"] = max(manager.observe_peak(),
                                     manager.peak_used)
        roll["spill_bytes"] = manager.spilled_bytes - acct.spilled0
        roll["spill_count"] = manager.spill_count - acct.spill_count0
    comp = _compile_snapshot()
    roll["compile_ms"] = round(
        (comp.get("compile_ns", 0)
         - acct.compile0.get("compile_ns", 0)) / 1e6)
    for k in ("cache_hits", "cache_misses", "compile_count"):
        roll[f"compile_{k}"] = comp.get(k, 0) - acct.compile0.get(k, 0)
    # boundary-time roll-up (count_time): one <category>_ms counter per
    # observed category — the doctor's critical-path inputs
    for cat, ns in acct.time_ns.items():
        roll[f"{cat}_ms"] = round(ns / 1e6, 3)
    return roll


def stage_span_attrs(qid: str, stage_id) -> Dict[str, Any]:
    """{moved_bytes, copied_bytes} plus any per-stage boundary-time
    `<category>_ms` accumulated for one stage so far — the local runner
    stamps them onto the stage span before it closes (explain_analyze
    and the ledger render them per stage). {} when unattributed."""
    with _lock:
        q = _queries.get(qid)
        if q is None:
            return {}
        m = q.stage_moved.get(stage_id, 0)
        c = q.stage_copied.get(stage_id, 0)
        times = dict(q.stage_time_ns.get(stage_id, ()))
    out: Dict[str, Any] = {}
    if m or c:
        out = {"moved_bytes": m, "copied_bytes": c}
    for cat in sorted(times):
        out[f"{cat}_ms"] = round(times[cat] / 1e6, 3)
    return out


def finish_query(qid: str, run_info: Dict[str, Any], manager=None) -> None:
    """Query-end hook: merge the roll-up into run_info and run the
    always-on leak check (independent of conf.monitor_enabled): live
    pipeline streams, pipeline reservations, or nonzero MemManager
    consumers at query end are a `resource_leak` trace event and a
    run-ledger counter — the chaos-soak checks, promoted to every
    query."""
    global _leaks_total
    if conf.monitor_enabled:
        run_info.update(query_end(qid, manager))
    leaks: List[str] = []
    live = run_info.get("pipeline_live_streams", 0)
    if live:
        leaks.append(f"pipeline_live_streams={live}")
    if manager is not None:
        if manager.pipeline_reserved:
            leaks.append(
                f"pipeline_reserved={manager.pipeline_reserved}")
        held = [(c.name, c.mem_used())
                for c in manager._consumers_snapshot() if c.mem_used() > 0]
        if held:
            leaks.append("consumers=" + ",".join(
                f"{name}:{used}" for name, used in held))
    run_info["resource_leaks"] = len(leaks)
    if leaks:
        with _lock:
            _leaks_total += len(leaks)
        trace.event("resource_leak", query_id=qid, leaks="; ".join(leaks))


def _compile_snapshot() -> Dict[str, int]:
    from blaze_tpu.runtime import compile_service

    return compile_service.TELEMETRY.snapshot()


def running_queries() -> List[Dict[str, Any]]:
    """Live queries (id, seconds running, bytes so far) for blaze_top."""
    now = time.time()
    with _lock:
        return [{"query_id": q.qid,
                 "seconds": round(now - q.t0, 1),
                 "bytes_copied": sum(q.copied.values()),
                 "bytes_moved": sum(q.moved.values())}
                for q in _queries.values()]


def query_t0(qid: str) -> Optional[float]:
    """Wall-clock start of a STILL-REGISTERED query (None after
    query_end pops it) — the flight recorder's ring-slice window start,
    read before the roll-up."""
    with _lock:
        q = _queries.get(qid)
        return q.t0 if q is not None else None


def query_time_breakdown(qid: str) -> Dict[str, float]:
    """Live boundary-time accounting for one running query: wall ms per
    critical-path category accumulated SO FAR (the doctor's term inputs,
    readable mid-query) — {} when unregistered or monitor disabled."""
    with _lock:
        q = _queries.get(qid)
        if q is None:
            return {}
        return {cat: round(ns / 1e6, 3)
                for cat, ns in sorted(q.time_ns.items())}


# -- background sampler ------------------------------------------------------


class ResourceMonitor:
    """Background sampler recording engine gauges into a bounded
    time-series ring (deque maxlen: oldest samples drop first). Explicit
    start()/stop(); sample_now() is callable without the thread (tests,
    blaze_top --once)."""

    def __init__(self, capacity: Optional[int] = None,
                 sample_ms: Optional[int] = None, manager=None) -> None:
        self._cap = int(capacity or conf.monitor_ring_samples)
        self._sample_ms = sample_ms
        self._manager = manager
        self._ring: deque = deque(maxlen=max(self._cap, 1))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_now(self) -> Dict[str, Any]:
        from blaze_tpu.runtime import faults, memory, pipeline, supervisor

        mgr = self._manager or memory.get_manager()
        used = mgr.observe_peak()
        depths = pipeline.queue_depths()
        comp = _compile_snapshot()
        copied, moved = copy_totals()
        s = {
            "ts": time.time(),
            "mem_used": used,
            "mem_total": mgr.total,
            "mem_peak": mgr.peak_used,
            "pipeline_reserved": mgr.pipeline_reserved,
            "spill_pages": mgr.spill_pages_pending(),
            "host_spill_bytes": mgr.host_spill_bytes,
            "spilled_bytes": mgr.spilled_bytes,
            "pipeline_live_streams": pipeline.live_streams(),
            "pipeline_queue_depth": sum(depths),
            "pipeline_queue_streams": len(depths),
            "supervisor_active_tasks": supervisor.active_tasks(),
            "io_pool_width": max(1, int(conf.io_threads)),
            "task_pool_width": max(1, int(conf.max_concurrent_tasks)),
            "queries_running": len(running_queries()),
            "bytes_copied": sum(copied.values()),
            "bytes_moved": sum(moved.values()),
            "compile_cache_hits": comp.get("cache_hits", 0),
            "compile_cache_misses": comp.get("cache_misses", 0),
            "compile_ms": round(comp.get("compile_ns", 0) / 1e6),
            "breaker_trips": faults.TELEMETRY.snapshot().get(
                "breaker.trips", 0),
        }
        from blaze_tpu.runtime import service

        st = service.stats()
        s["admission_queue_depth"] = st["queue_depth"]
        s["admission_parked"] = st["parked"]
        s["admission_rejected"] = st["rejected"]
        from blaze_tpu.runtime import executor_pool

        ps = executor_pool.pool_stats()
        if ps is not None:
            s["executors_live"] = ps["live"]
            s["executor_capacity"] = ps["capacity"]
            s["executor_deaths"] = ps["deaths_total"]
            s["executor_restarts"] = ps["restarts_total"]
        self._ring.append(s)
        return s

    def ring(self) -> List[Dict[str, Any]]:
        return list(self._ring)

    def ring_since(self, since_ts: Optional[float] = None
                   ) -> List[Dict[str, Any]]:
        """Samples with ts >= since_ts (whole ring when None) — the
        "gauges over the query's lifetime" slice dossiers embed."""
        ring = list(self._ring)
        if since_ts is None:
            return ring
        return [s for s in ring if s.get("ts", 0) >= since_ts]

    def start(self) -> "ResourceMonitor":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="blz-monitor", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_now()
            except Exception:  # noqa: BLE001 — the sampler must never die
                pass
            ms = self._sample_ms
            if ms is None:
                ms = conf.monitor_sample_ms
            self._stop.wait(max(int(ms), 1) / 1000.0)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None


# -- Prometheus exporter -----------------------------------------------------

# The scrape contract: every fixed sample family prometheus_text() emits,
# declared up front. Dashboards/alerts key on these names — renaming one is
# a breaking change, so tools/blazelint's registry-sync checker verifies
# each emit() literal appears here AND that each entry is still emitted
# (a stale registry row means a dashboard series silently went dark).
# Dynamic telemetry families (per-counter gauges minted from MetricsSet
# keys, histogram summaries) are constrained to GAUGE_PREFIXES instead.
GAUGE_NAMES = (
    "blaze_bytes_copied_total",
    "blaze_bytes_moved_total",
    "blaze_resource_leaks_total",
    "blaze_mem_used_bytes",
    "blaze_mem_budget_bytes",
    "blaze_mem_peak_bytes",
    "blaze_mem_pipeline_reserved_bytes",
    "blaze_spill_pages_bytes",
    "blaze_spilled_bytes_total",
    "blaze_spill_count_total",
    "blaze_trace_dropped_events_total",
    "blaze_trace_buffer_events",
    "blaze_trace_buffer_capacity",
    "blaze_monitor_ring_samples",
    "blaze_monitor_ring_capacity",
    "blaze_pipeline_live_streams",
    "blaze_pipeline_queue_depth",
    "blaze_supervisor_active_tasks",
    "blaze_queries_running",
    "blaze_admission_queue_depth",
    "blaze_admission_admitted_total",
    "blaze_admission_parked_total",
    "blaze_admission_rejected_total",
    "blaze_tenant_mem_used_bytes",
    "blaze_slo_objective_ms",
    "blaze_slo_attainment",
    "blaze_slo_burn_rate",
    "blaze_slo_breaches_total",
    "blaze_flight_dossiers_total",
    "blaze_query_progress_ratio",
    "blaze_endpoint_requests_total",
    "blaze_executor_up",
    "blaze_executor_live",
    "blaze_executor_restarts_total",
    "blaze_executor_deaths_total",
    "blaze_executor_heartbeat_age_ms",
    "blaze_executor_busy_slots",
    "blaze_executor_tasks_done_total",
    "blaze_executor_telemetry_bytes_total",
    "blaze_executor_draining",
    "blaze_executor_reconnects_total",
    "blaze_executor_drains_total",
    "blaze_shuffle_conn_dropped_total",
    "blaze_shuffle_mmap_hits_total",
    "blaze_shuffle_mmap_fallbacks_total",
    "blaze_dict_cols_encoded_total",
    "blaze_service_capacity",
    "blaze_artifact_corruptions_total",
    "blaze_recovered_queries_total",
    "blaze_autoscale_target_seats",
    "blaze_autoscale_decisions_total",
    "blaze_autopilot_overlays_active",
    "blaze_autopilot_promotions_total",
    "blaze_autopilot_rollbacks_total",
    "blaze_driver_role",
    "blaze_stream_lag_ms",
    "blaze_stream_batches_total",
    "blaze_stream_checkpoint_bytes",
    "blaze_profile_samples_total",
    "blaze_profile_remote_samples_total",
    "blaze_profile_recovered_samples_total",
    "blaze_profile_stacks",
    "blaze_profile_dropped_total",
    "blaze_profile_duty_pct",
    "blaze_profile_fleet_duty_pct",
)
GAUGE_PREFIXES = (
    "blaze_pipeline_",  # pipeline.TELEMETRY counters
    "blaze_faults_",    # faults.TELEMETRY counters
    "blaze_compile_",   # compile_service.TELEMETRY counters
    "blaze_hist_",      # trace histogram summaries
)


def _prom_name(raw: str) -> str:
    """Sanitize to the metric-name grammar [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = [ch if (ch.isalnum() and ch.isascii()) or ch in "_:" else "_"
           for ch in raw]
    name = "".join(out) or "_"
    if name[0].isdigit():
        name = "_" + name
    return name


def _prom_escape(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def prometheus_text() -> str:
    """The whole registry in Prometheus text exposition format
    (# HELP/# TYPE headers, one sample per line, trailing newline)."""
    from blaze_tpu.runtime import compile_service, faults, memory, pipeline
    from blaze_tpu.runtime import supervisor

    lines: List[str] = []

    def emit(name, mtype, help_text, samples):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            lab = ""
            if labels:
                lab = "{" + ",".join(
                    f'{k}="{_prom_escape(v)}"'
                    for k, v in sorted(labels.items())) + "}"
            lines.append(f"{name}{lab} {value}")

    copied, moved = copy_totals()
    emit("blaze_bytes_copied_total", "counter",
         "Bytes duplicated at each copy boundary",
         [({"boundary": b}, copied.get(b, 0)) for b in BOUNDARIES])
    emit("blaze_bytes_moved_total", "counter",
         "Bytes crossing each copy boundary",
         [({"boundary": b}, moved.get(b, 0)) for b in BOUNDARIES])
    emit("blaze_resource_leaks_total", "counter",
         "Queries that ended with leaked streams/reservations/consumers",
         [({}, leaks_total())])

    zc = zerocopy_stats()
    emit("blaze_shuffle_mmap_hits_total", "counter",
         "Same-host shuffle fetches served as zero-copy mmap views",
         [({}, zc.get("shuffle_mmap_hits", 0))])
    emit("blaze_shuffle_mmap_fallbacks_total", "counter",
         "mmap shuffle fetch attempts that fell back to the socket path",
         [({}, zc.get("shuffle_mmap_fallbacks", 0))])
    emit("blaze_dict_cols_encoded_total", "counter",
         "String columns shipped dictionary-encoded in serde frames",
         [({}, zc.get("dict_cols_encoded", 0))])

    mgr = memory.get_manager()
    emit("blaze_mem_used_bytes", "gauge",
         "MemManager usage (consumers + spill pages + pipeline_reserved)",
         [({}, mgr.mem_used())])
    emit("blaze_mem_budget_bytes", "gauge", "MemManager budget",
         [({}, mgr.total)])
    emit("blaze_mem_peak_bytes", "gauge",
         "Peak MemManager usage since the last query began",
         [({}, mgr.peak_used)])
    emit("blaze_mem_pipeline_reserved_bytes", "gauge",
         "Bytes held by in-flight pipelined batches",
         [({}, mgr.pipeline_reserved)])
    emit("blaze_spill_pages_bytes", "gauge",
         "Spill-file pages buffered but not yet synced",
         [({}, mgr.spill_pages_pending())])
    emit("blaze_spilled_bytes_total", "counter",
         "Bytes freed by consumer spills", [({}, mgr.spilled_bytes)])
    emit("blaze_spill_count_total", "counter", "Consumer spill operations",
         [({}, mgr.spill_count)])

    # trace-ring health: a nonzero dropped counter means the bounded
    # ring overflowed and the exported traces are truncated — previously
    # visible only in the ledger, now scrapeable
    emit("blaze_trace_dropped_events_total", "counter",
         "Trace records dropped by the bounded ring (oldest-first)",
         [({}, trace.TRACE.dropped)])
    emit("blaze_trace_buffer_events", "gauge",
         "Records currently held in the trace ring",
         [({}, len(trace.TRACE))])
    emit("blaze_trace_buffer_capacity", "gauge",
         "Trace ring capacity (conf.trace_buffer_events)",
         [({}, int(conf.trace_buffer_events))])
    s = sampler()
    ring = s.ring() if s is not None else []
    emit("blaze_monitor_ring_samples", "gauge",
         "Samples held in the resource-monitor ring",
         [({}, len(ring))])
    emit("blaze_monitor_ring_capacity", "gauge",
         "Resource-monitor ring capacity (conf.monitor_ring_samples)",
         [({}, int(conf.monitor_ring_samples))])

    depths = pipeline.queue_depths()
    emit("blaze_pipeline_live_streams", "gauge",
         "Prefetch streams/sinks created but not yet finalized",
         [({}, pipeline.live_streams())])
    emit("blaze_pipeline_queue_depth", "gauge",
         "Items queued across live prefetch streams", [({}, sum(depths))])
    emit("blaze_supervisor_active_tasks", "gauge",
         "Task attempts currently executing", [({}, supervisor.active_tasks())])
    emit("blaze_queries_running", "gauge", "Queries currently executing",
         [({}, len(running_queries()))])

    # multi-tenant service (runtime/service.py): admission control +
    # per-tenant memory attribution. All-zero with no service running.
    from blaze_tpu.runtime import service

    st = service.stats()
    emit("blaze_admission_queue_depth", "gauge",
         "Queries parked in the service admission queue",
         [({}, st["queue_depth"])])
    emit("blaze_admission_admitted_total", "counter",
         "Queries granted a run slot by admission control",
         [({}, st["admitted"])])
    emit("blaze_admission_parked_total", "counter",
         "Queries that waited in the admission queue before running",
         [({}, st["parked"])])
    emit("blaze_admission_rejected_total", "counter",
         "Queries load-shed at admission (queue full or deadline)",
         [({}, st["rejected"])])
    # finished tenants (zero bytes held) drop out of the exposition —
    # the {tenant=} cardinality tracks tenants with live usage, not
    # every tenant the process ever served
    emit("blaze_tenant_mem_used_bytes", "gauge",
         "MemManager bytes in use per tenant (consumers + pipeline; "
         "zero-usage tenants are pruned from the exposition)",
         [({"tenant": t}, v)
          for t, v in sorted(mgr.tenant_usage().items()) if v])

    # per-tenant SLO tracking (runtime/service.SloTracker over
    # conf.tenant_slo_spec): objective, rolling attainment, burn rate.
    # Present whenever a spec is configured — including mid-query.
    slo = service.slo_stats()
    emit("blaze_slo_objective_ms", "gauge",
         "Configured per-tenant latency objective (tenant_slo_spec)",
         [({"tenant": t}, s["latency_ms"])
          for t, s in sorted(slo.items())])
    emit("blaze_slo_attainment", "gauge",
         "Rolling share of arrivals meeting the tenant's objective",
         [({"tenant": t}, s["attainment"])
          for t, s in sorted(slo.items())])
    emit("blaze_slo_burn_rate", "gauge",
         "Error-budget burn rate (miss rate / allowed miss rate; "
         ">1 = budget burning hot)",
         [({"tenant": t}, s["burn_rate"])
          for t, s in sorted(slo.items())])
    emit("blaze_slo_breaches_total", "counter",
         "Arrivals that missed the tenant's latency objective",
         [({"tenant": t}, s["breaches"])
          for t, s in sorted(slo.items())])

    # process-isolated executor pool (runtime/executor_pool.py): per-seat
    # liveness, restart/death counters, and the degraded admission
    # capacity. Families stay present (empty) with no pool attached so
    # dashboards see a series disappear per-executor, never per-family.
    from blaze_tpu.runtime import executor_pool

    ps = executor_pool.pool_stats()
    execs = (ps or {}).get("executors", ())
    emit("blaze_executor_up", "gauge",
         "Executor process liveness (1 = heartbeating, 0 = declared dead)",
         [({"exec_id": e["exec_id"]}, 1 if e["up"] else 0) for e in execs])
    # telemetry-federation pane (blaze_top's executor rows): heartbeat
    # freshness, occupancy, lifetime work and shipped-telemetry volume
    emit("blaze_executor_heartbeat_age_ms", "gauge",
         "Milliseconds since the executor's last control-socket frame",
         [({"exec_id": e["exec_id"]}, e.get("heartbeat_age_ms", 0))
          for e in execs])
    emit("blaze_executor_busy_slots", "gauge",
         "Tasks currently in flight on the executor",
         [({"exec_id": e["exec_id"]}, e.get("inflight", 0))
          for e in execs])
    emit("blaze_executor_tasks_done_total", "counter",
         "Tasks the executor completed successfully",
         [({"exec_id": e["exec_id"]}, e.get("tasks_done", 0))
          for e in execs])
    emit("blaze_executor_telemetry_bytes_total", "counter",
         "Telemetry payload bytes shipped by the executor (incl. "
         "sidecar-recovered)",
         [({"exec_id": e["exec_id"]}, e.get("telemetry_bytes", 0))
          for e in execs])
    # partition-tolerant control plane: draining seats (excluded from
    # capacity without a death) and per-seat control-session resumes
    emit("blaze_executor_draining", "gauge",
         "Executor is gracefully decommissioning (1 = drain mode)",
         [({"exec_id": e["exec_id"]}, 1 if e.get("draining") else 0)
          for e in execs])
    emit("blaze_executor_reconnects_total", "counter",
         "Control-session resumes after a transport blip, per seat",
         [({"exec_id": e["exec_id"]}, e.get("reconnects", 0))
          for e in execs])
    emit("blaze_executor_drains_total", "counter",
         "Executors gracefully decommissioned (drain completed)",
         [({}, ps.get("drains_total", 0))] if ps else [])
    emit("blaze_shuffle_conn_dropped_total", "counter",
         "Shuffle-server client connections dropped mid-request",
         [({}, ps.get("shuffle_conns_dropped", 0))] if ps else [])
    emit("blaze_executor_live", "gauge",
         "Live executor processes in the pool",
         [({}, ps["live"])] if ps else [])
    emit("blaze_executor_restarts_total", "counter",
         "Executor processes respawned after a death",
         [({}, ps["restarts_total"])] if ps else [])
    emit("blaze_executor_deaths_total", "counter",
         "Executor deaths declared (exit, heartbeat, send error)",
         [({}, ps["deaths_total"])] if ps else [])
    emit("blaze_service_capacity", "gauge",
         "Admission capacity (live_executors x slots when a pool is "
         "attached, else max_concurrent_queries)",
         [({}, service.capacity())])

    # incident capture + live introspection (flight_recorder/progress):
    # lazy imports — both modules import monitor at module level
    from blaze_tpu.runtime import flight_recorder, progress

    emit("blaze_flight_dossiers_total", "counter",
         "Incident dossiers written by the flight recorder, by trigger",
         [({"trigger": t}, n)
          for t, n in sorted(flight_recorder.counts().items())])
    from blaze_tpu.runtime import artifacts, journal

    emit("blaze_artifact_corruptions_total", "counter",
         "Corrupt artifacts detected on read paths (checksum mismatch)",
         [({}, artifacts.corruption_stats()["corruptions"])])
    emit("blaze_recovered_queries_total", "counter",
         "Queries that reused journaled stage commits after a driver "
         "restart",
         [({}, journal.recovered_queries_total())])

    # elastic fleet & driver HA (runtime/autoscaler.py, standby.py):
    # the policy's seat target + decision counters, and which role this
    # process holds — a standby scrapes role=standby until takeover
    from blaze_tpu.runtime import autoscaler, standby

    asc = autoscaler.state()
    emit("blaze_autoscale_target_seats", "gauge",
         "Autoscaler's desired serving seat count (absent with the "
         "policy loop off)",
         [({}, asc["target_seats"])] if asc else [])
    emit("blaze_autoscale_decisions_total", "counter",
         "Autoscaler actuations, by direction",
         [({"direction": d}, n)
          for d, n in sorted((asc or {}).get("decisions", {}).items())])
    emit("blaze_driver_role", "gauge",
         "Driver role of this process (1 for the held role)",
         [({"role": standby.role()}, 1)])

    # self-tuning autopilot (runtime/autopilot.py): the folded
    # OverlayStore posture — fingerprints with a live overlay, lifetime
    # promotions, and rollbacks by knob (restart-persistent: the fold is
    # what a restarted driver resumes from, so the counters are too)
    from blaze_tpu.runtime import autopilot

    apm = autopilot.metrics()
    emit("blaze_autopilot_overlays_active", "gauge",
         "Plan fingerprints with a settled or canary overlay (absent "
         "with the autopilot off)",
         [({}, apm["overlays_active"])] if apm else [])
    emit("blaze_autopilot_promotions_total", "counter",
         "Canary overlays promoted to settled",
         [({}, apm["promotions_total"])] if apm else [])
    emit("blaze_autopilot_rollbacks_total", "counter",
         "Canary overlays rolled back + quarantined, by knob",
         [({"knob": k}, n) for k, n in
          sorted((apm or {}).get("rollbacks_total", {}).items())])

    # durable streaming (runtime/streaming.py): one series per LIVE
    # stream — a stopped stream's series disappears from the exposition
    # (same bounded-cardinality posture as the progress ring)
    from blaze_tpu.runtime import streaming

    ss = streaming.stream_stats()
    emit("blaze_stream_lag_ms", "gauge",
         "Per-stream end-to-end lag (age of the oldest unconsumed "
         "source file; 0 when caught up)",
         [({"qid": sid}, s["lag_ms"]) for sid, s in sorted(ss.items())])
    emit("blaze_stream_batches_total", "counter",
         "Micro-batches committed per stream (resumed batches included)",
         [({"qid": sid}, s["batches_total"])
          for sid, s in sorted(ss.items())])
    emit("blaze_stream_checkpoint_bytes", "gauge",
         "Serialized size of each stream's last durable checkpoint",
         [({"qid": sid}, s["checkpoint_bytes"])
          for sid, s in sorted(ss.items())])
    # bounded label cardinality: live queries plus the last-N finished
    # ring (progress.finished_queries) — older finished series age out of
    # the exposition instead of accumulating one {qid=} series per query
    # for the life of the endpoint
    emit("blaze_query_progress_ratio", "gauge",
         "Per-query progress ratio (0-1, monotone; finished queries "
         "linger in a bounded last-N ring, then their series is pruned)",
         [({"qid": s["query_id"]}, s["progress_ratio"])
          for s in progress.snapshot_queries()
          if s.get("progress_ratio") is not None]
         + [({"qid": s["query_id"]}, s["progress_ratio"])
            for s in progress.finished_queries()
            if s.get("progress_ratio") is not None])
    with _lock:
        reqs = dict(_endpoint_requests)
    emit("blaze_endpoint_requests_total", "counter",
         "Debug-endpoint requests served, by route",
         [({"route": r}, n) for r, n in sorted(reqs.items())])

    # continuous sampling profiler (runtime/profiler.py): fleet-merged
    # folded-stack table posture — local + federated executor samples
    from blaze_tpu.runtime import profiler

    ps = profiler.stats()
    emit("blaze_profile_samples_total", "counter",
         "Thread-samples folded locally by this process's sampler",
         [({}, ps["samples"])])
    emit("blaze_profile_remote_samples_total", "counter",
         "Executor samples federated driver-ward on telemetry frames",
         [({}, ps["remote_samples"])])
    emit("blaze_profile_recovered_samples_total", "counter",
         "Remote samples replayed from a dead worker's sidecar spill",
         [({}, ps["recovered_samples"])])
    emit("blaze_profile_stacks", "gauge",
         "Distinct (attribution, folded-stack) entries in the bounded "
         "aggregate table",
         [({}, ps["stacks"])])
    emit("blaze_profile_dropped_total", "counter",
         "Samples dropped with the table at capacity",
         [({}, ps["dropped"])])
    emit("blaze_profile_duty_pct", "gauge",
         "Sampler overhead: cpu seconds inside sampling passes per "
         "wall second alive, this process",
         [({}, ps["duty_pct"])])
    emit("blaze_profile_fleet_duty_pct", "gauge",
         "Sampler overhead summed across this driver and every "
         "executor's shipped duty ledger",
         [({}, ps["fleet_duty_pct"])])

    for prefix, help_text, ms in (
            ("blaze_pipeline", "pipeline telemetry", pipeline.TELEMETRY),
            ("blaze_faults", "resilience telemetry", faults.TELEMETRY),
            ("blaze_compile", "compile-service telemetry",
             compile_service.TELEMETRY)):
        for k, v in sorted(ms.snapshot().items()):
            if not isinstance(v, (int, float)):
                continue
            emit(_prom_name(f"{prefix}_{k}"), "gauge",
                 f"{help_text}: {k}", [({}, v)])

    # engine histograms (task_latency_us, pipeline_*, shuffle_write_
    # bytes, ...): proper Prometheus histogram exposition — cumulative
    # _bucket{le=...} series straight from the log2 bucket counts
    # (metrics.Histogram.bucket_upper_bound), plus _sum/_count. Replaces
    # the earlier quantile-summary rendering: quantiles cannot be
    # aggregated across processes, buckets can.
    from blaze_tpu.runtime.metrics import Histogram

    for name, snap in sorted(trace.histograms_snapshot().items()):
        base = _prom_name(f"blaze_hist_{name}")
        counts = snap.get("counts") or []
        last = max((i for i, c in enumerate(counts) if c), default=-1)
        lines.append(f"# HELP {base} engine histogram {name}")
        lines.append(f"# TYPE {base} histogram")
        cum = 0
        for i in range(last + 1):
            cum += counts[i]
            le = Histogram.bucket_upper_bound(i)
            lines.append(f'{base}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{base}_bucket{{le="+Inf"}} {snap["count"]}')
        lines.append(f"{base}_sum {snap['total']}")
        lines.append(f"{base}_count {snap['count']}")

    return "\n".join(lines) + "\n"


# per-route request counters for the debug endpoints (exported as
# blaze_endpoint_requests_total{route=})
_endpoint_requests: Dict[str, int] = {}


def _note_request(route: str) -> None:
    with _lock:
        _endpoint_requests[route] = _endpoint_requests.get(route, 0) + 1


def health_snapshot() -> Dict[str, Any]:
    """Cheap liveness payload (GET /healthz): ring occupancy + sampler
    staleness for container probes, without the full exposition. With an
    executor pool attached, ok flips False ONLY at zero live executors
    (degraded-but-serving capacity is healthy — the probe must not
    restart a pod that is recovering one seat). Reports this process's
    driver `role` and the autoscaler's policy state: a warm standby has
    no pool attached, so it serves 200 with role=standby — load
    balancers probe both drivers with the same check."""
    from blaze_tpu.runtime import autoscaler, executor_pool, standby

    s = sampler()
    ring = s.ring() if s is not None else []
    last_ts = ring[-1].get("ts") if ring else None
    ps = executor_pool.pool_stats()
    ok = True
    if ps is not None:
        ok = ps["live"] > 0
    asc = autoscaler.state()
    return {
        "ok": ok,
        "role": standby.role(),
        "standby_enabled": bool(conf.standby_enabled),
        "autoscaler": (None if asc is None else {
            "target_seats": asc["target_seats"],
            "last_decision": asc["last_decision"],
            "cooldown_remaining_ms": asc["cooldown_remaining_ms"],
        }),
        "executors_live": ps["live"] if ps else None,
        "executors_draining": ps.get("draining") if ps else None,
        "capacity": ps["capacity"] if ps else None,
        "ring_samples": len(ring),
        "ring_capacity": int(conf.monitor_ring_samples),
        "sampler_alive": bool(s is not None and s._thread is not None
                              and s._thread.is_alive()),
        "sampler_staleness_s": (round(time.time() - last_ts, 3)
                                if last_ts is not None else None),
        "trace_events": len(trace.TRACE),
        "trace_dropped": trace.TRACE.dropped,
        "queries_running": len(running_queries()),
    }


def serve_path(path: str) -> Tuple[int, str, bytes]:
    """Route one debug-endpoint GET -> (status, content-type, body).
    Factored out of the socket handler so tests and blaze_inspect can
    hit the routes without a live server."""
    if path in ("/metrics", "/"):
        _note_request("metrics")
        return (200, "text/plain; version=0.0.4",
                prometheus_text().encode())
    if path == "/healthz":
        _note_request("healthz")
        snap = health_snapshot()
        # 503 only at zero live executors: a load balancer must keep
        # routing to a DEGRADED pool (it still serves, at reduced
        # capacity) and only eject a truly dead one
        return (200 if snap["ok"] else 503, "application/json",
                json.dumps(snap).encode())
    # live introspection (runtime/progress.py): lazy import — progress
    # imports monitor at module level
    if path == "/queries":
        _note_request("queries")
        from blaze_tpu.runtime import progress

        return (200, "application/json",
                json.dumps(progress.render_queries(),
                           default=str).encode())
    if path.startswith("/queries/"):
        _note_request("query_detail")
        from blaze_tpu.runtime import progress

        snap = progress.render_query(path[len("/queries/"):])
        if snap is None:
            return (404, "application/json",
                    b'{"error": "unknown or finished query"}')
        return (200, "application/json",
                json.dumps(snap, default=str).encode())
    _note_request("other")
    return 404, "text/plain", b"not found"


class MetricsServer:
    """Metrics + debug-endpoint server on a stdlib http.server daemon
    thread: GET /metrics (Prometheus exposition), /healthz (liveness),
    /queries and /queries/<qid> (live progress). Port 0 binds an
    ephemeral port (tests); `host` defaults to conf.metrics_host —
    loopback unless an operator deliberately exposes it.
    close() shuts the socket down and joins the thread."""

    def __init__(self, port: int, host: Optional[str] = None) -> None:
        if host is None:
            host = str(conf.metrics_host or "127.0.0.1")

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server contract
                try:
                    status, ctype, body = serve_path(
                        self.path.split("?")[0])
                except Exception as e:  # noqa: BLE001 — scrape, not crash
                    self.send_error(500, str(e)[:100])
                    return
                if status != 200 and not body:
                    self.send_error(status)
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-scrape stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="blz-metrics",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# -- global endpoint + sampler (lazily started by the local runner) ----------

_global_lock = threading.Lock()
_server: Optional[MetricsServer] = None
_sampler: Optional[ResourceMonitor] = None


def ensure_started() -> Optional[MetricsServer]:
    """Idempotent: serve /metrics on conf.metrics_port (restarting when
    the port changed) and run the background sampler. No-op when
    conf.metrics_port is 0."""
    global _server, _sampler
    port = int(conf.metrics_port or 0)
    with _global_lock:
        if port <= 0:
            return _server
        if _server is not None and _server.port != port:
            _server.close()
            _server = None
        if _server is None:
            _server = MetricsServer(port)
        if _sampler is None and conf.monitor_sample_ms > 0:
            _sampler = ResourceMonitor().start()
        return _server


def sampler() -> Optional[ResourceMonitor]:
    with _global_lock:
        return _sampler


def ring_slice(since_ts: Optional[float] = None) -> List[Dict[str, Any]]:
    """Global-sampler ring samples with ts >= since_ts ([] when the
    sampler never started) — the flight recorder's monitor slice."""
    s = sampler()
    if s is None:
        return []
    return s.ring_since(since_ts)


def shutdown() -> None:
    """Stop the global endpoint + sampler (tests / embedder teardown)."""
    global _server, _sampler
    with _global_lock:
        if _server is not None:
            _server.close()
            _server = None
        if _sampler is not None:
            _sampler.stop()
            _sampler = None
