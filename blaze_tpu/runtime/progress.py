"""Live per-query progress: stage waterfalls, attempt states, ETA.

The monitor answers "how much is the process doing"; this module
answers "how far along is query X" while it runs. A per-query record
tracks stage lifecycles (from the local runner), batch-boundary rows
(from ops/base.count_stream — the SAME heartbeat call site trace and
history tap, so the hot path gains no new check points), task attempt
states (from the supervisor), and resilience counters (retries, ladder
rungs, speculation — from the executor/supervisor hooks). Snapshots are
served by the metrics HTTP server as `GET /queries` (all live sessions:
tenant, phase, progress ratio, ETA, SLO headroom) and
`GET /queries/<qid>` (per-stage waterfall + live critical-path-so-far
from the monitor's boundary-time accounting).

ETA comes from history: at stage begin, the fingerprint's
`StatisticsFeed.observed_stage_cost()` p50 becomes the stage's expected
cost; remaining = sum(expected - elapsed) over unfinished stages. With
no history the ETA is null and the progress ratio falls back to stage
counts. The reported ratio is CLAMPED MONOTONE per query (a scraper
never sees progress go backwards).

Gating: every hook is one `conf.progress_enabled` truthiness check at
the call site (count_stream uses the same conditional-import posture as
the history tap); disabled, the registry stays empty and the endpoints
serve [].
"""

from __future__ import annotations

import threading
import time

from collections import deque
from typing import Any, Dict, List, Optional

from blaze_tpu.config import conf
from blaze_tpu.runtime import monitor, trace

_lock = threading.Lock()
_queries: Dict[str, "_QueryProgress"] = {}
# bounded ring of final summary rows for COMPLETED queries: the metrics
# exposition serves blaze_query_progress_ratio for live + last-N
# finished queries, so the {qid=} label cardinality on a long-lived
# endpoint is live+N instead of one series per query ever run. A module
# constant, not a knob — the bound exists to cap cardinality, not to be
# tuned per deployment.
FINISHED_RING = 32
_finished: deque = deque(maxlen=FINISHED_RING)


class _StageProgress:
    __slots__ = ("stage_id", "kind", "fingerprint", "tasks", "started_at",
                 "finished_at", "rows", "batches", "expected_ms", "error",
                 "attempts", "retries", "rungs", "speculations")

    def __init__(self, stage_id, kind, fingerprint, tasks,
                 expected_ms) -> None:
        self.stage_id = stage_id
        self.kind = kind
        self.fingerprint = fingerprint
        self.tasks = tasks
        self.started_at = time.time()
        self.finished_at: Optional[float] = None
        self.rows = 0
        self.batches = 0
        self.expected_ms = expected_ms
        self.error: Optional[str] = None
        # attempt_id -> {task, state, speculative, ts}
        self.attempts: Dict[Any, Dict[str, Any]] = {}
        self.retries = 0
        self.rungs: List[str] = []
        self.speculations = 0

    def elapsed_ms(self, now: float) -> float:
        end = self.finished_at if self.finished_at is not None else now
        return max(end - self.started_at, 0.0) * 1000.0


class _QueryProgress:
    __slots__ = ("query_id", "tenant_id", "started_at", "stages", "order",
                 "current_stage", "last_ratio", "slo_ms", "rows", "phase",
                 "streaming", "batch_epoch", "batches", "lag_ms",
                 "batch_ms_ewma", "resumed_batches")

    def __init__(self, query_id: str, tenant_id: Optional[str],
                 slo_ms: Optional[float]) -> None:
        self.query_id = query_id
        self.tenant_id = tenant_id or ""
        self.started_at = time.time()
        self.stages: Dict[Any, _StageProgress] = {}
        self.order: List[Any] = []
        self.current_stage: Any = None
        self.last_ratio = 0.0
        self.slo_ms = slo_ms
        self.rows = 0
        self.phase = "running"
        # unbounded (streaming) sessions: a 0..1 ratio is meaningless
        # over an infinite plan, so the summary reports per-batch
        # progress + a lag/watermark ETA instead
        self.streaming = False
        self.batch_epoch = 0
        self.batches = 0
        self.lag_ms = 0.0
        self.batch_ms_ewma: Optional[float] = None
        self.resumed_batches = 0


def _slo_objective_ms(tenant_id: Optional[str]) -> Optional[float]:
    spec = conf.tenant_slo_spec
    if not tenant_id or not isinstance(spec, dict):
        return None
    ten = spec.get(tenant_id)
    if isinstance(ten, dict) and ten.get("latency_ms"):
        return float(ten["latency_ms"])
    return None


def _stage_expectation(fingerprint: Optional[str]) -> Optional[float]:
    """Historical p50 stage cost for `fingerprint` (None without a
    history store or first-ever plan) — the ETA's unit of work."""
    if not fingerprint or not conf.history_dir:
        return None
    try:
        from blaze_tpu.runtime.history import StatisticsFeed

        exp = StatisticsFeed().observed_stage_cost(fingerprint)
    except Exception:  # noqa: BLE001 — ETA is advisory, never fatal
        return None
    return exp.get("ms_p50") if exp else None


# -- lifecycle hooks (call sites gate on conf.progress_enabled) --------------


def begin_query(query_id: str, tenant_id: Optional[str] = None) -> None:
    if not query_id:
        return
    q = _QueryProgress(query_id, tenant_id, _slo_objective_ms(tenant_id))
    with _lock:
        _queries[query_id] = q


def finish_query(query_id: str) -> None:
    """Drop the query from the live registry (endpoints list live
    queries only; the flight recorder + ledger own the postmortem) and
    stash its final summary row in the bounded finished ring for the
    metrics exposition."""
    now = time.time()
    with _lock:
        q = _queries.pop(query_id, None)
        if q is not None:
            q.phase = "finished"
            q.current_stage = None
            _finished.append(_summary_locked(q, now))


def begin_stream(stream_id: str, tenant_id: Optional[str] = None) -> None:
    """Register a long-lived streaming session (runtime/streaming.py).
    Unlike bounded queries it never reports a completion-fraction ratio;
    batches/epoch/lag carry its progress until finish_query drops it."""
    if not stream_id:
        return
    q = _QueryProgress(stream_id, tenant_id, _slo_objective_ms(tenant_id))
    q.streaming = True
    q.phase = "streaming"
    with _lock:
        _queries[stream_id] = q


def stream_batch(stream_id: str, epoch: int, rows: int, lag_ms: float,
                 batch_ms: float, resumed: bool = False) -> None:
    """One committed micro-batch: advances the epoch, feeds the lag-ETA
    estimator (EWMA of batch cost), and counts batches replayed from a
    checkpoint after a resume."""
    with _lock:
        q = _queries.get(stream_id)
        if q is None or not q.streaming:
            return
        q.batch_epoch = int(epoch)
        q.batches += 1
        q.rows += int(rows)
        q.lag_ms = float(lag_ms)
        q.batch_ms_ewma = (float(batch_ms) if q.batch_ms_ewma is None
                           else 0.7 * q.batch_ms_ewma + 0.3 * float(batch_ms))
        if resumed:
            q.resumed_batches += 1


def stream_lag(stream_id: str, lag_ms: float) -> None:
    """Between-batch lag refresh (idle ticks still age the watermark)."""
    with _lock:
        q = _queries.get(stream_id)
        if q is not None and q.streaming:
            q.lag_ms = float(lag_ms)


def stage_begin(query_id: str, stage_id, kind: str,
                fingerprint: Optional[str] = None,
                tasks: int = 1) -> None:
    expected = _stage_expectation(fingerprint)
    with _lock:
        q = _queries.get(query_id)
        if q is None:
            return
        st = _StageProgress(stage_id, kind, fingerprint, tasks, expected)
        q.stages[stage_id] = st
        if stage_id not in q.order:
            q.order.append(stage_id)
        q.current_stage = stage_id


def stage_end(query_id: str, stage_id, error: Optional[str] = None) -> None:
    with _lock:
        q = _queries.get(query_id)
        st = q.stages.get(stage_id) if q else None
        if st is None:
            return
        st.finished_at = time.time()
        st.error = error
        if q.current_stage == stage_id:
            q.current_stage = None


def on_batch(op, rows: int) -> None:
    """Batch-boundary tap (ops/base.count_stream). Attribution follows
    the monitor: trace context when present (supervised pool threads
    replay it), else the query's driver-registered current stage."""
    ctx = trace.current_context()
    qid = ctx.get("query_id")
    sid = ctx.get("stage_id")
    with _lock:
        if qid is None and len(_queries) == 1:
            qid = next(iter(_queries))
        q = _queries.get(qid) if qid else None
        if q is None:
            return
        q.rows += rows
        if sid is None:
            sid = q.current_stage
        st = q.stages.get(sid) if sid is not None else None
        if st is not None:
            st.rows += rows
            st.batches += 1


def attempt_update(trace_ctx: Dict[str, Any], attempt_id,
                   state: str, speculative: bool = False) -> None:
    """Task-attempt state export (supervisor._attempt_once): `state` is
    running -> ok | failed | killed:<reason>."""
    qid = trace_ctx.get("query_id")
    sid = trace_ctx.get("stage_id")
    with _lock:
        q = _queries.get(qid) if qid else None
        if q is None:
            return
        st = q.stages.get(sid if sid is not None else q.current_stage)
        if st is None:
            return
        rec = st.attempts.setdefault(
            attempt_id, {"task": trace_ctx.get("task_id"),
                         "speculative": bool(speculative)})
        rec["state"] = state
        rec["ts"] = time.time()
        if speculative and state == "running":
            st.speculations += 1


def note_event(kind: str, detail: Optional[str] = None) -> None:
    """Resilience-event tap (executor): retries and ladder rungs land on
    the attributed stage's waterfall row."""
    ctx = trace.current_context()
    qid = ctx.get("query_id")
    sid = ctx.get("stage_id")
    with _lock:
        if qid is None and len(_queries) == 1:
            qid = next(iter(_queries))
        q = _queries.get(qid) if qid else None
        if q is None:
            return
        st = q.stages.get(sid if sid is not None else q.current_stage)
        if st is None:
            return
        if kind == "retry":
            st.retries += 1
        elif kind == "ladder_rung" and detail:
            st.rungs.append(detail)


# -- snapshots ---------------------------------------------------------------


def _eta_ms(q: _QueryProgress, now: float) -> Optional[float]:
    """Remaining work from history expectations: sum over unfinished
    stages of (expected - elapsed), floored at 0. None until at least
    one live stage has an expectation (first-ever plans)."""
    known = False
    remaining = 0.0
    for st in q.stages.values():
        if st.finished_at is not None or st.expected_ms is None:
            continue
        known = True
        remaining += max(st.expected_ms - st.elapsed_ms(now), 0.0)
    return round(remaining, 3) if known else None


def _ratio(q: _QueryProgress, now: float) -> float:
    """Progress in [0, 1), monotone per query. Expected-cost weighted
    when history covers the stages seen so far; stage-count fallback
    otherwise (scaled by 0.9: the total stage count is unknown until
    the query ends, so the ratio never claims completion)."""
    total = done = 0.0
    weighted = True
    for sid in q.order:
        st = q.stages[sid]
        if st.expected_ms is None:
            weighted = False
            break
        total += st.expected_ms
        done += (st.elapsed_ms(now) if st.finished_at is None
                 else st.expected_ms)
    if weighted and total > 0:
        ratio = min(done / total, 0.99)
    else:
        n = len(q.order)
        fin = sum(1 for st in q.stages.values()
                  if st.finished_at is not None)
        ratio = 0.9 * fin / n if n else 0.0
    q.last_ratio = max(q.last_ratio, ratio)
    return round(q.last_ratio, 4)


def _summary_locked(q: _QueryProgress, now: float) -> Dict[str, Any]:
    elapsed = (now - q.started_at) * 1000.0
    if q.streaming:
        # unbounded session: no 0..1 ratio (the plan has no end). The
        # ETA reported is the LAG eta — expected time to drain the
        # current backlog at the observed per-batch cost — and the
        # per-batch fields carry the "how far along" story.
        lag_eta = (0.0 if q.lag_ms <= 0 else q.batch_ms_ewma)
        return {
            "query_id": q.query_id,
            "tenant_id": q.tenant_id,
            "phase": q.phase,
            "streaming": True,
            "elapsed_ms": round(elapsed, 3),
            "progress_ratio": None,
            "eta_ms": None,
            "batch_epoch": q.batch_epoch,
            "batches": q.batches,
            "lag_ms": round(q.lag_ms, 3),
            "lag_eta_ms": (round(lag_eta, 3)
                           if lag_eta is not None else None),
            "batch_ms": (round(q.batch_ms_ewma, 3)
                         if q.batch_ms_ewma is not None else None),
            "resumed_batches": q.resumed_batches,
            "slo_objective_ms": q.slo_ms,
            "slo_headroom_ms": None,
            "rows": q.rows,
            "stages_total": len(q.order),
            "stages_done": sum(1 for st in q.stages.values()
                               if st.finished_at is not None),
        }
    eta = _eta_ms(q, now)
    return {
        "query_id": q.query_id,
        "tenant_id": q.tenant_id,
        "phase": q.phase if q.current_stage is None
        else f"stage:{q.current_stage}",
        "elapsed_ms": round(elapsed, 3),
        "progress_ratio": _ratio(q, now),
        "eta_ms": eta,
        "slo_objective_ms": q.slo_ms,
        "slo_headroom_ms": (round(q.slo_ms - elapsed, 3)
                            if q.slo_ms else None),
        "rows": q.rows,
        "stages_total": len(q.order),
        "stages_done": sum(1 for st in q.stages.values()
                           if st.finished_at is not None),
    }


def snapshot_queries() -> List[Dict[str, Any]]:
    """Summary row per live query (the /queries payload)."""
    now = time.time()
    with _lock:
        return [_summary_locked(q, now) for q in _queries.values()]


def finished_queries() -> List[Dict[str, Any]]:
    """Final summary rows of the last FINISHED_RING completed queries
    (oldest-first) — the bounded tail the metrics exposition appends to
    the live rows."""
    with _lock:
        return list(_finished)


def snapshot_query(query_id: str) -> Optional[Dict[str, Any]]:
    """Per-stage waterfall + live critical-path-so-far for one live
    query (the /queries/<qid> payload); None when not live."""
    now = time.time()
    with _lock:
        q = _queries.get(query_id)
        if q is None:
            return None
        doc = _summary_locked(q, now)
        stages = []
        for sid in q.order:
            st = q.stages[sid]
            stages.append({
                "stage_id": st.stage_id,
                "kind": st.kind,
                "fingerprint": st.fingerprint,
                "state": ("failed" if st.error else
                          "done" if st.finished_at is not None
                          else "running"),
                "started_offset_ms": round(
                    (st.started_at - q.started_at) * 1000.0, 3),
                "elapsed_ms": round(st.elapsed_ms(now), 3),
                "expected_ms": st.expected_ms,
                "rows": st.rows,
                "batches": st.batches,
                "tasks": st.tasks,
                "attempts": [dict(v, attempt_id=k)
                             for k, v in st.attempts.items()],
                "retries": st.retries,
                "rungs": list(st.rungs),
                "speculations": st.speculations,
                "error": st.error,
            })
        doc["stages"] = stages
    # live critical-path-so-far: the monitor's boundary-time accounting
    # for the still-registered query (the doctor's term inputs, live)
    doc["critical_path_so_far_ms"] = monitor.query_time_breakdown(query_id)
    return doc


def render_queries() -> List[Dict[str, Any]]:
    """Endpoint wrapper: snapshot + a progress_snapshot trace event (the
    scrape itself is part of the query's record)."""
    snaps = snapshot_queries()
    trace.event("progress_snapshot", scope="queries", live=len(snaps))
    return snaps


def render_query(query_id: str) -> Optional[Dict[str, Any]]:
    snap = snapshot_query(query_id)
    if snap is not None:
        trace.event("progress_snapshot", query_id=query_id, scope="query")
    return snap


def active() -> List[str]:
    with _lock:
        return list(_queries)


def reset() -> None:
    with _lock:
        _queries.clear()
        _finished.clear()
