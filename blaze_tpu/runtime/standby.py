"""Warm-standby driver: fenced leader lease + mid-query takeover.

Ref: ROADMAP item 1 (driver high availability). PRs 12-15 made every
MECHANISM of a driverless recovery exist — the write-ahead journal
replays a dead writer's queries (journal.ensure_recovery_scan), shuffle
artifacts are crash-atomic and checksummed (runtime/artifacts.py), and
executors survive a vanished driver for a bounded lease window, re-
dialing the control socket until it expires (executor_pool._reconnect).
This module connects them into an ONLINE failover path: a second driver
process tails the journal directory, detects primary death by
pid-liveness (the same os.kill(pid, 0) posture journal._writer_alive
uses), fences the dead primary behind an epoch-bumped leader lease, and
takes over the live fleet mid-query.

The lease is one crash-atomic JSON file beside the journals
(artifacts.commit_file — temp + fsync + rename, so no reader ever sees
a torn lease):

    {"epoch": 3, "pid": 12345, "role": "primary",
     "acquired_at": ..., "renewed_at": ...}

Fencing mirrors PR 15's executor posture exactly: acquisition BUMPS the
epoch, and a paused-then-resumed old primary discovers the higher epoch
on its next renew() and stands down (``lease_fenced``) — it can never
split-brain the fleet, for the same reason a zombie executor's stale-
epoch results are rejected at the driver.

Takeover sequence (StandbyDriver._takeover):

  1. acquire the lease (epoch bump — the fence point);
  2. rebind the executor control plane at the dead primary's socket
     paths (ExecutorPool.rebind + start_rebound): dead workers are
     respawned, surviving workers are ADOPTED as their reconnect loop
     re-dials the very same ctl path;
  3. replay dead-writer journals into live resumable queries
     (journal.ensure_recovery_scan(force=True) — PR 13's offline
     recovery scan, run online);
  4. capture exactly one ``driver_failover`` dossier (lease epoch, dead
     primary pid, journals replayed, queries resumed vs. re-billed) and
     resume admission via the embedder's on_takeover callback.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from blaze_tpu.config import conf

LEASE_FILE = "leader.lease.json"
MANIFEST_FILE = "fleet.manifest.json"


def lease_path(directory: Optional[str] = None) -> str:
    return os.path.join(directory or conf.journal_dir, LEASE_FILE)


def manifest_path(directory: Optional[str] = None) -> str:
    return os.path.join(directory or conf.journal_dir, MANIFEST_FILE)


def read_lease(directory: Optional[str] = None) -> Optional[dict]:
    try:
        with open(lease_path(directory)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


# ---------------------------------------------------------------------------
# Role registry (monitor's blaze_driver_role gauge / /healthz "role")
# ---------------------------------------------------------------------------

_role_lock = threading.Lock()
_role = "primary"


def set_role(role: str) -> None:
    global _role
    with _role_lock:
        _role = role


def role() -> str:
    with _role_lock:
        return _role


# ---------------------------------------------------------------------------
# Fleet manifest
# ---------------------------------------------------------------------------


def publish_manifest(pool, directory: Optional[str] = None) -> str:
    """Commit the pool's socket topology beside the journals so a
    standby can rebind after this process dies. Crash-atomic: a SIGKILL
    mid-publish leaves the previous manifest intact. Re-published on
    every membership change (wire_manifest) so the seat list tracks
    spawns, deaths and drains."""
    from blaze_tpu.runtime import artifacts

    path = manifest_path(directory)
    doc = pool.manifest()

    def write(tmp: str) -> None:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    artifacts.commit_file(write, path)
    return path


def read_manifest(directory: Optional[str] = None) -> Optional[dict]:
    try:
        with open(manifest_path(directory)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def wire_manifest(pool, directory: Optional[str] = None) -> None:
    """Publish now and on every membership change."""
    publish_manifest(pool, directory)
    pool.on_membership(
        lambda p, d=directory: _republish_quiet(p, d))


def _republish_quiet(pool, directory: Optional[str]) -> None:
    try:
        publish_manifest(pool, directory)
    except Exception:  # noqa: BLE001 — membership cbs must not wedge
        pass


# ---------------------------------------------------------------------------
# Leader lease
# ---------------------------------------------------------------------------


class LeaderLease:
    """One process's handle on the leader lease file.

    ``acquire()`` takes the lease when it is free, its holder is dead,
    or its holder stopped renewing for conf.leader_lease_ms — always
    bumping the epoch, which IS the fence. ``renew()`` refreshes the
    holder's claim and returns False (setting ``fenced``) the moment a
    higher epoch appears in the file: a paused-then-resumed old primary
    self-fences instead of split-braining the fleet."""

    def __init__(self, directory: Optional[str] = None) -> None:
        self.directory = directory or conf.journal_dir
        self.epoch = 0
        self.fenced = False
        self._renew_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- core protocol -------------------------------------------------

    def acquire(self) -> bool:
        from blaze_tpu.runtime import artifacts

        cur = read_lease(self.directory)
        if cur is not None:
            pid = int(cur.get("pid", -1))
            cur_epoch = int(cur.get("epoch", 0))
            if (pid == os.getpid() and cur_epoch == self.epoch
                    and self.epoch > 0):
                return True  # already ours
            age_ms = (time.time()
                      - float(cur.get("renewed_at", 0.0))) * 1000.0
            fresh = age_ms <= max(int(conf.leader_lease_ms), 1)
            if artifacts._pid_alive(pid) and fresh:
                return False  # a live, renewing leader holds it
            self.epoch = cur_epoch + 1
        else:
            self.epoch = 1
        self.fenced = False
        self._write(acquired=True)
        return True

    def renew(self) -> bool:
        cur = read_lease(self.directory)
        if cur is not None and int(cur.get("epoch", 0)) > self.epoch:
            if not self.fenced:
                self.fenced = True
                from blaze_tpu.runtime import trace

                trace.event("lease_fenced", epoch=self.epoch,
                            observed_epoch=int(cur.get("epoch", 0)),
                            pid=os.getpid())
            return False
        if self.epoch <= 0 or self.fenced:
            return False
        self._write(acquired=False)
        return True

    def release(self) -> None:
        self._stop.set()

    def _write(self, acquired: bool) -> None:
        from blaze_tpu.runtime import artifacts

        now = time.time()
        doc = {"epoch": self.epoch, "pid": os.getpid(),
               "role": "primary", "renewed_at": now}
        if acquired:
            doc["acquired_at"] = now
            self._acquired_at = now
        doc.setdefault("acquired_at",
                       getattr(self, "_acquired_at", now))

        def write(tmp: str) -> None:
            with open(tmp, "w") as f:
                json.dump(doc, f)

        os.makedirs(self.directory, exist_ok=True)
        artifacts.commit_file(write, lease_path(self.directory))

    # -- background renewal (the primary's heartbeat) ------------------

    def start_renewing(self,
                       on_fenced: Optional[Callable[[], None]] = None
                       ) -> "LeaderLease":
        period = max(int(conf.leader_lease_ms), 30) / 3000.0

        def loop() -> None:
            while not self._stop.wait(period):
                try:
                    if not self.renew():
                        if on_fenced is not None:
                            on_fenced()
                        return
                except Exception:  # noqa: BLE001 — keep heartbeating
                    pass

        self._renew_thread = threading.Thread(
            target=loop, name="blz-lease-renew", daemon=True)
        self._renew_thread.start()
        return self


# ---------------------------------------------------------------------------
# The standby driver
# ---------------------------------------------------------------------------


class StandbyDriver:
    """Tails the lease + journal dir; takes over when the primary dies.

    The embedder supplies ``on_takeover(standby)`` to resume admission
    (start its QueryService, re-run resumable queries) — everything
    mechanical below that (lease fencing, control-plane rebind, worker
    adoption, journal replay, the driver_failover dossier) is handled
    here. ``takeover_info`` holds the evidence afterwards."""

    def __init__(self, directory: Optional[str] = None,
                 on_takeover: Optional[
                     Callable[["StandbyDriver"], None]] = None,
                 poll_s: float = 0.05) -> None:
        self.directory = directory or conf.journal_dir
        if not self.directory:
            raise ValueError("standby needs a journal_dir to tail")
        self.on_takeover = on_takeover
        self.poll_s = max(float(poll_s), 0.01)
        self.lease = LeaderLease(self.directory)
        self.pool = None
        self.took_over = False
        self.takeover_info: Optional[dict] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dog = None
        self._watched_pid: Optional[int] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "StandbyDriver":
        set_role("standby")
        self._thread = threading.Thread(
            target=self._watch, name="blz-standby", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._dog is not None:
            self._dog.close()
            self._dog = None
        self.lease.release()
        if self.pool is not None:
            self.pool.close()
            self.pool = None

    def wait_takeover(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and not self.took_over:
            time.sleep(0.02)
        return self.took_over

    # -- primary-death watch -------------------------------------------

    def _primary_down(self) -> bool:
        from blaze_tpu.runtime import artifacts

        cur = read_lease(self.directory)
        if cur is None:
            return True  # no leader at all: the seat is open
        pid = int(cur.get("pid", -1))
        if not artifacts._pid_alive(pid):
            return True  # the journal._writer_alive posture, online
        age_ms = (time.time()
                  - float(cur.get("renewed_at", 0.0))) * 1000.0
        return age_ms > max(int(conf.leader_lease_ms), 1)

    def _track_primary_pid(self) -> None:
        """Register the current lease holder with a ProcessWatchdog as a
        SILENT pid-liveness watch (supervisor stale_ms=0: no heartbeat
        expectation, no executor-death accounting) so a SIGKILLed
        primary wakes the watch loop at watchdog-tick latency instead of
        waiting out the lease staleness window."""
        cur = read_lease(self.directory)
        pid = int(cur.get("pid", -1)) if cur else -1
        if pid == self._watched_pid or pid <= 0:
            return
        from blaze_tpu.runtime import supervisor

        if self._dog is None:
            self._dog = supervisor.ProcessWatchdog()
        if self._watched_pid is not None:
            self._dog.unregister(f"primary:{self._watched_pid}")
        self._watched_pid = pid
        self._dog.register(f"primary:{pid}", pid,
                           lambda _peer, _reason, _rc: self._wake.set(),
                           stale_ms=0)

    def _watch(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.poll_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._track_primary_pid()
                if not self._primary_down():
                    continue
                if not self.lease.acquire():
                    continue  # lost the race to another standby
            except Exception:  # noqa: BLE001 — keep watching
                continue
            self._takeover()
            return

    # -- the takeover --------------------------------------------------

    def _takeover(self) -> None:
        from blaze_tpu.runtime import (executor_pool, flight_recorder,
                                       journal, trace)

        if self._dog is not None:
            self._dog.close()
            self._dog = None
        dead = read_manifest(self.directory) or {}
        # manifest-less primaries (no pool wired) still leave their pid
        # in the lease the watch loop tracked before acquiring over it
        dead_pid = int(dead.get("pid", -1))
        if dead_pid <= 0 and self._watched_pid:
            dead_pid = self._watched_pid
        set_role("primary")
        self.lease.start_renewing()
        t0 = time.monotonic()
        if dead.get("ctl_path"):
            try:
                self.pool = executor_pool.ExecutorPool.rebind(dead)
                self.pool.start_rebound()
                executor_pool.activate(self.pool)
                wire_manifest(self.pool, self.directory)
            except Exception:  # noqa: BLE001 — degrade to in-process
                if self.pool is not None:
                    self.pool.close()
                self.pool = None
        adopted = getattr(self.pool, "adopted_total", 0) \
            if self.pool is not None else 0
        # PR 13's offline recovery scan, run online: dead-writer
        # journals become live resumable queries / failed bills NOW,
        # under the new epoch, before admission resumes
        old_journal_dir = conf.journal_dir
        conf.update(journal_dir=self.directory)
        try:
            scan = journal.ensure_recovery_scan(force=True) or {}
        finally:
            conf.update(journal_dir=old_journal_dir or self.directory)
        self.takeover_info = {
            "lease_epoch": self.lease.epoch,
            "dead_primary_pid": dead_pid,
            "journals_replayed": int(scan.get("scanned", 0)),
            "queries_resumed": int(scan.get("resumable", 0)),
            "queries_rebilled": int(scan.get("billed_failed", 0)),
            "stages_recovered": int(scan.get("stages_recovered", 0)),
            "streams_adoptable": int(scan.get("streams_adoptable", 0)),
            "executors_adopted": adopted,
            "takeover_ms": round((time.monotonic() - t0) * 1000),
        }
        trace.event("driver_failover", **self.takeover_info)
        # exactly once per takeover: the dedup key is the epoch-stamped
        # query id — a second capture attempt for the same takeover
        # no-ops inside the recorder
        flight_recorder.capture(
            "driver_failover", f"failover-e{self.lease.epoch}",
            detail=dict(self.takeover_info))
        self.took_over = True
        if self.on_takeover is not None:
            try:
                self.on_takeover(self)
            except Exception:  # noqa: BLE001 — takeover already durable
                pass
