"""Multi-tenant query service: admission control, per-tenant quotas,
fair scheduling, and overload shedding (ROADMAP item 1).

The single-query driver (spark/local_runner.run_plan) assumes it owns
the process: one Supervisor pool, one global memory budget, one breaker.
`QueryService` turns that driver into a shared service — concurrent
query sessions tagged with a tenant id and priority, with the engine's
existing resilience machinery scoped per query instead of per process:

  admission    a bounded waiting room in front of the run slots
               (conf.max_concurrent_queries running,
               conf.admission_queue_depth parked). A query that arrives
               when every slot is busy PARKS; once the queue is full the
               service load-sheds by REJECTING new arrivals with a typed
               `faults.AdmissionRejected` instead of letting them pile
               up. The absolute query deadline is stamped at ARRIVAL, so
               time spent parked counts against conf.query_deadline_ms —
               a query whose budget expires while parked is shed, not
               started doomed.

  quotas       `MemManager.set_tenant_quotas(conf.tenant_quota_spec)`
               carves per-tenant ceilings out of the shared budget; a
               tenant over its ceiling spills its OWN consumers first
               (memory.py), so one tenant's spill pressure cannot evict
               another's working set.

  fairness     every admitted query submits its TaskSpecs to one shared
               `supervisor.FairScheduler` (stride scheduling across
               session queues, weighted by conf.tenant_priority_spec)
               instead of a private FIFO pool — under contention a
               weight-3 tenant gets ~3x the dispatch share of a
               weight-1 tenant, and no session starves.

  isolation    the breaker stays per-Supervisor (= per query), resource
               ids are namespaced by query id (spark/stages.py), and
               monitor/history attribute by the per-thread trace
               context — query A tripping its breaker or leaking a
               stream never reroutes or bills query B.

Every outcome lands in the run ledger (trace.export_run_ledger): an
admitted query's line carries `tenant_id`, `admission_outcome`
("admitted" | "parked") and `admission_wait_ms`; a shed query gets its
own line with outcome "rejected" — the ledger is the billing/SLO record
for all arrivals, not just the ones that ran.

Synchronous submission from N caller threads and async submission via
`submit()` futures are both supported; `run()` is submit + result.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from blaze_tpu.config import conf
from blaze_tpu.runtime import faults, memory, supervisor, trace

__all__ = ["QuerySession", "QueryService", "SloTracker", "stats",
           "slo_stats", "capacity"]


class QuerySession:
    """Identity + budgets for one query's lifetime inside the service.

    Duck-typed consumers (Supervisor, executor ladder, ops/common
    adaptive batching) read: `tenant_id`, `query_id`, `priority`,
    `deadline_at` (absolute monotonic, admission-stamped, or None),
    `scheduler` (the shared FairScheduler, or None), and `batch_target`
    (session-scoped ladder override of conf.target_batch_bytes; 0 = no
    override)."""

    __slots__ = ("tenant_id", "query_id", "priority", "deadline_at",
                 "scheduler", "batch_target", "arrived_at",
                 "admission_outcome", "admission_wait_ms")

    def __init__(self, tenant_id: str, priority: Optional[float] = None,
                 scheduler=None) -> None:
        self.tenant_id = tenant_id
        self.query_id = trace.new_query_id()
        if priority is None:
            priority = float(
                (conf.tenant_priority_spec or {}).get(tenant_id, 1.0))
        self.priority = max(float(priority), 1e-6)
        self.arrived_at = time.monotonic()
        self.deadline_at: Optional[float] = None
        if conf.query_deadline_ms and conf.query_deadline_ms > 0:
            self.deadline_at = (self.arrived_at
                                + conf.query_deadline_ms / 1000.0)
        self.scheduler = scheduler
        self.batch_target = 0
        self.admission_outcome = ""
        self.admission_wait_ms = 0.0


class SloTracker:
    """Rolling per-tenant latency-SLO attainment + burn rate.

    `conf.tenant_slo_spec` declares the objectives ({'tenant':
    {'latency_ms': 500, 'target': 0.99}}). Every arrival's TOTAL latency
    (admission wait + execution — the number the run ledger records as
    admission_wait_ms + duration_ms, so offline recomputation from
    ledger lines matches) is scored against the tenant's objective over
    a rolling window of conf.slo_window_queries arrivals; queries SHED
    at admission count as misses. Burn rate is miss_rate /
    error_budget: 1.0 burns the budget exactly at window turnover, 2.0
    burns it in half a window — past conf.slo_burn_alert_rate each
    observation emits a `slo_burn` trace event. monitor.prometheus_text
    exports the numbers as blaze_slo_* gauges via `slo_stats()`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._met: Dict[str, deque] = {}
        self._breaches: Dict[str, int] = {}

    @staticmethod
    def _spec(tenant_id: str) -> Optional[Dict[str, float]]:
        sp = (conf.tenant_slo_spec or {}).get(tenant_id)
        if not isinstance(sp, dict):
            return None
        obj = float(sp.get("latency_ms", 0) or 0)
        if obj <= 0:
            return None
        target = min(max(float(sp.get("target", 0.99)), 0.0), 1.0)
        return {"latency_ms": obj, "target": target}

    def observe(self, tenant_id: str, latency_ms: float,
                rejected: bool = False,
                query_id: Optional[str] = None) -> None:
        """Score one arrival; emits `slo_burn` when the budget runs hot."""
        sp = self._spec(tenant_id)
        if sp is None:
            return
        met = (not rejected) and latency_ms <= sp["latency_ms"]
        with self._lock:
            win = self._met.get(tenant_id)
            if win is None or win.maxlen != max(
                    int(conf.slo_window_queries), 1):
                win = deque(win or (),
                            maxlen=max(int(conf.slo_window_queries), 1))
                self._met[tenant_id] = win
            win.append(met)
            if not met:
                self._breaches[tenant_id] = \
                    self._breaches.get(tenant_id, 0) + 1
            stats = self._stats_locked(tenant_id, sp)
        if stats["burn_rate"] > max(float(conf.slo_burn_alert_rate), 0.0):
            trace.event("slo_burn", tenant_id=tenant_id,
                        latency_ms=round(latency_ms, 1),
                        objective_ms=sp["latency_ms"],
                        attainment=stats["attainment"],
                        burn_rate=stats["burn_rate"])
        # SLO-breach dossier (shed arrivals get their own "shed" dossier
        # in admit()). No locks held here: _release scores after leaving
        # the admission section, and capture does file I/O.
        if not met and not rejected and query_id and conf.flight_dir:
            from blaze_tpu.runtime import flight_recorder

            flight_recorder.capture(
                "slo_breach", query_id, tenant_id=tenant_id,
                detail={"latency_ms": round(latency_ms, 3),
                        "objective_ms": sp["latency_ms"],
                        "attainment": stats["attainment"],
                        "burn_rate": stats["burn_rate"]})

    def _stats_locked(self, tenant_id: str,
                      sp: Dict[str, float]) -> Dict[str, Any]:
        win = self._met.get(tenant_id) or ()
        n = len(win)
        attainment = (sum(1 for m in win if m) / n) if n else 1.0
        budget = 1.0 - sp["target"]
        miss = 1.0 - attainment
        if budget > 0:
            burn = miss / budget
        else:
            burn = 0.0 if miss <= 0 else float(n)  # target=1.0: any miss
        return {"latency_ms": sp["latency_ms"], "target": sp["target"],
                "window": n, "attainment": round(attainment, 4),
                "burn_rate": round(burn, 4),
                "breaches": self._breaches.get(tenant_id, 0)}

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant SLO readout for every tenant in the spec (tenants
        with no observations yet report attainment 1.0 / burn 0.0 — the
        gauges exist from the first scrape, mid-query included)."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            tenants = set(self._met) | set(conf.tenant_slo_spec or {})
            for t in sorted(tenants):
                sp = self._spec(t)
                if sp is not None:
                    out[t] = self._stats_locked(t, sp)
        return out

    def reset(self) -> None:
        with self._lock:
            self._met.clear()
            self._breaches.clear()


class QueryService:
    """Shared driver accepting concurrent query sessions.

    Use as a context manager (or start()/close()). `run(root, tenant_id,
    ...)` admits, executes, and returns the result batch; `submit(...)`
    does the same asynchronously on a per-query driver thread and
    returns a Future. Both raise `faults.AdmissionRejected` when the
    query is shed (queue full, or deadline expired while parked)."""

    def __init__(self, max_concurrent: Optional[int] = None,
                 queue_depth: Optional[int] = None) -> None:
        self.max_concurrent = max(1, int(
            max_concurrent if max_concurrent is not None
            else conf.max_concurrent_queries))
        self.queue_depth = max(0, int(
            queue_depth if queue_depth is not None
            else conf.admission_queue_depth))
        self._lock = threading.Lock()
        self._slot_free = threading.Condition(self._lock)
        self._running = 0
        self._parked = 0
        self._admitted_total = 0
        self._parked_total = 0
        self._rejected_total = 0
        self._threads: List[threading.Thread] = []
        self.scheduler: Optional[supervisor.FairScheduler] = None
        self._open = False
        self._pool = None  # attached executor pool (capacity source)
        self._streams: List[Any] = []  # long-lived StreamingQuery sessions

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "QueryService":
        global _active
        # driver-crash recovery before the first admission: incomplete
        # journals from a killed predecessor are replayed (verified
        # stage commits harvested for reuse, the rest billed failed)
        from blaze_tpu.runtime import journal

        journal.ensure_recovery_scan()
        self.scheduler = supervisor.FairScheduler(
            max(1, int(conf.max_concurrent_tasks)))
        memory.get_manager().set_tenant_quotas(conf.tenant_quota_spec)
        with self._lock:
            self._open = True
        _active = self
        # a process-isolated pool that is already active becomes the
        # capacity source automatically (graceful-degradation contract)
        from blaze_tpu.runtime import executor_pool

        pool = executor_pool.active()
        if pool is not None:
            self.attach_pool(pool)
        return self

    def attach_pool(self, pool) -> None:
        """Derive admission capacity from an executor pool: capacity =
        live_executors x slots, recomputed on every membership change
        (death or rejoin). A shrink does not kill running queries — it
        parks new arrivals until a seat rejoins or their deadline sheds
        them; capacity 0 parks everything (and /healthz goes 503)."""
        # plain attribute store: capacity() reads _pool from admission
        # waits that already hold the slot condition — no extra lock
        self._pool = pool
        pool.on_membership(self._on_pool_change)
        self._on_pool_change(pool)

    def _on_pool_change(self, pool) -> None:
        cap = pool.capacity()
        trace.event("capacity_changed", capacity=cap,
                    live_executors=pool.live_count(), slots=pool.slots)
        with self._slot_free:
            # capacity may have GROWN (rejoin): wake the waiting room
            self._slot_free.notify_all()

    def capacity(self) -> int:
        pool = self._pool
        if pool is not None:
            return pool.capacity()
        return self.max_concurrent

    def close(self) -> None:
        global _active
        # detach live streams FIRST (their micro-batches run through
        # admission): non-graceful stop — a service shutdown must not
        # settle a stream's journal, the stream stays adoptable by the
        # next driver (streaming.resume_stream)
        with self._lock:
            streams = list(self._streams)
            self._streams = []
        for sq in streams:
            try:
                sq.stop(graceful=False)
            except Exception:  # noqa: BLE001 — close() must not raise
                pass
        with self._lock:
            self._open = False
            self._slot_free.notify_all()
            drivers = list(self._threads)
        for t in drivers:
            t.join(timeout=30.0)
        if self.scheduler is not None:
            self.scheduler.close()
        memory.get_manager().set_tenant_quotas(None)
        if _active is self:
            _active = None

    def __enter__(self) -> "QueryService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- admission ---------------------------------------------------------

    def _shed_locked(self, session: QuerySession, reason: str,
                     wait_ms: float) -> None:
        """Reject (caller holds self._lock): count, trace, write the
        ledger line — shed queries are billed too — raise the typed
        error."""
        self._rejected_total += 1
        session.admission_outcome = "rejected"
        session.admission_wait_ms = wait_ms
        trace.event("admission_rejected", query_id=session.query_id,
                    tenant_id=session.tenant_id, reason=reason,
                    wait_ms=round(wait_ms, 1))
        self._export_shed_ledger(session, reason)
        _slo.observe(session.tenant_id, wait_ms, rejected=True,
                     query_id=session.query_id)
        raise faults.AdmissionRejected(
            f"query {session.query_id} (tenant {session.tenant_id!r}) "
            f"shed at admission: {reason} "
            f"(waited {wait_ms:.0f}ms)",
            tenant_id=session.tenant_id, wait_ms=wait_ms)

    def _export_shed_ledger(self, session: QuerySession,
                            reason: str) -> None:
        d = conf.trace_export_dir
        if not (conf.trace_enabled and d):
            return
        info = {"tenant_id": session.tenant_id,
                "admission_outcome": "rejected",
                "admission_wait_ms": round(session.admission_wait_ms, 1),
                "admission_reject_reason": reason}
        rec = trace.build_run_record(session.query_id, info)
        trace.export_run_ledger(os.path.join(d, "ledger.jsonl"), rec)

    def admit(self, tenant_id: str,
              priority: Optional[float] = None) -> QuerySession:
        """Block until the session holds a run slot (or shed it).

        Immediate admit when a slot is free; PARK while the bounded
        queue has room, waking on slot release; REJECT when the queue is
        full or the parked session's deadline expires. The returned
        session owns a slot — `_release` it exactly once (run/submit do
        this internally)."""
        session = QuerySession(tenant_id, priority, self.scheduler)
        try:
            return self._admit_inner(session)
        except faults.AdmissionRejected as e:
            # shed dossier AFTER the admission lock is released (capture
            # does file I/O; _shed_locked runs holding self._lock)
            if conf.flight_dir:
                from blaze_tpu.runtime import flight_recorder

                flight_recorder.capture(
                    "shed", session.query_id, error=e,
                    tenant_id=session.tenant_id,
                    run_info={
                        "tenant_id": session.tenant_id,
                        "admission_outcome": "rejected",
                        "admission_wait_ms":
                            round(session.admission_wait_ms, 1)})
            raise

    def _admit_inner(self, session: QuerySession) -> QuerySession:
        parked = False
        with self._slot_free:
            if not self._open:
                raise RuntimeError("QueryService is closed")
            if self._running >= self.capacity():
                if self._parked >= self.queue_depth:
                    self._shed_locked(session, "queue_full", 0.0)
                parked = True
                self._parked += 1
                self._parked_total += 1
                trace.event("admission_parked", query_id=session.query_id,
                            tenant_id=session.tenant_id,
                            queue_depth=self._parked)
                try:
                    # capacity() is re-read every wake: an executor death
                    # shrinks it mid-wait (stay parked), a rejoin grows
                    # it (admit)
                    while self._open and self._running >= self.capacity():
                        timeout = None
                        if session.deadline_at is not None:
                            timeout = session.deadline_at - time.monotonic()
                            if timeout <= 0:
                                break
                        self._slot_free.wait(timeout)
                finally:
                    self._parked -= 1
                wait_ms = (time.monotonic() - session.arrived_at) * 1000.0
                if not self._open:
                    raise RuntimeError("QueryService closed while parked")
                if self._running >= self.capacity():
                    # deadline expired in the waiting room — shed without
                    # starting a run that could only end in DeadlineError
                    self._shed_locked(session, "deadline_while_parked",
                                      wait_ms)
            self._running += 1
            self._admitted_total += 1
        wait_ms = (time.monotonic() - session.arrived_at) * 1000.0
        session.admission_outcome = "parked" if parked else "admitted"
        session.admission_wait_ms = wait_ms
        trace.event("admission_admitted", query_id=session.query_id,
                    tenant_id=session.tenant_id,
                    wait_ms=round(wait_ms, 1), parked=parked)
        return session

    def _release(self, session: QuerySession) -> None:
        if self.scheduler is not None:
            self.scheduler.forget(session)
        # total latency since ARRIVAL: admission wait + execution — the
        # same number the ledger line decomposes, scored once per admit
        _slo.observe(session.tenant_id,
                     (time.monotonic() - session.arrived_at) * 1000.0,
                     query_id=session.query_id)
        with self._slot_free:
            self._running -= 1
            self._slot_free.notify_all()

    # -- execution ---------------------------------------------------------

    def run(self, root, tenant_id: str = "", *,
            priority: Optional[float] = None,
            run_info: Optional[Dict[str, Any]] = None,
            conf_pins: Optional[Dict[str, Any]] = None,
            **run_plan_kwargs):
        """Admit + execute on the CALLING thread; returns the result
        batch. Raises faults.AdmissionRejected when shed.

        conf_pins: per-query knob overrides — the highest-precedence
        overlay layer (base -> tenant -> autopilot fingerprint -> pin),
        validated against the Knob registry at resolution."""
        from blaze_tpu.spark import local_runner

        session = self.admit(tenant_id, priority)
        if run_info is None:
            run_info = {}
        run_info["tenant_id"] = session.tenant_id
        run_info["admission_outcome"] = session.admission_outcome
        run_info["admission_wait_ms"] = round(session.admission_wait_ms, 1)
        if conf_pins:
            run_info["conf_pins"] = dict(conf_pins)
        try:
            return local_runner.run_plan(root, run_info=run_info,
                                         session=session,
                                         **run_plan_kwargs)
        finally:
            self._release(session)

    def submit(self, root, tenant_id: str = "", *,
               priority: Optional[float] = None,
               run_info: Optional[Dict[str, Any]] = None,
               conf_pins: Optional[Dict[str, Any]] = None,
               **run_plan_kwargs) -> Future:
        """Admit on the calling thread (so AdmissionRejected raises
        HERE, synchronously — shedding must push back on the submitter),
        then execute on a per-query driver thread; returns a Future.
        conf_pins: as in run() — the per-query overlay layer."""
        from blaze_tpu.spark import local_runner

        session = self.admit(tenant_id, priority)
        if run_info is None:
            run_info = {}
        run_info["tenant_id"] = session.tenant_id
        run_info["admission_outcome"] = session.admission_outcome
        run_info["admission_wait_ms"] = round(session.admission_wait_ms, 1)
        if conf_pins:
            run_info["conf_pins"] = dict(conf_pins)
        fut: Future = Future()

        def drive() -> None:
            if not fut.set_running_or_notify_cancel():
                self._release(session)
                return
            try:
                fut.set_result(local_runner.run_plan(
                    root, run_info=run_info, session=session,
                    **run_plan_kwargs))
            except BaseException as e:  # noqa: BLE001 — relay via future
                fut.set_exception(e)
            finally:
                self._release(session)

        t = threading.Thread(target=drive,
                             name=f"blz-query-{session.query_id}",
                             daemon=True)
        with self._lock:
            # bounded bookkeeping: drop finished driver threads
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        t.start()
        return fut

    # -- streaming sessions ------------------------------------------------

    def open_stream(self, source, spec, tenant_id: str = "", *,
                    stream_id: Optional[str] = None, **kwargs: Any):
        """Open a long-lived streaming session (runtime/streaming.py)
        bound to this service: every micro-batch is admitted like any
        other query — the tenant's priority weight, quota, fairness
        share and per-batch SLO scoring all apply — so a stream cannot
        starve batch tenants, and admission pressure shows up as stream
        lag rather than unbounded queueing. Returns the started
        StreamingQuery."""
        from blaze_tpu.runtime import streaming

        with self._lock:
            if not self._open:
                raise RuntimeError("QueryService is closed")
        sq = streaming.open_stream(source, spec, stream_id=stream_id,
                                   tenant_id=tenant_id, service=self,
                                   **kwargs)
        with self._lock:
            self._streams = [s for s in self._streams if s.alive()]
            self._streams.append(sq)
        return sq

    def resume_stream(self, stream_id: str, **kwargs: Any):
        """Adopt a dead driver's stream (journal checkpoints) into this
        service — the standby-takeover path."""
        from blaze_tpu.runtime import streaming

        sq = streaming.resume_stream(stream_id, service=self, **kwargs)
        with self._lock:
            self._streams.append(sq)
        return sq

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, int]:
        cap = self.capacity()
        with self._lock:
            return {
                "running": self._running,
                "queue_depth": self._parked,
                "admitted": self._admitted_total,
                "parked": self._parked_total,
                "rejected": self._rejected_total,
                "capacity": cap,
                "streams": sum(1 for s in self._streams if s.alive()),
            }


_active: Optional[QueryService] = None


def active() -> Optional[QueryService]:
    return _active


def stats() -> Dict[str, int]:
    """Admission stats of the active service; all-zero when none is
    running (monitor.py imports this unconditionally for the Prometheus
    gauges and blaze_top rows)."""
    svc = _active
    if svc is None:
        return {"running": 0, "queue_depth": 0, "admitted": 0,
                "parked": 0, "rejected": 0, "capacity": capacity()}
    return svc.stats()


def capacity() -> int:
    """Current admission capacity: the active service's (pool-derived
    when one is attached), else the active pool's, else the static
    conf.max_concurrent_queries."""
    svc = _active
    if svc is not None:
        return svc.capacity()
    from blaze_tpu.runtime import executor_pool

    pool = executor_pool.active()
    if pool is not None:
        return pool.capacity()
    return max(1, int(conf.max_concurrent_queries))


# SLO state is process-wide, not per-QueryService: objectives describe
# tenants, and tenants outlive service restarts within one process.
_slo = SloTracker()


def slo_stats() -> Dict[str, Dict[str, Any]]:
    """Per-tenant SLO attainment/burn for monitor.prometheus_text and
    blaze_top; one entry per tenant in conf.tenant_slo_spec."""
    return _slo.stats()


def reset_slo() -> None:
    """Drop all SLO windows/breach totals (tests)."""
    _slo.reset()
