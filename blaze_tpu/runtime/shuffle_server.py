"""Shuffle service: serves committed `.data`/`.index` segments (and
broadcast frame lists) to executor processes over a Unix socket.

Ref: Spark's shuffle service — reduce tasks fetch map outputs from the
node that committed them, not from the writer task (which may be dead).
Here the driver owns the crash-atomic artifacts (artifacts.py commit
protocol), so it serves them: an executor's ipc_reader resolves a
"<qid>/shuffle:<sid>" resource to a client that fetches partition
segments from THIS server. Because segments are read from the committed
files, a map executor can die after commit and its output remains
fetchable — the lineage property executor-death recovery relies on
(re-execute only the LOST partitions).

Wire format (shared with the executor control socket,
runtime/executor_pool.py): the serde frame discipline applied to control
messages — `u32 magic | u32 raw_len | u32 comp_len | u32 blob_len |
compressed(json header) | blob`. The header rides the same
compressor family as shuffle frames (serde's zstd-or-zlib posture at
conf.zstd_level); the blob is opaque bytes — for segment replies it is a
concatenation of serde "BTB1" frames, handed to IpcReaderExec undecoded.
The executor control socket carries one extra message family over the
same framing: `{"type": "telemetry", "seq": N, ...}` batches ship a
worker's span/counter/histogram deltas driver-ward (executor_pool's
federation path). BCS1 framing is type-agnostic, so telemetry needed no
wire change — only a new header "type" the driver-side reader dispatches.

Kept import-light on purpose: executor worker processes import this
before deciding whether a task needs the engine at all, so nothing here
may pull jax/numpy.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

MAGIC = b"BCS1"
_HEAD = struct.Struct("<4sIII")
# largest accepted frame: a poisoned/corrupt length prefix must not make
# recv_msg attempt a multi-GiB allocation
MAX_FRAME = 1 << 31


class WireError(ConnectionError):
    """Framing violation (bad magic / oversized length): the peer is not
    speaking the protocol — callers treat it like a lost connection."""


def send_msg(sock: socket.socket, header: dict, blob: bytes = b"",
             lock: Optional[threading.Lock] = None) -> None:
    """Serialize + frame one message; `lock` serializes concurrent
    senders sharing the socket (a torn frame is unrecoverable)."""
    raw = json.dumps(header, separators=(",", ":")).encode()
    comp = zlib.compress(raw, 1)
    buf = _HEAD.pack(MAGIC, len(raw), len(comp), len(blob)) + comp
    if lock is not None:
        with lock:
            sock.sendall(buf)
            if blob:
                sock.sendall(blob)
    else:
        sock.sendall(buf)
        if blob:
            sock.sendall(blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame"
                                  if chunks else "peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    """Read one framed message; raises ConnectionError on EOF/short read
    and WireError on a malformed frame."""
    head = _recv_exact(sock, _HEAD.size)
    magic, raw_len, comp_len, blob_len = _HEAD.unpack(head)
    if magic != MAGIC:
        raise WireError(f"bad frame magic {magic!r}")
    if max(raw_len, comp_len, blob_len) > MAX_FRAME:
        raise WireError("frame length exceeds MAX_FRAME")
    raw = zlib.decompress(_recv_exact(sock, comp_len))
    if len(raw) != raw_len:
        raise WireError("frame raw_len mismatch")
    blob = _recv_exact(sock, blob_len) if blob_len else b""
    return json.loads(raw.decode()), blob


def _read_segment(data_path: str, index_path: str, partition: int) -> bytes:
    """One map output's VERIFIED bytes for `partition`, located through
    the committed little-endian u64 offsets index (the FileSegment fetch
    of shuffle_manager.get_reader, without the decode). Delegates to
    artifacts.fetch_segment — checksum verification, quarantine and
    lineage repair happen server-side, where the repair closures live.
    The import is lazy to keep this module import-light (worker
    processes import it before deciding whether they need the engine;
    _read_segment only ever runs driver-side)."""
    from blaze_tpu.runtime import artifacts

    return artifacts.fetch_segment(data_path, index_path, partition)


class ShuffleServer:
    """Driver-side artifact server. `register_shuffle` publishes a
    completed stage's map outputs under its resource id;
    `register_frames` publishes a broadcast stage's frame list. Executors
    fetch with {"type": "fetch", "rid": ..., "partition": p} and get the
    concatenated serde frames back as the reply blob."""

    def __init__(self, sock_path: str) -> None:
        self.sock_path = sock_path
        self._lock = threading.Lock()
        # rid -> list of (data_path, index_path) map outputs
        self._shuffles: Dict[str, List[Tuple[str, str]]] = {}
        # rid -> broadcast frame list (already serde frames)
        self._frames: Dict[str, List[bytes]] = {}
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        self.fetches = 0

    # -- registry ------------------------------------------------------

    def register_shuffle(self, rid: str,
                         outputs: Sequence[Tuple[str, str]]) -> None:
        with self._lock:
            self._shuffles[rid] = list(outputs)

    def register_frames(self, rid: str, frames: Sequence[bytes]) -> None:
        with self._lock:
            self._frames[rid] = list(frames)

    def unregister(self, rid: str) -> None:
        with self._lock:
            self._shuffles.pop(rid, None)
            self._frames.pop(rid, None)

    def unregister_prefix(self, prefix: str) -> None:
        """Drop every rid of a finished query's namespace."""
        with self._lock:
            for reg in (self._shuffles, self._frames):
                for rid in [r for r in reg if r.startswith(prefix)]:
                    reg.pop(rid, None)

    def registered(self) -> List[str]:
        with self._lock:
            return sorted(self._shuffles) + sorted(self._frames)

    # -- serving -------------------------------------------------------

    def start(self) -> None:
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self.sock_path)
        listener.listen(64)
        self._listener = listener
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="blz-shufsrv", daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="blz-shufsrv-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                try:
                    msg, _blob = recv_msg(conn)
                except ConnectionError:
                    return
                if msg.get("type") != "fetch":
                    send_msg(conn, {"ok": False,
                                    "error": "unknown request type"})
                    continue
                rid = msg.get("rid", "")
                partition = int(msg.get("partition", 0))
                try:
                    blob = self._fetch(rid, partition)
                except Exception as e:  # noqa: BLE001 — relayed to peer
                    send_msg(conn, {"ok": False, "rid": rid,
                                    "error": f"{type(e).__name__}: {e}"})
                    continue
                send_msg(conn, {"ok": True, "rid": rid}, blob)
        finally:
            conn.close()

    def _fetch(self, rid: str, partition: int) -> bytes:
        with self._lock:
            outputs = self._shuffles.get(rid)
            frames = self._frames.get(rid)
            self.fetches += 1
        if outputs is not None:
            return b"".join(_read_segment(d, i, partition)
                            for d, i in outputs)
        if frames is not None:
            return b"".join(frames)
        raise KeyError(f"resource not served: {rid}")

    def close(self) -> None:
        self._closed.set()
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=1.0)
            self._accept_thread = None
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass


class ShuffleClient:
    """Executor-side fetch client: one connection, request/response under
    a lock (concurrent task slots in one worker share it)."""

    def __init__(self, sock_path: str) -> None:
        self.sock_path = sock_path
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    @staticmethod
    def _timeout_ms() -> float:
        # lazy conf import: importing blaze_tpu.config initializes the
        # package (jax), which this module must not do at import time
        from blaze_tpu.config import conf

        return float(conf.shuffle_connect_timeout_ms)

    def _ensure_locked(self) -> socket.socket:
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            timeout_ms = self._timeout_ms()
            if timeout_ms > 0:
                # bounds connect AND every recv: a hung shuffle server
                # surfaces as socket.timeout (an OSError the retry
                # ladder absorbs) instead of blocking the task forever
                s.settimeout(timeout_ms / 1000.0)
            s.connect(self.sock_path)
            self._sock = s
        return self._sock

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _fetch_once_locked(self, rid: str,
                           partition: int) -> Tuple[dict, bytes]:
        sock = self._ensure_locked()
        send_msg(sock, {"type": "fetch", "rid": rid,
                        "partition": partition})
        return recv_msg(sock)

    def fetch(self, rid: str, partition: int) -> bytes:
        """Fetch one partition segment, retrying lost/hung connections
        on a bounded exponential-backoff ladder: the whole ladder (and
        each socket read) fits inside conf.shuffle_connect_timeout_ms,
        so a hung or restarting shuffle server costs a bounded wait,
        never a wedged task. 0 restores the legacy posture — blocking
        socket, one reconnect."""
        timeout_ms = self._timeout_ms()
        with self._lock:
            if timeout_ms <= 0:
                try:
                    msg, blob = self._fetch_once_locked(rid, partition)
                except (ConnectionError, OSError):
                    # one reconnect: the driver may have restarted the
                    # listener; a second failure is the caller's problem
                    self._close_locked()
                    msg, blob = self._fetch_once_locked(rid, partition)
            else:
                deadline = time.monotonic() + timeout_ms / 1000.0
                delay = 0.01
                attempt = 0
                while True:
                    try:
                        msg, blob = self._fetch_once_locked(rid, partition)
                        break
                    except (ConnectionError, OSError) as e:
                        self._close_locked()
                        attempt += 1
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise ConnectionError(
                                f"shuffle fetch {rid}[{partition}] "
                                f"failed after {attempt} attempts "
                                f"within {int(timeout_ms)}ms: {e}"
                            ) from e
                        time.sleep(min(delay, remaining))
                        delay = min(delay * 2.0, 0.5)
        if not msg.get("ok"):
            raise KeyError(msg.get("error", f"fetch failed: {rid}"))
        return blob

    def close(self) -> None:
        with self._lock:
            self._close_locked()


def split_frames(blob: bytes) -> List[bytes]:
    """Split a fetched segment into its serde "BTB1" frames (layout:
    columnar/serde.py — u32 magic | u32 raw_len | u32 comp_len | body).
    IpcReaderExec decodes raw frame bytes itself, so executors never need
    the serde module just to route segments."""
    frames: List[bytes] = []
    off = 0
    total = len(blob)
    while off < total:
        if off + 12 > total:
            raise WireError("truncated shuffle frame header")
        _raw_len, comp_len = struct.unpack_from("<II", blob, off + 4)
        end = off + 12 + comp_len
        if end > total:
            raise WireError("truncated shuffle frame body")
        frames.append(blob[off:end])
        off = end
    return frames
