"""Shuffle service: serves committed `.data`/`.index` segments (and
broadcast frame lists) to executor processes over a Unix socket.

Ref: Spark's shuffle service — reduce tasks fetch map outputs from the
node that committed them, not from the writer task (which may be dead).
Here the driver owns the crash-atomic artifacts (artifacts.py commit
protocol), so it serves them: an executor's ipc_reader resolves a
"<qid>/shuffle:<sid>" resource to a client that fetches partition
segments from THIS server. Because segments are read from the committed
files, a map executor can die after commit and its output remains
fetchable — the lineage property executor-death recovery relies on
(re-execute only the LOST partitions).

Wire format (shared with the executor control socket,
runtime/executor_pool.py): the serde frame discipline applied to control
messages — `u32 magic | u32 raw_len | u32 comp_len | u32 blob_len |
[u32 crc32 when magic is BCS2] | compressed(json header) | blob`; the
CRC covers compressed header + blob, and BCS1 frames (no checksum)
still parse for version tolerance. The header rides the same
compressor family as shuffle frames (serde's zstd-or-zlib posture at
conf.zstd_level); the blob is opaque bytes — for segment replies it is a
concatenation of serde "BTB1" frames, handed to IpcReaderExec undecoded.
The executor control socket carries one extra message family over the
same framing: `{"type": "telemetry", "seq": N, ...}` batches ship a
worker's span/counter/histogram deltas driver-ward (executor_pool's
federation path). BCS1 framing is type-agnostic, so telemetry needed no
wire change — only a new header "type" the driver-side reader dispatches.

Kept import-light on purpose: executor worker processes import this
before deciding whether a task needs the engine at all, so nothing here
may pull jax/numpy.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

MAGIC = b"BCS1"
_HEAD = struct.Struct("<4sIII")
# BCS2 appends a CRC32 of the frame body (compressed header + blob) so
# torn/corrupted frames raise a typed WireError instead of decoding
# garbage. The first 16 bytes stay layout-compatible with BCS1: recv
# branches on the magic, so old BCS1 frames still parse (version-
# tolerant rolling upgrades between driver and executors).
MAGIC2 = b"BCS2"
_CRC_TAIL = struct.Struct("<I")
# largest accepted frame: a poisoned/corrupt length prefix must not make
# recv_msg attempt a multi-GiB allocation
MAX_FRAME = 1 << 31

# Network fault seam (faults.py net.* points). faults.install() points
# this at faults.net_rule when a spec arms any net.* point, and back to
# None on reset — a plain module global so this module stays import-
# light (no config/faults import at module load; worker processes never
# arm it because fault_injection_spec is stripped from their conf).
NET_HOOK = None


def net_rule(point: str):
    """Fire the driver-side net fault schedule for `point`; returns the
    armed rule dict (kind/ms/...) when this call should inject a wire
    fault, else None. Call sites pass the rule to send_msg/recv_msg via
    net_fault= so injection happens at the exact socket operation."""
    hook = NET_HOOK
    return hook(point) if hook is not None else None


class WireError(ConnectionError):
    """Framing violation (bad magic / oversized length / CRC mismatch):
    the peer is not speaking the protocol — callers treat it like a
    lost connection."""


def _apply_send_fault(sock: socket.socket, buf: bytes, rule: dict) -> bool:
    """Apply a fired net.* rule to an outgoing frame. Returns True when
    the frame was (ab)used by the fault and must not be sent again;
    raises for connection-fatal kinds."""
    kind = rule.get("kind")
    if kind == "delay":
        time.sleep(float(rule.get("ms", 25)) / 1000.0)
        return False
    if kind == "dup":
        sock.sendall(buf + buf)  # duplicate delivery: same frame twice
        return True
    if kind == "reset":
        raise ConnectionResetError("injected: connection reset by peer")
    if kind == "blackhole":
        # the peer sees nothing; the sender stalls then loses the conn
        time.sleep(float(rule.get("ms", 2000)) / 1000.0)
        raise ConnectionError("injected: blackhole (frame never sent)")
    if kind == "torn":
        sock.sendall(buf[: max(1, len(buf) // 2)])
        raise ConnectionResetError("injected: torn frame (partial write)")
    return False


def send_msg(sock: socket.socket, header: dict, blob: bytes = b"",
             lock: Optional[threading.Lock] = None,
             net_fault: Optional[dict] = None) -> None:
    """Serialize + frame one message; `lock` serializes concurrent
    senders sharing the socket (a torn frame is unrecoverable).
    `net_fault` is a pre-fired net.* rule (from net_rule) applied at
    the sendall boundary — wire-level chaos without monkeypatching."""
    raw = json.dumps(header, separators=(",", ":")).encode()
    comp = zlib.compress(raw, 1)
    crc = zlib.crc32(blob, zlib.crc32(comp)) & 0xFFFFFFFF
    buf = (_HEAD.pack(MAGIC2, len(raw), len(comp), len(blob))
           + _CRC_TAIL.pack(crc) + comp + blob)
    if lock is not None:
        with lock:
            if net_fault and _apply_send_fault(sock, buf, net_fault):
                return
            sock.sendall(buf)
    else:
        if net_fault and _apply_send_fault(sock, buf, net_fault):
            return
        sock.sendall(buf)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame"
                                  if chunks else "peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket,
             net_fault: Optional[dict] = None) -> Tuple[dict, bytes]:
    """Read one framed message; raises ConnectionError on EOF/short read
    and WireError on a malformed frame. Accepts both BCS1 (legacy, no
    checksum) and BCS2 (CRC32 over compressed header + blob) frames."""
    if net_fault:
        kind = net_fault.get("kind")
        if kind == "delay":
            time.sleep(float(net_fault.get("ms", 25)) / 1000.0)
        elif kind == "reset":
            raise ConnectionResetError("injected: connection reset on recv")
        elif kind == "blackhole":
            time.sleep(float(net_fault.get("ms", 2000)) / 1000.0)
            raise ConnectionError("injected: blackhole on recv")
        elif kind == "torn":
            raise WireError("injected: torn frame on recv")
        # "dup" is applied by callers that own the message loop (the
        # frame itself arrives once; duplication is a delivery property)
    head = _recv_exact(sock, _HEAD.size)
    magic, raw_len, comp_len, blob_len = _HEAD.unpack(head)
    if magic not in (MAGIC, MAGIC2):
        raise WireError(f"bad frame magic {magic!r}")
    if max(raw_len, comp_len, blob_len) > MAX_FRAME:
        raise WireError("frame length exceeds MAX_FRAME")
    want_crc = None
    if magic == MAGIC2:
        want_crc = _CRC_TAIL.unpack(_recv_exact(sock, _CRC_TAIL.size))[0]
    comp = _recv_exact(sock, comp_len)
    blob = _recv_exact(sock, blob_len) if blob_len else b""
    if want_crc is not None:
        got = zlib.crc32(blob, zlib.crc32(comp)) & 0xFFFFFFFF
        if got != want_crc:
            raise WireError(
                f"frame CRC mismatch (want {want_crc:#010x}, "
                f"got {got:#010x})")
    raw = zlib.decompress(comp)
    if len(raw) != raw_len:
        raise WireError("frame raw_len mismatch")
    return json.loads(raw.decode()), blob


def _read_segment(data_path: str, index_path: str, partition: int) -> bytes:
    """One map output's VERIFIED bytes for `partition`, located through
    the committed little-endian u64 offsets index (the FileSegment fetch
    of shuffle_manager.get_reader, without the decode). Delegates to
    artifacts.fetch_segment — checksum verification, quarantine and
    lineage repair happen server-side, where the repair closures live.
    The import is lazy to keep this module import-light (worker
    processes import it before deciding whether they need the engine;
    _read_segment only ever runs driver-side)."""
    from blaze_tpu.runtime import artifacts

    return artifacts.fetch_segment(data_path, index_path, partition)


class ShuffleServer:
    """Driver-side artifact server. `register_shuffle` publishes a
    completed stage's map outputs under its resource id;
    `register_frames` publishes a broadcast stage's frame list. Executors
    fetch with {"type": "fetch", "rid": ..., "partition": p} and get the
    concatenated serde frames back as the reply blob."""

    def __init__(self, sock_path: str) -> None:
        self.sock_path = sock_path
        self._lock = threading.Lock()
        # rid -> list of (data_path, index_path) map outputs
        self._shuffles: Dict[str, List[Tuple[str, str]]] = {}
        # rid -> broadcast frame list (already serde frames)
        self._frames: Dict[str, List[bytes]] = {}
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        self.fetches = 0
        # unclean client disconnects (mid-frame EOF, framing violation,
        # reply send failure) — partition chaos made observable server-
        # side; clean EOF between requests is a normal client close
        self.conns_dropped = 0

    # -- registry ------------------------------------------------------

    def register_shuffle(self, rid: str,
                         outputs: Sequence[Tuple[str, str]]) -> None:
        with self._lock:
            self._shuffles[rid] = list(outputs)

    def register_frames(self, rid: str, frames: Sequence[bytes]) -> None:
        with self._lock:
            self._frames[rid] = list(frames)

    def unregister(self, rid: str) -> None:
        with self._lock:
            self._shuffles.pop(rid, None)
            self._frames.pop(rid, None)

    def unregister_prefix(self, prefix: str) -> None:
        """Drop every rid of a finished query's namespace."""
        with self._lock:
            for reg in (self._shuffles, self._frames):
                for rid in [r for r in reg if r.startswith(prefix)]:
                    reg.pop(rid, None)

    def registered(self) -> List[str]:
        with self._lock:
            return sorted(self._shuffles) + sorted(self._frames)

    # -- serving -------------------------------------------------------

    def start(self) -> None:
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self.sock_path)
        listener.listen(64)
        self._listener = listener
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="blz-shufsrv", daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="blz-shufsrv-conn", daemon=True).start()

    def _conn_dropped(self, why: str) -> None:
        """Count + trace one unclean client disconnect. Lazy trace
        import (this only runs driver-side; the module must stay
        import-light for worker processes)."""
        with self._lock:
            self.conns_dropped += 1
        from blaze_tpu.runtime import trace

        trace.event("shuffle_conn_dropped", why=why)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                try:
                    msg, _blob = recv_msg(conn)
                except WireError as e:
                    self._conn_dropped(f"wire_error: {e}")
                    return
                except ConnectionError as e:
                    # clean EOF between requests is a normal client
                    # close; a mid-frame EOF is a dropped connection
                    if "mid-frame" in str(e):
                        self._conn_dropped("eof_mid_frame")
                    return
                if msg.get("type") == "locate":
                    # publish the committed artifact paths for a shuffle
                    # rid so a same-host client can mmap the .data files
                    # instead of streaming segments over the socket.
                    # Redirects are resolved HERE: quarantine/repair
                    # state lives in this (driver) process, so clients
                    # re-locating after a checksum fallback see the
                    # repaired pair, not the quarantined one.
                    rid = msg.get("rid", "")
                    echo = {k: msg[k] for k in ("req",) if k in msg}
                    with self._lock:
                        outputs = self._shuffles.get(rid)
                    if outputs is None:
                        # broadcast frame lists have no file backing;
                        # unknown rids are equally non-mappable
                        send_msg(conn, {"ok": False, "rid": rid,
                                        "error": f"not file-backed: {rid}",
                                        **echo})
                        continue
                    from blaze_tpu.runtime import artifacts

                    resolved = [list(artifacts.resolve_artifact(d, i))
                                for d, i in outputs]
                    send_msg(conn, {"ok": True, "rid": rid,
                                    "outputs": resolved, **echo})
                    continue
                if msg.get("type") != "fetch":
                    send_msg(conn, {"ok": False,
                                    "error": "unknown request type"})
                    continue
                rid = msg.get("rid", "")
                partition = int(msg.get("partition", 0))
                # echo the client's request id so it can discard stale
                # or duplicated replies (absent on old clients — the
                # reply then carries no "req" and is accepted as-is)
                echo = {k: msg[k] for k in ("req",) if k in msg}
                try:
                    blob = self._fetch(rid, partition)
                except Exception as e:  # noqa: BLE001 — relayed to peer
                    send_msg(conn, {"ok": False, "rid": rid,
                                    "error": f"{type(e).__name__}: {e}",
                                    **echo})
                    continue
                try:
                    send_msg(conn, {"ok": True, "rid": rid, **echo}, blob,
                             net_fault=net_rule("net.shuffle.fetch"))
                except (ConnectionError, OSError) as e:
                    self._conn_dropped(f"send_failed: {e}")
                    return
        finally:
            conn.close()

    def _fetch(self, rid: str, partition: int) -> bytes:
        with self._lock:
            outputs = self._shuffles.get(rid)
            frames = self._frames.get(rid)
            self.fetches += 1
        if outputs is not None:
            return b"".join(_read_segment(d, i, partition)
                            for d, i in outputs)
        if frames is not None:
            return b"".join(frames)
        raise KeyError(f"resource not served: {rid}")

    def close(self) -> None:
        self._closed.set()
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=1.0)
            self._accept_thread = None
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass


class ShuffleClient:
    """Executor-side fetch client: one connection, request/response under
    a lock (concurrent task slots in one worker share it)."""

    def __init__(self, sock_path: str) -> None:
        self.sock_path = sock_path
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        # monotone request id: replies echo it back so a duplicated or
        # stale reply (net.* dup chaos, a retry racing its first answer)
        # is discarded instead of being matched to the wrong request
        self._req = 0
        # rid -> same-host mmap fast-path state: a list of per-output
        # dicts (buf/offsets/frames/seen, see _map_one), or None caching
        # a negative answer (broadcast rid, legacy index without frame
        # checksums, paths not visible from this process)
        self._maps: Dict[str, Optional[List[dict]]] = {}

    @staticmethod
    def _timeout_ms() -> float:
        # lazy conf import: importing blaze_tpu.config initializes the
        # package (jax), which this module must not do at import time
        from blaze_tpu.config import conf

        return float(conf.shuffle_connect_timeout_ms)

    def _ensure_locked(self) -> socket.socket:
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            timeout_ms = self._timeout_ms()
            if timeout_ms > 0:
                # bounds connect AND every recv: a hung shuffle server
                # surfaces as socket.timeout (an OSError the retry
                # ladder absorbs) instead of blocking the task forever
                s.settimeout(timeout_ms / 1000.0)
            s.connect(self.sock_path)
            self._sock = s
        return self._sock

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _fetch_once_locked(self, rid: str,
                           partition: int) -> Tuple[dict, bytes]:
        sock = self._ensure_locked()
        self._req += 1
        req = self._req
        send_msg(sock, {"type": "fetch", "rid": rid,
                        "partition": partition, "req": req})
        while True:
            msg, blob = recv_msg(sock)
            got = msg.get("req")
            # accept replies without a req echo (old servers); discard
            # duplicated/stale replies for earlier request ids
            if got is None or got == req:
                return msg, blob
            if got > req:
                raise WireError(f"reply for future request {got} > {req}")

    def fetch(self, rid: str, partition: int) -> bytes:
        """Fetch one partition segment, retrying lost/hung connections
        on a bounded exponential-backoff ladder: the whole ladder (and
        each socket read) fits inside conf.shuffle_connect_timeout_ms,
        so a hung or restarting shuffle server costs a bounded wait,
        never a wedged task. 0 restores the legacy posture — blocking
        socket, one reconnect."""
        timeout_ms = self._timeout_ms()
        with self._lock:
            if timeout_ms <= 0:
                try:
                    msg, blob = self._fetch_once_locked(rid, partition)
                except (ConnectionError, OSError):
                    # one reconnect: the driver may have restarted the
                    # listener; a second failure is the caller's problem
                    self._close_locked()
                    msg, blob = self._fetch_once_locked(rid, partition)
            else:
                deadline = time.monotonic() + timeout_ms / 1000.0
                delay = 0.01
                attempt = 0
                while True:
                    try:
                        msg, blob = self._fetch_once_locked(rid, partition)
                        break
                    except (ConnectionError, OSError) as e:
                        self._close_locked()
                        attempt += 1
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise ConnectionError(
                                f"shuffle fetch {rid}[{partition}] "
                                f"failed after {attempt} attempts "
                                f"within {int(timeout_ms)}ms: {e}"
                            ) from e
                        time.sleep(min(delay, remaining))
                        delay = min(delay * 2.0, 0.5)
        if not msg.get("ok"):
            raise KeyError(msg.get("error", f"fetch failed: {rid}"))
        return blob

    # -- same-host mmap fast path -------------------------------------

    def _locate_locked(self, rid: str) -> Optional[List[Tuple[str, str]]]:
        """Ask the server for rid's committed (data, index) paths.
        None when the rid is not file-backed (broadcast frame list) or
        the server predates the locate message (it replies ok=False
        "unknown request type" without a req echo — accepted here the
        same way fetch accepts echo-less replies from old servers)."""
        sock = self._ensure_locked()
        self._req += 1
        req = self._req
        send_msg(sock, {"type": "locate", "rid": rid, "req": req})
        while True:
            msg, _blob = recv_msg(sock)
            got = msg.get("req")
            if got is None or got == req:
                break
            if got > req:
                raise WireError(f"reply for future request {got} > {req}")
        if not msg.get("ok"):
            return None
        return [(str(d), str(i)) for d, i in msg.get("outputs") or []]

    @staticmethod
    def _map_one(data_path: str, index_path: str) -> Optional[dict]:
        """mmap one committed output read-only. None when the pair is
        not visible from this process or the index carries no per-frame
        checksums (legacy commit): lazy verification is then impossible
        and the socket path — which verifies whole segments server-side
        — stays authoritative."""
        import mmap as _mmap

        from blaze_tpu.runtime import artifacts

        if not (os.path.exists(data_path) and os.path.exists(index_path)):
            return None
        offsets_bytes, meta = artifacts.read_index(index_path)
        if not meta or not meta.get("frames"):
            return None
        n = len(offsets_bytes) // 8
        offsets = struct.unpack("<%dQ" % n, offsets_bytes[: 8 * n])
        with open(data_path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            buf = (_mmap.mmap(f.fileno(), 0, prot=_mmap.PROT_READ)
                   if size else b"")
        return {"buf": buf, "offsets": offsets,
                "frames": dict(meta["frames"]), "seen": set()}

    @staticmethod
    def _slice_frames(state: dict,
                      partition: int) -> Optional[List[memoryview]]:
        """Zero-copy frame views for one partition of a mapped output,
        verifying each frame's committed CRC32 on FIRST touch only
        (`seen` remembers verified frame offsets). None on any
        discrepancy — truncated mapping, unindexed frame boundary,
        checksum mismatch — so the caller falls back to the socket path
        where fetch_segment quarantines + lineage-repairs the pair."""
        offsets = state["offsets"]
        if partition + 1 >= len(offsets):
            return None
        lo, hi = offsets[partition], offsets[partition + 1]
        buf = state["buf"]
        if hi > len(buf) or lo > hi:
            return None
        view = memoryview(buf)
        frames: List[memoryview] = []
        off = lo
        while off < hi:
            if off + 12 > hi:
                return None
            (comp_len,) = struct.unpack_from("<I", buf, off + 8)
            end = off + 12 + comp_len
            if end > hi:
                return None
            if off not in state["seen"]:
                want = state["frames"].get(off)
                if want is None:
                    return None
                if zlib.crc32(view[off:end]) & 0xFFFFFFFF != want:
                    return None
                state["seen"].add(off)
            frames.append(view[off:end])
            off = end
        return frames

    def _mmap_fetch(self, rid: str, partition: int):
        """Returns (frames, nbytes, status) with status one of "hit"
        (zero-copy views returned), "miss" (rid is not mmap-eligible —
        broadcast, legacy index, remote paths; cached so later fetches
        skip the locate round-trip), "fallback" (mapping was live but
        verification failed: the cache is dropped so the next fetch
        re-locates, picking up any repaired redirect)."""
        with self._lock:
            if rid not in self._maps:
                outputs = self._locate_locked(rid)
                if outputs is None:
                    self._maps[rid] = None
                    return None, 0, "miss"
                states: Optional[List[dict]] = []
                for d, i in outputs:
                    st = self._map_one(d, i)
                    if st is None:
                        states = None
                        break
                    states.append(st)
                self._maps[rid] = states
                if states is None:
                    return None, 0, "fallback"
            states = self._maps[rid]
            if states is None:
                return None, 0, "miss"
            frames: List[memoryview] = []
            nbytes = 0
            for st in states:
                part = self._slice_frames(st, partition)
                if part is None:
                    self._maps.pop(rid, None)
                    return None, 0, "fallback"
                frames.extend(part)
                nbytes += sum(len(f) for f in part)
            return frames, nbytes, "hit"

    def fetch_frames(self, rid: str, partition: int) -> List:
        """One partition's serde frames (memoryview on the mmap path,
        bytes on the socket path), preferring the same-host
        zero-copy path: when the server's committed .data/.index pair is
        visible from this process, the data file is mmap'd read-only and
        partition segments come back as memoryview slices — no socket
        streaming, no blob copy — with per-frame CRC32s verified lazily
        on first touch. Any discrepancy falls back to the socket fetch,
        whose server-side fetch_segment runs the existing quarantine +
        lineage-repair protocol; a later fetch_frames re-locates and
        maps the repaired pair. Bookkeeping is single-entry per logical
        transfer: a mmap hit books moved bytes only (nothing was
        copied), the socket path books copied bytes reader-side."""
        from blaze_tpu.config import conf

        status = "miss"
        if conf.shuffle_mmap_enabled:
            try:
                frames, nbytes, status = self._mmap_fetch(rid, partition)
            except (ConnectionError, OSError, ValueError, struct.error):
                # locate/map plumbing failure: the socket retry ladder
                # below owns reconnection; treat as a fallback
                frames, status = None, "fallback"
                self._drop_maps(rid)
            if frames is not None:
                from blaze_tpu.runtime import monitor

                if conf.monitor_enabled:
                    monitor.count_move("shuffle", nbytes)
                    monitor.count_zerocopy("shuffle_mmap_hits")
                if conf.trace_enabled:
                    from blaze_tpu.runtime import trace

                    trace.event("shuffle_mmap_fetch", rid=rid,
                                partition=partition, nbytes=nbytes,
                                frames=len(frames))
                return frames
        blob = self.fetch(rid, partition)
        from blaze_tpu.runtime import monitor

        if conf.monitor_enabled:
            monitor.count_copy("shuffle", len(blob))
            if status == "fallback":
                monitor.count_zerocopy("shuffle_mmap_fallbacks")
        return split_frames(blob)

    def _drop_maps(self, rid: Optional[str] = None) -> None:
        with self._lock:
            if rid is None:
                self._maps.clear()
            else:
                self._maps.pop(rid, None)

    def close(self) -> None:
        with self._lock:
            self._close_locked()
            self._maps.clear()


def split_frames(blob: bytes) -> List[bytes]:
    """Split a fetched segment into its serde "BTB1" frames (layout:
    columnar/serde.py — u32 magic | u32 raw_len | u32 comp_len | body).
    IpcReaderExec decodes raw frame bytes itself, so executors never need
    the serde module just to route segments."""
    frames: List[bytes] = []
    off = 0
    total = len(blob)
    while off < total:
        if off + 12 > total:
            raise WireError("truncated shuffle frame header")
        _raw_len, comp_len = struct.unpack_from("<II", blob, off + 4)
        end = off + 12 + comp_len
        if end > total:
            raise WireError("truncated shuffle frame body")
        frames.append(blob[off:end])
        off = end
    return frames
