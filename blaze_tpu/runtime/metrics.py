"""Per-operator metrics tree.

Ref: DataFusion MetricsSet per operator + the JVM MetricNode tree walked in
lockstep on finalize (blaze/src/metrics.rs:21-50, MetricNode.scala:21-34).
Same shape here: every operator owns a `MetricsSet`; `MetricNode` mirrors the
plan tree and carries an optional value handler so an embedding layer (JVM
bridge) can remap values into Spark's metric system.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class MetricsSet:
    def __init__(self) -> None:
        self.values: Dict[str, int] = {
            "output_rows": 0,
            "output_batches": 0,
            "elapsed_compute_ns": 0,
        }
        self._lock = threading.Lock()

    def add(self, name: str, delta: int) -> None:
        # locked: an operator's MetricsSet (and the process-global
        # resilience TELEMETRY) is updated from every supervisor pool
        # thread; an unlocked read-modify-write would lose counts
        with self._lock:
            self.values[name] = self.values.get(name, 0) + int(delta)

    def set_max(self, name: str, value: int) -> None:
        """Max-semantics update (a read-then-add emulation would produce
        impossible values when concurrent tasks interleave); per-instance
        lock so different operators' metrics never contend."""
        with self._lock:
            if int(value) > self.values.get(name, 0):
                self.values[name] = int(value)

    def timer(self, name: str = "elapsed_compute_ns"):
        return _Timer(self, name)

    def snapshot(self) -> Dict[str, int]:
        """Point-in-time copy of the counters; compile-service task
        scopes diff two snapshots to attribute process-global deltas
        (compile_count/compile_ns/...) to one task's MetricsSet."""
        return dict(self.values)

    def __getitem__(self, name: str) -> int:
        return self.values.get(name, 0)


class _Timer:
    def __init__(self, ms: MetricsSet, name: str) -> None:
        self.ms, self.name = ms, name

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self.ms.add(self.name, time.perf_counter_ns() - self.t0)
        return False


class MetricNode:
    """Mirror of the plan tree for metric export (ref MetricNode.scala)."""

    def __init__(self, metrics: MetricsSet, children: List["MetricNode"],
                 handler: Optional[Callable[[str, int], None]] = None) -> None:
        self.metrics = metrics
        self.children = children
        self.handler = handler

    def push(self) -> None:
        """Walk the tree pushing values through handlers (task finalize)."""
        if self.handler is not None:
            for k, v in self.metrics.values.items():
                self.handler(k, v)
        for c in self.children:
            c.push()

    @staticmethod
    def from_operator(op) -> "MetricNode":
        return MetricNode(op.metrics, [MetricNode.from_operator(c) for c in op.children])
