"""Per-operator metrics tree.

Ref: DataFusion MetricsSet per operator + the JVM MetricNode tree walked in
lockstep on finalize (blaze/src/metrics.rs:21-50, MetricNode.scala:21-34).
Same shape here: every operator owns a `MetricsSet`; `MetricNode` mirrors the
plan tree and carries an optional value handler so an embedding layer (JVM
bridge) can remap values into Spark's metric system.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


class MetricsSet:
    def __init__(self) -> None:
        self.values: Dict[str, int] = {
            "output_rows": 0,
            "output_batches": 0,
            "elapsed_compute_ns": 0,
        }
        self._lock = threading.Lock()

    def add(self, name: str, delta: int) -> None:
        # locked: an operator's MetricsSet (and the process-global
        # resilience TELEMETRY) is updated from every supervisor pool
        # thread; an unlocked read-modify-write would lose counts
        with self._lock:
            self.values[name] = self.values.get(name, 0) + int(delta)

    def set_max(self, name: str, value: int) -> None:
        """Max-semantics update (a read-then-add emulation would produce
        impossible values when concurrent tasks interleave); per-instance
        lock so different operators' metrics never contend."""
        with self._lock:
            if int(value) > self.values.get(name, 0):
                self.values[name] = int(value)

    def timer(self, name: str = "elapsed_compute_ns"):
        return _Timer(self, name)

    def snapshot(self) -> Dict[str, int]:
        """Point-in-time copy of the counters; compile-service task
        scopes diff two snapshots to attribute process-global deltas
        (compile_count/compile_ns/...) to one task's MetricsSet.

        Taken under the lock: readers (MetricNode.push, metric_report,
        the telemetry summaries) iterate this copy while supervisor pool
        threads keep mutating the live dict — iterating `values` raw
        raises RuntimeError("dict changed size during iteration")."""
        with self._lock:
            return dict(self.values)

    def reset(self) -> None:
        """Clear every counter under the lock. A bare `values.clear()`
        racing a pool-thread `add` can resurrect a stale key (the adder
        read-modify-writes outside the clear's view); resets must take
        the same lock the adders do."""
        with self._lock:
            self.values.clear()

    def __getitem__(self, name: str) -> int:
        with self._lock:
            return self.values.get(name, 0)


class _Timer:
    def __init__(self, ms: MetricsSet, name: str) -> None:
        self.ms, self.name = ms, name

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self.ms.add(self.name, time.perf_counter_ns() - self.t0)
        return False


class MetricNode:
    """Mirror of the plan tree for metric export (ref MetricNode.scala)."""

    def __init__(self, metrics: MetricsSet, children: List["MetricNode"],
                 handler: Optional[Callable[[str, int], None]] = None) -> None:
        self.metrics = metrics
        self.children = children
        self.handler = handler

    def push(self) -> None:
        """Walk the tree pushing values through handlers (task finalize).

        Iterates a locked snapshot: finalize can overlap live supervisor
        pool threads still adding counters (a speculative twin draining,
        the telemetry nodes executor.metric_tree appends)."""
        if self.handler is not None:
            for k, v in self.metrics.snapshot().items():
                self.handler(k, v)
        for c in self.children:
            c.push()

    @staticmethod
    def from_operator(op) -> "MetricNode":
        return MetricNode(op.metrics, [MetricNode.from_operator(c) for c in op.children])


class Histogram:
    """Fixed-bucket log2 latency/size histogram (lock-protected, mergeable).

    Bucket i counts values v with 2^(i-1) <= v < 2^i (bucket 0 takes
    v <= 0, bucket 1 takes v == 1); 64 buckets cover the full non-negative
    int64 range, so recording never allocates and two histograms merge by
    summing counts — the same fixed-layout trick HdrHistogram-style
    recorders use so per-task histograms can fold into a per-query one.

    Percentiles are bucket-resolution estimates: `percentile(p)` returns
    the upper bound of the bucket holding the p-th value (clamped to the
    observed max), which is exact within a factor of 2 — enough for the
    trace ledger's p50/p95/p99 trend lines (runtime/trace.py)."""

    N_BUCKETS = 64

    def __init__(self, name: str = "") -> None:
        self.name = name
        self._lock = threading.Lock()
        self.counts = [0] * self.N_BUCKETS
        self.count = 0
        self.total = 0
        self.vmin: Optional[int] = None
        self.vmax: Optional[int] = None

    @staticmethod
    def bucket_index(value: int) -> int:
        v = int(value)
        if v <= 0:
            return 0
        return min(v.bit_length(), Histogram.N_BUCKETS - 1)

    @staticmethod
    def bucket_upper_bound(index: int) -> int:
        """Exclusive upper bound of bucket `index` (1 for bucket 0)."""
        return 1 << max(index, 0)

    def record(self, value: int) -> None:
        v = int(value)
        i = self.bucket_index(v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.total += v
            if self.vmin is None or v < self.vmin:
                self.vmin = v
            if self.vmax is None or v > self.vmax:
                self.vmax = v

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold `other` into self (same fixed layout, so a plain sum)."""
        o = other.snapshot()
        with self._lock:
            for i, n in enumerate(o["counts"]):
                self.counts[i] += n
            self.count += o["count"]
            self.total += o["total"]
            if o["min"] is not None:
                self.vmin = o["min"] if self.vmin is None \
                    else min(self.vmin, o["min"])
            if o["max"] is not None:
                self.vmax = o["max"] if self.vmax is None \
                    else max(self.vmax, o["max"])
        return self

    def percentile(self, p: float) -> Optional[int]:
        """Upper bound of the bucket holding the p-th percentile value,
        clamped to the observed max (None when empty)."""
        with self._lock:
            if not self.count:
                return None
            rank = max(1, -(-int(self.count * p) // 100))  # ceil
            seen = 0
            for i, n in enumerate(self.counts):
                seen += n
                if seen >= rank:
                    return min(self.bucket_upper_bound(i), self.vmax)
            return self.vmax

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            nonzero: List[Tuple[int, int]] = [
                (i, n) for i, n in enumerate(self.counts) if n]
            return {
                "name": self.name, "count": self.count, "total": self.total,
                "min": self.vmin, "max": self.vmax,
                "mean": (self.total / self.count) if self.count else None,
                "counts": list(self.counts),
                "buckets": {f"<{self.bucket_upper_bound(i)}": n
                            for i, n in nonzero},
            }

    def summary(self) -> str:
        """One-line 'n= p50= p95= p99= max=' rendering ('' when empty)."""
        snap = self.snapshot()
        if not snap["count"]:
            return ""
        return (f"{self.name}: n={snap['count']} p50={self.percentile(50)} "
                f"p95={self.percentile(95)} p99={self.percentile(99)} "
                f"max={snap['max']}")
