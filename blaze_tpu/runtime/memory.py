"""Memory manager: budgeted consumers with fair-share spilling.

Ref: datafusion-ext-plans common/memory_manager.rs — a global registry of
MemConsumers (sort, agg tables, repartitioners); over-budget growing
consumers either spill themselves (when holding > 1/8 of their fair share)
or ask others to free memory (:194-323, 16MB min trigger :26) — and the
spill sink of common/onheap_spill.rs (JVM-heap pages on executors, tempfiles
on the driver / in tests :26-75).

TPU translation (SURVEY.md §5.2): the budget models HBM for device-resident
operator state; "spilling" moves batches to host files in the compact zstd
frame format (columnar/serde.py — same format as the reference's spill
serde). Execution here is single-threaded per task, so the condvar wait
protocol degenerates: an over-budget update first asks the LARGEST other
consumer to spill, then self-spills (mirroring the fair-share decision
without the blocking path).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
import weakref
import zlib
from typing import BinaryIO, Iterator, List, Optional

from blaze_tpu.columnar import serde
from blaze_tpu.columnar.batch import ColumnBatch
from blaze_tpu.columnar.types import Schema
from blaze_tpu.config import conf
from blaze_tpu.runtime import monitor, trace

class MemConsumer:
    """Spillable operator state (ref MemConsumer trait)."""

    name: str = "consumer"

    def mem_used(self) -> int:
        return 0

    def spill(self) -> int:
        """Release memory; returns bytes freed."""
        return 0


class MemManager:
    def __init__(self, total: Optional[int] = None) -> None:
        self.total = total or conf.memory_budget or (1 << 30)
        self._consumers: List[MemConsumer] = []
        self._lock = threading.Lock()
        # serializes consumer-STATE mutation against host-driven spills
        # (bn_spill runs on a host thread while a task thread may be
        # mid-add on the same consumer): consumers hold it while adding
        # state, release() holds it while spilling. RLock so a task
        # thread's own add -> update_mem_used -> spill chain re-enters.
        self.op_lock = threading.RLock()
        self.spill_count = 0
        self.spilled_bytes = 0
        # host spill pages (SpillFile frames buffered but not yet synced
        # to disk) tracked SEPARATELY from _consumers: they count toward
        # the budget but must not join the fair_share() denominator —
        # a spill file is a sink, not a spillable consumer. Weak refs so
        # tracking never keeps a dropped file (and its tempfile) alive.
        self._spill_files: List[weakref.ref] = []
        self.host_spill_bytes = 0
        self.host_spill_files = 0
        # bytes held by in-flight pipelined batches (runtime/pipeline.py)
        # between production on an I/O pool thread and consumption. Like
        # spill pages, these count toward the budget but are NOT a
        # MemConsumer: they cannot be spilled (the consumer is about to
        # use them), so joining the registry would stall the
        # update_mem_used spill-selection loop on an unspillable
        # "largest consumer". Over-budget pipelines stop producing
        # instead (backpressure in PrefetchStream._over_budget_locked).
        self.pipeline_reserved = 0
        # high-water mark of mem_used(): observed at every consumer
        # growth (update_mem_used) and by the monitor sampler; reset at
        # query start so per-query roll-ups report peak_mem_bytes
        self.peak_used = 0
        # -- multi-tenant quota ledger (runtime/service.py) --
        # conf.tenant_quota_spec carves per-tenant ceilings out of the
        # budget; consumers and pipeline reservations are tagged with the
        # registering thread's tenant (trace context). Empty quotas =
        # single-tenant fast path: one dict-emptiness check per update.
        self._quotas: dict = {}
        self._tenant_of: dict = {}          # id(consumer) -> tenant id
        self._tenant_pipeline: dict = {}    # tenant id -> reserved bytes

    # -- registry --
    def register(self, consumer: MemConsumer) -> None:
        tid = trace.current_context().get("tenant_id", "")
        with self._lock:
            self._consumers.append(consumer)
            if tid:
                self._tenant_of[id(consumer)] = tid

    def unregister(self, consumer: MemConsumer) -> None:
        with self._lock:
            if consumer in self._consumers:
                self._consumers.remove(consumer)
            self._tenant_of.pop(id(consumer), None)

    def track_spill(self, sf: "SpillFile") -> None:
        with self._lock:
            self._spill_files.append(weakref.ref(sf))
        self.host_spill_files += 1

    def untrack_spill(self, sf: "SpillFile") -> None:
        with self._lock:
            self._spill_files = [r for r in self._spill_files
                                 if r() is not None and r() is not sf]

    def _live_spill_files(self) -> List["SpillFile"]:
        with self._lock:
            live = [(r, r()) for r in self._spill_files]
            self._spill_files = [r for r, sf in live if sf is not None]
            return [sf for _, sf in live if sf is not None]

    def _consumers_snapshot(self) -> List[MemConsumer]:
        # registry snapshot: supervisor pool threads register/unregister
        # concurrently with accounting walks over the list
        with self._lock:
            return list(self._consumers)

    # -- accounting --
    def mem_used(self) -> int:
        consumed = sum(c.mem_used() for c in self._consumers_snapshot())
        with self._lock:
            reserved = self.pipeline_reserved
        return consumed + self.spill_pages_pending() + reserved

    def observe_peak(self) -> int:
        """mem_used() with high-water-mark tracking. NOT called from
        paths holding self._lock (mem_used walks the registry under it)."""
        used = self.mem_used()
        if used > self.peak_used:
            self.peak_used = used
        return used

    def reset_peak(self) -> None:
        self.peak_used = 0

    def reserve_pipeline(self, nbytes: int) -> None:
        """Charge an in-flight pipelined batch against the budget (and
        the reserving thread's tenant ledger when quotas are active)."""
        with self._lock:
            self.pipeline_reserved += int(nbytes)
            if self._quotas:
                tid = trace.current_context().get("tenant_id", "")
                if tid:
                    self._tenant_pipeline[tid] = \
                        self._tenant_pipeline.get(tid, 0) + int(nbytes)

    def release_pipeline(self, nbytes: int) -> None:
        with self._lock:
            self.pipeline_reserved -= int(nbytes)
            if self._quotas:
                tid = trace.current_context().get("tenant_id", "")
                if tid and tid in self._tenant_pipeline:
                    self._tenant_pipeline[tid] -= int(nbytes)

    def spill_pages_pending(self) -> int:
        """Bytes written to tracked spill files but not yet synced to
        disk — host buffer pages the budget must account for."""
        return sum(sf.pending_bytes for sf in self._live_spill_files())

    def flush_spill_pages(self) -> int:
        """Sync every tracked spill file's buffered frames to disk;
        returns the pending bytes released back to the budget."""
        freed = 0
        for sf in self._live_spill_files():
            freed += sf.flush_pages()
        if freed > 0:
            trace.event("spill_pages_flush", freed_bytes=freed)
        return freed

    def fair_share(self) -> int:
        with self._lock:
            n = max(len(self._consumers), 1)
        return self.total // n

    # -- tenant quotas --
    def set_tenant_quotas(self, spec: Optional[dict]) -> None:
        """Install per-tenant ceilings from conf.tenant_quota_spec: int
        values are bytes, floats in (0, 1] are fractions of the budget.
        None/{} clears quotas (single-tenant fast path)."""
        quotas: dict = {}
        for tid, v in (spec or {}).items():
            if isinstance(v, float) and 0 < v <= 1:
                quotas[tid] = int(self.total * v)
            else:
                quotas[tid] = int(v)
        with self._lock:
            self._quotas = quotas
            self._tenant_pipeline = {}

    def tenant_quota(self, tenant: str) -> Optional[int]:
        with self._lock:
            return self._quotas.get(tenant)

    def _tenant_consumers(self, tenant: str) -> List[MemConsumer]:
        with self._lock:
            return [c for c in self._consumers
                    if self._tenant_of.get(id(c), "") == tenant]

    def tenant_used(self, tenant: str) -> int:
        used = sum(c.mem_used() for c in self._tenant_consumers(tenant))
        with self._lock:
            return used + self._tenant_pipeline.get(tenant, 0)

    def tenant_usage(self) -> dict:
        """{tenant: bytes in use} over every tenant with tagged state or
        a declared quota — the Prometheus per-tenant gauge source."""
        with self._lock:
            tids = set(self._quotas) | set(self._tenant_of.values()) \
                | set(self._tenant_pipeline)
        return {tid: self.tenant_used(tid) for tid in sorted(tids)}

    def update_mem_used(self, updater: MemConsumer) -> None:
        """Called by a consumer after growing; triggers spills if needed.

        Decision mirrors memory_manager.rs:236-323: over budget, a grower
        holding more than 1/8 of its fair share self-spills, otherwise the
        largest other consumer is asked first (the reference's 16MB
        min-trigger floor is intentionally not applied — tiny budgets must
        force spills, which its own fuzztests also rely on).
        """
        used = self.observe_peak()
        with self._lock:
            tenant = (self._tenant_of.get(id(updater), "")
                      if self._quotas else "")
            quota = self._quotas.get(tenant)
        if quota:
            # quota enforcement BEFORE the global check: an over-quota
            # tenant sheds its OWN working set (grower first, then its
            # largest same-tenant sibling) — it can never reach across
            # and evict another tenant's state
            t_over = self.tenant_used(tenant) - quota
            if t_over > 0:
                trace.event("tenant_over_quota", tenant_id=tenant,
                            over_bytes=t_over, quota_bytes=quota)
                freed = updater.spill()
                self._note_spill(freed)
                t_over -= freed
                while t_over > 0:
                    sibs = sorted(
                        (c for c in self._tenant_consumers(tenant)
                         if c is not updater and c.mem_used() > 0),
                        key=lambda c: -c.mem_used())
                    if not sibs:
                        break
                    freed = sibs[0].spill()
                    self._note_spill(freed)
                    if freed <= 0:
                        break
                    t_over -= freed
                used = self.mem_used()
        if used <= self.total:
            return
        # cheapest reclaim first: sync buffered spill pages to disk —
        # accounting then matches the consumer-only view, so consumer
        # spill decisions are unchanged when no pages were pending
        used -= self.flush_spill_pages()
        if used <= self.total:
            return
        over = used - self.total
        share = self.fair_share()
        if updater.mem_used() > share // 8:
            freed = updater.spill()
            self._note_spill(freed)
            over -= freed
        while over > 0:
            # with quotas active the grower's spill pressure stays inside
            # its own tenant while that tenant still has spillable state;
            # cross-tenant eviction is the last resort before OOM
            others = sorted((c for c in self._consumers_snapshot()
                             if c is not updater and c.mem_used() > 0),
                            key=lambda c: -c.mem_used())
            if tenant:
                with self._lock:
                    same = [c for c in others
                            if self._tenant_of.get(id(c), "") == tenant]
                if same:
                    others = same
            if not others:
                if updater.mem_used() > 0:
                    freed = updater.spill()
                    self._note_spill(freed)
                    if freed <= 0:
                        break
                    over -= freed
                    continue
                break
            freed = others[0].spill()
            self._note_spill(freed)
            if freed <= 0:
                break
            over -= freed

    def _note_spill(self, freed: int) -> None:
        if freed > 0:
            self.spill_count += 1
            self.spilled_bytes += freed
            trace.event("spill", spill_bytes=freed)

    def release(self, bytes_needed: int,
                tenant: Optional[str] = None) -> int:
        """Host-driven reclamation (ref OnHeapSpillManager.scala:61-144:
        Spark's memory manager can force executor spill state to disk
        under heap pressure; the C ABI exposes this as bn_spill so the
        embedding layer can reclaim without killing the task). Spills
        the largest consumers first until `bytes_needed` is freed; a
        consumer that yields nothing is skipped, not a stop condition
        (smaller spillable consumers behind it must still drain).
        `tenant` scopes the sweep to one tenant's consumers — the
        degradation ladder's force-spill rung passes the failing query's
        tenant so its recovery can't evict other tenants' working sets.
        Returns bytes actually freed."""
        freed = 0
        with self.op_lock:
            with self._lock:
                candidates = sorted(
                    (c for c in self._consumers
                     if not tenant
                     or self._tenant_of.get(id(c), "") == tenant),
                    key=lambda c: -c.mem_used())
            for c in candidates:
                if freed >= bytes_needed:
                    break
                if c.mem_used() <= 0:
                    continue
                got = c.spill()
                self._note_spill(got)
                if got > 0:
                    freed += got
            if freed < bytes_needed:
                freed += self.flush_spill_pages()
        trace.event("mem_release", requested_bytes=bytes_needed,
                    freed_bytes=freed)
        return freed


_global = MemManager()


def get_manager(ctx=None) -> MemManager:
    if ctx is not None and getattr(ctx, "mem_manager", None) is not None:
        return ctx.mem_manager
    return _global


def init(total: int) -> MemManager:
    """Ref: MemManager::init(overhead x memoryFraction), exec.rs:68-71."""
    global _global
    _global = MemManager(total)
    return _global


def close_all_quietly(closeables, what: str) -> None:
    """Close every item best-effort. Cleanup paths run during exception
    unwinding (§5.3 double-fault contract): one failing close must
    neither mask the original query error nor stop the remaining
    closes — failures are logged and swallowed."""
    import logging

    for c in closeables:
        try:
            c.close()
        except Exception:  # noqa: BLE001 — see contract above
            logging.getLogger(__name__).warning(
                "%s close failed during cleanup", what, exc_info=True)


class SpillFile:
    """A sequence of serialized batches in a host tempfile (ref FileSpill,
    onheap_spill.rs:26-75; format = the zstd batch frames)."""

    def __init__(self, schema: Schema, dir: Optional[str] = None,
                 manager: Optional[MemManager] = None) -> None:
        self.schema = schema
        d = dir or conf.spill_dir
        os.makedirs(d, exist_ok=True)
        # pid-tagged name: runtime/artifacts.sweep_orphans reclaims
        # spill files whose owning process died mid-task
        fd, self.path = tempfile.mkstemp(
            prefix=f"blz{os.getpid()}-", suffix=".spill", dir=d)
        self._fp: Optional[BinaryIO] = os.fdopen(fd, "w+b")
        self.bytes_written = 0
        self.num_batches = 0
        # frames written but not yet synced to disk: host buffer pages
        # that count against the owning manager's budget
        self.pending_bytes = 0
        # per-frame (offset, crc32) recorded at write time: a spill
        # never outlives its process, so the checksums live here rather
        # than in a footer; read()/read_host() verify the file against
        # them before a single frame decodes
        self._frame_crcs: list = []
        self._quarantined: list = []
        self._manager = manager
        if manager is not None:
            manager.track_spill(self)

    def write(self, batch: ColumnBatch) -> int:
        from blaze_tpu.runtime import faults

        t0 = time.perf_counter_ns()
        if conf.fault_injection_spec:
            faults.inject("spill.write")
        t1 = time.perf_counter_ns()
        # serialize outside the spill window (it bills serde_encode);
        # the spill term is the injected stall + the file write itself
        buf = serde.serialize_batch(batch)
        t2 = time.perf_counter_ns()
        if conf.artifact_checksums:
            self._frame_crcs.append((self.bytes_written, zlib.crc32(buf)))
        self._fp.write(buf)
        n = len(buf)
        self.bytes_written += n
        self.num_batches += 1
        self.pending_bytes += n
        if self._manager is not None:
            self._manager.host_spill_bytes += n
        if conf.monitor_enabled:
            monitor.count_copy("spill", n)
            monitor.count_time("spill", (t1 - t0) +
                               (time.perf_counter_ns() - t2))
        return n

    def flush_pages(self) -> int:
        """Sync buffered frames to disk; returns pending bytes released."""
        freed = self.pending_bytes
        if self._fp is not None and freed:
            self._fp.flush()
            os.fsync(self._fp.fileno())
        self.pending_bytes = 0
        return freed

    def _verify_frames(self) -> None:
        """Re-read verification against the write-time frame crcs (the
        spill never outlives the process, so in-memory checksums are the
        whole-file digest). A mismatch quarantines the file and raises
        CorruptArtifactError — retryable: the task's retry rebuilds its
        spill from the input stream, there is no lineage to repair."""
        from blaze_tpu.runtime import artifacts, faults

        if not conf.artifact_checksums:
            return
        faults.maybe_corrupt("corrupt.spill", self.path)
        self._fp.seek(0)
        try:
            frames, _crc = artifacts.walk_frames(self._fp)
            ok = frames == self._frame_crcs
        except ValueError:
            ok = False
        if not ok:
            qpath = artifacts.note_corruption(
                self.path, "spill frame checksum mismatch")
            if qpath:
                self._quarantined.append(qpath)
            raise faults.CorruptArtifactError(
                f"spill checksum mismatch in {self.path} (quarantined)")

    def read(self) -> Iterator[ColumnBatch]:
        from blaze_tpu.runtime import faults, pipeline

        t0 = time.perf_counter_ns()
        if conf.fault_injection_spec:
            faults.inject("spill.read")
        self.flush_pages()
        self._verify_frames()
        self._fp.seek(0)
        if conf.monitor_enabled:
            # the whole file is about to be re-read; counted up front
            # (the lazy prefetch below consumes every frame). The frame
            # reads themselves bill serde_decode; spill gets the fsync.
            monitor.count_copy("spill", self.bytes_written)
            monitor.count_time("spill", time.perf_counter_ns() - t0)
        # read+decompress frames ahead on the I/O pool; the k-way merge
        # consumer interleaves many runs, and each run's readahead is
        # charged against the budget so merges can't silently re-inflate
        # the memory the spill was supposed to shed
        return pipeline.prefetch(serde.read_batches(self._fp, self.schema),
                                 manager=self._manager, name="spill_read")

    def read_host(self):
        """Frames as host numpy batches (serde.HostBatch) — the spill
        merge consumes runs host-side (ops/host_sort.py)."""
        from blaze_tpu.runtime import faults, pipeline

        t0 = time.perf_counter_ns()
        if conf.fault_injection_spec:
            faults.inject("spill.read")
        self.flush_pages()
        self._verify_frames()
        self._fp.seek(0)
        if conf.monitor_enabled:
            monitor.count_copy("spill", self.bytes_written)
            monitor.count_time("spill", time.perf_counter_ns() - t0)
        return pipeline.prefetch(
            serde.read_batches_host(self._fp, self.schema),
            manager=self._manager, name="spill_read")

    def close(self) -> None:
        if self._fp is not None:
            self._fp.close()
            self._fp = None
            self.pending_bytes = 0
            if self._manager is not None:
                self._manager.untrack_spill(self)
            try:
                os.unlink(self.path)
            except OSError:
                pass
            # a quarantined spill is ephemeral evidence: the retry that
            # follows rebuilds the data, so closing reclaims it (a
            # shuffle pair's quarantine, by contrast, is kept)
            for q in self._quarantined:
                try:
                    os.unlink(q)
                except OSError:
                    pass
            self._quarantined = []

    def __del__(self):
        self.close()


def batch_nbytes(batch: ColumnBatch) -> int:
    """Device-memory estimate of a batch (capacity-based, validity incl.)."""
    total = 0
    for c in batch.columns:
        total += _col_nbytes(c)
    return total


def _col_nbytes(c) -> int:
    from blaze_tpu.columnar.batch import (
        DictData, ListData, StringData, StructData,
    )

    n = 0
    if isinstance(c.data, DictData):
        # encoded resident form: codes + the small dictionary (NOT the
        # expanded (capacity, width) matrix — that is the point)
        n += (4 * c.data.codes.shape[0] + c.data.dict_bytes.size
              + 4 * c.data.dict_lengths.shape[0])
    elif isinstance(c.data, StringData):
        n += c.data.bytes.size + 4 * c.data.lengths.shape[0]
    elif isinstance(c.data, ListData):
        n += 4 * c.data.offsets.shape[0] + _col_nbytes(c.data.elements)
    elif isinstance(c.data, StructData):
        n += sum(_col_nbytes(ch) for ch in c.data.children)
    else:
        n += c.data.size * c.data.dtype.itemsize
    if c.validity is not None:
        n += c.validity.shape[0]
    return n
