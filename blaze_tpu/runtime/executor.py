"""Per-task execution runtime: pipeline fusion + streaming drive loop.

Ref: blaze/src/rt.rs NativeExecutionRuntime — there, `plan.execute(partition)`
wires a tokio stream pipeline and a producer loop polls batches across the
FFI boundary. Here the pipeline is *compiled*: maximal chains of map-like
operators become one jit-compiled function (cached globally by plan key, see
jit_cache.py), and the drive loop is a plain Python generator pulling from
the chain's root source.
"""

from __future__ import annotations

import time

from typing import Callable, Optional

from blaze_tpu.columnar.batch import ColumnBatch
from blaze_tpu.config import conf
from blaze_tpu.ops.base import (
    BatchStream, ExecContext, MapLikeOp, Operator, add_compute_split,
    count_stream,
)
from blaze_tpu.runtime import faults, jit_cache, monitor, trace
from blaze_tpu.runtime.metrics import MetricNode


def run_task_with_resilience(attempt: Callable[[], object], *,
                             what: str = "task",
                             run_info: Optional[dict] = None,
                             fallback: Optional[Callable[[], object]] = None,
                             ctx: Optional[ExecContext] = None,
                             deadline: Optional[float] = None,
                             on_error: Optional[Callable] = None,
                             session=None):
    """Drive one task attempt through the resilience ladder.

    `attempt` must be a FULL re-runnable unit of work (decode plan ->
    execute -> commit): every operator here rebuilds its state per
    attempt and artifact commits are crash-atomic (runtime/artifacts.py),
    so re-running after a failure is safe — the Spark task-retry model,
    executed in-engine.

    Policy by error category (faults.classify):
      retryable  bounded retries (conf.max_task_retries) with exponential
                 backoff + jitter (faults.backoff_ms)
      resource   the degradation ladder (conf.enable_degradation_ladder):
                 rung 1 halves conf.target_batch_bytes for the remaining
                 attempts, rung 2 forces a MemManager release (self-spill
                 of every consumer), rung 3 reroutes the task to
                 `fallback` (the CPU row interpreter in the local runner).
                 Ladder off => treated as plain retryable.
      plan/fatal relayed immediately (original exception type preserved)
      killed     relayed immediately, never counted as an engine error

    Rungs and retries are recorded in the process-global resilience
    telemetry and, when given, in `run_info` ("retries", "degradations",
    "degraded.<rung>", "ladder_rung", "errors.<category>").

    `deadline` (time.monotonic seconds, from the supervisor's
    task/query budgets): backoff sleeps are CLAMPED to the remaining
    budget, and a retryable failure with no budget left is reclassified
    to faults.DeadlineError instead of sleeping past the deadline.

    `on_error(exc, category)` is invoked for every classified failure
    except "killed" — the supervisor's per-operator circuit breaker
    counts failures through it.

    `session` (a service.QuerySession) scopes the ladder's degradations
    to ONE query: rung 1 halves the session's batch-target override
    instead of mutating the process-global conf.target_batch_bytes, and
    rung 2's forced spill sweeps only the session tenant's consumers —
    a degrading query cannot shrink another tenant's batches or evict
    its working set."""
    import time as _time

    from blaze_tpu.config import conf
    from blaze_tpu.runtime import memory

    retries = 0
    hang_relaunches = 0
    rung = 0
    saved_target = None
    try:
        while True:
            try:
                return attempt()
            except Exception as e:  # noqa: BLE001 — classify-and-decide
                cat = faults.classify(e)
                if cat == "killed":
                    raise
                faults.note_error(cat, run_info)
                trace.event("task_error", what=what, category=cat,
                            error=type(e).__name__)
                if on_error is not None:
                    try:
                        on_error(e, cat)
                    except Exception:  # noqa: BLE001 — observer only
                        pass
                ladder = cat == "resource" and conf.enable_degradation_ladder
                if ladder:
                    if rung == 0:
                        rung = 1
                        if session is not None:
                            saved_target = (session.batch_target
                                            or conf.target_batch_bytes)
                            session.batch_target = max(
                                saved_target // 2, 1 << 20)
                        else:
                            saved_target = conf.target_batch_bytes
                            conf.target_batch_bytes = max(
                                saved_target // 2, 1 << 20)
                        faults.note_degradation("halve_batch", run_info)
                        trace.event("ladder_rung", what=what, rung=1,
                                    action="halve_batch")
                        _note_rung(run_info, rung)
                        _note_progress("ladder_rung", "halve_batch")
                        continue
                    if rung == 1:
                        rung = 2
                        memory.get_manager(ctx).release(
                            1 << 62,
                            tenant=(session.tenant_id
                                    if session is not None else None))
                        faults.note_degradation("force_spill", run_info)
                        trace.event("ladder_rung", what=what, rung=2,
                                    action="force_spill")
                        _note_rung(run_info, rung)
                        _note_progress("ladder_rung", "force_spill")
                        continue
                    if rung == 2 and fallback is not None:
                        rung = 3
                        faults.note_degradation("fallback", run_info)
                        trace.event("ladder_rung", what=what, rung=3,
                                    action="fallback")
                        _note_rung(run_info, rung)
                        _note_progress("ladder_rung", "fallback")
                        return fallback()
                elif isinstance(e, faults.HungError) and \
                        hang_relaunches < conf.max_task_retries:
                    # a watchdog kill-on-suspicion, not a failure: its
                    # own relaunch budget (a false-positive hang must
                    # not drain the error-retry budget) and no backoff
                    # sleep — but never relaunch past the deadline
                    if deadline is not None and \
                            _time.monotonic() >= deadline:
                        trace.event("deadline_exceeded", what=what,
                                    during="hang_relaunch")
                        raise faults.DeadlineError(
                            f"{what}: hang-relaunch budget exhausted by "
                            f"deadline (after {hang_relaunches} "
                            f"relaunches)") from e
                    faults.note_retry(run_info)
                    hang_relaunches += 1
                    trace.event("hang_relaunch", what=what,
                                n=hang_relaunches)
                    continue
                elif cat in ("retryable", "resource") and \
                        retries < conf.max_task_retries:
                    sleep_s = faults.backoff_ms(retries) / 1000.0
                    if deadline is not None:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            trace.event("deadline_exceeded", what=what,
                                        during="retry")
                            raise faults.DeadlineError(
                                f"{what}: retry budget exhausted by "
                                f"deadline (after {retries} retries)"
                            ) from e
                        sleep_s = min(sleep_s, remaining)
                    faults.note_retry(run_info)
                    retries += 1
                    trace.event("retry", what=what, n=retries,
                                category=cat,
                                backoff_ms=round(sleep_s * 1000, 2))
                    _note_progress("retry", cat)
                    t0 = _time.perf_counter_ns()
                    faults._sleep(sleep_s)
                    if conf.monitor_enabled:
                        monitor.count_time("retry_backoff",
                                           _time.perf_counter_ns() - t0)
                    continue
                raise faults.ensure_classified(e) from e
    finally:
        if saved_target is not None:
            # restore-to-max: with concurrent tasks two ladders can
            # interleave their save/restore — taking the max keeps a
            # degraded (halved) target from outliving the query even if
            # the saves raced
            if session is not None:
                session.batch_target = max(session.batch_target or 0,
                                           saved_target)
            else:
                conf.target_batch_bytes = max(conf.target_batch_bytes,
                                              saved_target)


def run_pool_plan(node, ctx: ExecContext, what: str = "pool_task"):
    """Executor-PROCESS entry for one shipped plan proto
    (runtime/executor_pool.py worker): decode -> execute -> crash-atomic
    commit, driven through the in-process resilience ladder — a
    transient fault burns an executor-local retry (or a resource fault a
    ladder rung) before it costs the driver a cross-process re-queue.
    No row fallback here: the driver owns the lineage and re-executes
    lost partitions itself. conf.task_deadline_ms bounds all attempts,
    same contract as the supervised thread path. Returns the executed
    operator (its metrics carry the stage statistics the worker reports
    back)."""
    import time as _time

    from blaze_tpu.config import conf
    from blaze_tpu.plan import decode_plan

    def attempt():
        op = decode_plan(node)  # fresh operator state per attempt
        list(execute_plan(op, ctx))
        return op

    deadline = None
    if conf.task_deadline_ms and conf.task_deadline_ms > 0:
        deadline = _time.monotonic() + conf.task_deadline_ms / 1000.0
    return run_task_with_resilience(attempt, what=what, ctx=ctx,
                                    deadline=deadline)


def _note_rung(run_info: Optional[dict], rung: int) -> None:
    if run_info is not None:
        run_info["ladder_rung"] = max(run_info.get("ladder_rung", 0), rung)


def _note_progress(kind: str, detail: str) -> None:
    """Mirror a resilience event into the live progress registry (the
    /queries waterfall's retry/rung annotations). One truthiness check
    when live introspection is off; events are rare, so the lazy import
    on the enabled path is fine."""
    from blaze_tpu.config import conf

    if conf.progress_enabled:
        from blaze_tpu.runtime import progress

        progress.note_event(kind, detail)


def _fused_chain(op: MapLikeOp) -> tuple:
    """Longest chain of MapLikeOps ending at `op` (top-down order)."""
    chain = [op]
    while isinstance(chain[-1].child, MapLikeOp):
        chain.append(chain[-1].child)
    return chain[0], chain[-1].child, list(reversed(chain))


def execute_fused(op: MapLikeOp, ctx: ExecContext) -> BatchStream:
    """Execute a map-like operator, fusing its maximal map-like chain.

    Chains containing host-evaluated expressions (digests/JSON/UDF — see
    Operator.jit_safe) run UNJITTED: device ops still dispatch eagerly on
    device, host kernels get concrete arrays (hostfns.host_apply). The axon
    TPU backend rejects XLA host callbacks, so this is the only execution
    mode for such pipelines on real hardware."""
    top, source, chain = _fused_chain(op)
    jit = all(c.jit_safe() for c in chain)
    key = ("fused", jit, top.plan_key())

    def make():
        from blaze_tpu.exprs.compiler import cse_scope

        fns = [c.make_batch_fn() for c in chain]

        def fused(batch: ColumnBatch) -> ColumnBatch:
            # one CSE scope PER OP: shared subexpressions within an op
            # evaluate once; a chain-wide scope would retain every
            # intermediate batch in the memo until the chain ends (ops
            # build fresh batches, so cross-op hits can't happen anyway)
            for fn in fns:
                with cse_scope():
                    batch = fn(batch)
            return batch

        return fused

    def gen():
        for batch in source.execute(ctx):
            ctx.check_running()
            fused = jit_cache.get_or_compile(key + batch.shape_key(), make,
                                             jit=jit)
            t0 = time.perf_counter_ns()
            with op.metrics.timer():
                out = fused(batch)
            batch_ns = time.perf_counter_ns() - t0
            add_compute_split(op, batch_ns, device=jit)
            if conf.monitor_enabled:
                # unjitted chains (host kernels: digests/JSON/UDF) bill
                # host_compute; fused jit dispatch bills device_compute
                monitor.count_time(
                    "device_compute" if jit else "host_compute", batch_ns)
            yield out

    return count_stream(op, gen())


def execute_plan(root: Operator, ctx: Optional[ExecContext] = None) -> BatchStream:
    ctx = ctx or ExecContext()
    return root.execute(ctx)


def execute_stage_or_plan(root: Operator,
                          ctx: Optional[ExecContext] = None) -> BatchStream:
    """Whole-stage single-dispatch attempt first, streaming otherwise.

    Used by stage DRIVERS (shuffle writers, the mesh exchange) whose
    subtree is a complete stage: a matching scan→filter→project→partial
    agg pipeline runs as ONE jit program (stage_compiler), so a shuffle
    map task costs one dispatch instead of one-per-batch. Agg-less
    chains stay streaming (chain_ok=False): one whole-stage batch would
    defeat the drivers' bounded staging/spill."""
    ctx = ctx or ExecContext()
    from blaze_tpu.runtime.stage_compiler import try_run_stage

    staged = try_run_stage(root, ctx, chain_ok=False)
    if staged is not None:
        return iter([staged])
    return root.execute(ctx)


def collect(root: Operator, ctx: Optional[ExecContext] = None) -> ColumnBatch:
    """Materialize all output into one batch (test/driver helper)."""
    ctx = ctx or ExecContext()
    from blaze_tpu.runtime.stage_compiler import try_run_stage

    staged = try_run_stage(root, ctx)
    if staged is not None:
        return staged
    return _collect_streamed(root, ctx)


def _collect_streamed(root: Operator, ctx: ExecContext) -> ColumnBatch:
    from blaze_tpu.ops.common import concat_batches

    batches = list(execute_plan(root, ctx))
    if not batches:
        return ColumnBatch.empty(root.schema)
    if len(batches) == 1:
        return batches[0]
    return concat_batches(batches, root.schema)


def collect_fetch(root: Operator, pack: Callable,
                  ctx: Optional[ExecContext] = None):
    """Run the plan and fetch `pack(batch) -> 1-D f64 array` to the host
    in ONE dependent device→host round trip.

    Remote-attached accelerator reality (the deployment this engine is
    designed for): every dependent dispatch+pull cycle costs a fixed
    ~90ms tunnel round trip regardless of size, so a collect that pulls
    validation flags and then the result pays twice. Here the stage
    compiler's oob/num_rows flags ride the SAME fetch as the packed
    result (optimistic execution): if the flags show the memoized dense
    range no longer covers the data, the packed result is discarded and
    the stage recomputes through the probe/fallback loop — correctness
    is unchanged, only the pull count drops.

    No reference analog: the reference engine is host-resident and its
    collect is free (rt.rs polls batches over an in-process FFI stream).
    """
    return collect_fetch_async(root, pack, ctx)()


def collect_fetch_async(root: Operator, pack: Callable,
                        ctx: Optional[ExecContext] = None):
    """collect_fetch split into dispatch and fetch: returns a zero-arg
    `finish()` whose call pulls the packed result (and, on a tripped
    stage flag, recomputes via the probe/fallback loop).

    Lets a driver PIPELINE partitions/reps: dispatch partition i+1's
    program before pulling partition i's result, hiding the fixed
    ~90ms device->host round trip behind the next dispatch's device
    time (the deployment shape bench.py measures as steady-state).
    collect_fetch is this plus an immediate finish()."""
    import jax.numpy as jnp
    import numpy as np

    ctx = ctx or ExecContext()
    from blaze_tpu.runtime.stage_compiler import try_run_stage

    # the pack fn participates in the jit key: one plan may be fetched
    # through several different packings (digest vs full export). Pin the
    # fn so its id() can never be recycled onto a different pack (the jit
    # cache outlives the caller's reference).
    pack_id = (getattr(pack, "__qualname__", ""), id(pack))
    _pack_pins[id(pack)] = pack

    staged = try_run_stage(root, ctx, deferred=True)
    if staged is not None:
        out, flags, retry, commit_metrics = staged
        if flags is not None:
            key = ("collect_fetch", root.plan_key(), out.shape_key(),
                   pack_id)

            def make():
                def f(out, flags):
                    return jnp.concatenate(
                        [flags.astype(jnp.float64), pack(out)])
                return f

            fn = jit_cache.get_or_compile(key, make)
            packed_dev = fn(out, flags)  # dispatched, NOT pulled

            def finish():
                packed = np.asarray(packed_dev)
                if not bool(packed[0]):
                    commit_metrics()
                    return packed[2:]
                out2 = retry()
                key2 = ("collect_fetch_plain", root.plan_key(),
                        out2.shape_key(), pack_id)
                fn2 = jit_cache.get_or_compile(key2, lambda: pack)
                return np.asarray(fn2(out2))

            return finish
        if commit_metrics is not None:
            commit_metrics()
    else:
        out = _collect_streamed(root, ctx)

    key = ("collect_fetch_plain", root.plan_key(), out.shape_key(), pack_id)
    fn = jit_cache.get_or_compile(key, lambda: pack)
    packed_dev = fn(out)
    return lambda: np.asarray(packed_dev)


def collect_arrow(root: Operator, ctx: Optional[ExecContext] = None):
    from blaze_tpu.columnar.arrow_io import batch_to_arrow

    return batch_to_arrow(collect(root, ctx))


# strong refs for collect_fetch pack fns (keyed by id; see pack_id above)
_pack_pins: dict = {}


def metric_tree(root: Operator) -> MetricNode:
    from blaze_tpu.runtime import compile_service

    node = MetricNode.from_operator(root)
    # process-global compile + resilience counters ride along as extra
    # children (no handler of their own: embedders that only set the root
    # handler are unaffected; tree-walking embedders get the telemetry)
    node.children = list(node.children) + [compile_service.telemetry_node(),
                                           faults.telemetry_node()]
    return node
