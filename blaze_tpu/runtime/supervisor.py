"""Task supervisor: bounded concurrency, heartbeats, deadlines, hang
detection, straggler speculation and per-operator circuit breaking.

The reference engine gets all of this for free from Spark's scheduler:
TaskSchedulerImpl enforces task/stage deadlines, `spark.speculation`
relaunches stragglers with first-commit-wins through the shuffle commit
protocol, and blacklisting retires repeatedly-failing executors. This
engine IS its own scheduler, so PR-2's resilience ladder (retry /
degrade / fallback — executor.run_task_with_resilience) gets the
missing *time axis* here:

  pool        shuffle-map / broadcast / result tasks run on a bounded
              worker pool (conf.max_concurrent_tasks). Deterministic
              chaos replay serializes the pool to ONE worker while a
              fault spec without {"concurrent": true} is armed —
              scheduling order is part of an injection schedule.

  heartbeat   every `ctx.check_running()` a task performs at a batch
              boundary doubles as its heartbeat (TaskAttempt.is_running
              bumps `last_beat`). No second instrument: proof of
              cooperative liveness and the cancel point are the same
              call, exactly the JniBridge.isTaskRunning polling posture.

  watchdog    a daemon thread scans live attempts: heartbeat stalled
              past conf.hang_detect_ms => the attempt is KILLED
              (classified "killed", never retried as-is) and relaunched
              under the ladder as a fresh attempt; a task/query deadline
              (conf.task_deadline_ms / conf.query_deadline_ms) exceeded
              => killed and relayed as faults.DeadlineError. Backoff
              sleeps inside the ladder are clamped to the remaining
              budget (executor.run_task_with_resilience `deadline`).

  speculation a running attempt exceeding conf.speculation_multiplier x
              the running median attempt duration of its stage gets a
              speculative twin on a dedicated thread (NOT the bounded
              pool — a saturated pool must never deadlock waiting on
              itself). Both race to the finish; file-publishing tasks
              arbitrate through a shared CommitGate threaded into
              artifacts.commit_shuffle_pair, so exactly one `.data`/
              `.index` pair is ever published and the loser aborts as
              SpeculationLostError with its temps swept.

  breaker     classified failures carrying an `op.<Kind>` fault point
              are attributed to that operator kind; after
              conf.breaker_failure_threshold of them within one query
              the kind TRIPS and every remaining task whose plan
              contains it is rerouted straight to the row-interpreter
              fallback (no more doomed device attempts). State is
              exported through the resilience telemetry
              (`breaker.tripped.<Kind>`) and run_info.

Disabled (conf.enable_supervisor=False) the runner degrades to the
PR-2 sequential path: tasks run inline on the driver thread with
retries/ladder only — overhead is one branch per stage.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import statistics
import sys
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

from blaze_tpu import config
from blaze_tpu.config import conf
from blaze_tpu.ops.base import ExecContext, TaskKilledError
from blaze_tpu.runtime import faults, trace

# thread-local plumbing: the attempt running on THIS thread (read by
# faults._stall to make injected stalls kill-interruptible) and the task
# owning it (read by fallback builders to inherit the commit gate).
_current = threading.local()

# task attempts currently executing across every Supervisor instance —
# a pool-occupancy gauge for the monitor sampler / Prometheus endpoint
_active_lock = threading.Lock()
_active = 0


def _active_delta(d: int) -> None:
    global _active
    with _active_lock:
        _active += d


def active_tasks() -> int:
    with _active_lock:
        return _active


def current_kill_event() -> Optional[threading.Event]:
    att = getattr(_current, "attempt", None)
    return att.kill_event if att is not None else None


def current_commit_gate():
    task = getattr(_current, "task", None)
    return task.gate if task is not None else None


def current_session():
    """The QuerySession (runtime/service.py) owning the work on THIS
    thread, or None outside the multi-tenant service. Pool workers reach
    it through their task; the query's driver thread through the
    thread-local run_plan pushes for the run's duration."""
    task = getattr(_current, "task", None)
    if task is not None:
        sess = getattr(task, "session", None)
        if sess is not None:
            return sess
    return getattr(_current, "session", None)


class TaskAttempt:
    """One execution of a task's attempt function. The kill flag is an
    Event so cooperative sleeps (faults._stall, backoff) can block on it;
    `is_running()` is wired into ExecContext, so every batch-boundary
    check is simultaneously the attempt's heartbeat."""

    __slots__ = ("task", "speculative", "started", "last_beat",
                 "kill_event", "kill_reason", "deadline", "attempt_id")

    def __init__(self, task: "_Task", speculative: bool) -> None:
        self.task = task
        self.speculative = speculative
        self.started = time.monotonic()
        self.last_beat = self.started
        self.kill_event = threading.Event()
        self.kill_reason: Optional[str] = None
        self.deadline = task.deadline
        # trace correlation id, unique within the task (speculative twins
        # get their own — "which attempt actually produced partition 7")
        self.attempt_id = task.next_attempt_id()

    def is_running(self) -> bool:
        self.last_beat = time.monotonic()
        return not self.kill_event.is_set()

    def kill(self, reason: str) -> bool:
        """Request cancellation; returns True only for the first kill so
        watchdog telemetry counts each detection once."""
        if self.kill_event.is_set():
            return False
        self.kill_reason = self.kill_reason or reason
        self.kill_event.set()
        return True


class CommitGate:
    """First-commit-wins arbiter shared by an attempt and its
    speculative twin. `claim()` is true exactly once; a claimant whose
    publish then fails calls `abort()` so the surviving lineage's retry
    can still commit."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._committed = False

    def claim(self) -> bool:
        with self._lock:
            if self._committed:
                return False
            self._committed = True
            return True

    def abort(self) -> None:
        with self._lock:
            self._committed = False


class CircuitBreaker:
    """Per-query, per-operator-kind failure counter. Attribution comes
    from the fault `point` the taxonomy attaches to classified errors
    ("op.<Kind>" at operator stream boundaries); unattributable errors
    (no point, or a non-operator point like spill.write) don't count —
    tripping must name an operator to reroute around."""

    def __init__(self, run_info: Optional[dict] = None) -> None:
        self._lock = threading.Lock()
        self._failures: Dict[str, int] = {}
        self._tripped: set = set()
        self._run_info = run_info

    def note_failure(self, exc: BaseException, category: str = "") -> None:
        if category == "killed":
            return
        threshold = int(conf.breaker_failure_threshold)
        if threshold <= 0:
            return
        point = getattr(exc, "point", None)
        if not point:
            point = getattr(getattr(exc, "__cause__", None), "point", None)
        if not isinstance(point, str) or not point.startswith("op."):
            return
        kind = point.split(".", 1)[1]
        with self._lock:
            n = self._failures[kind] = self._failures.get(kind, 0) + 1
            if kind in self._tripped or n < threshold:
                return
            self._tripped.add(kind)
        faults.TELEMETRY.add("breaker.trips", 1)
        faults.TELEMETRY.add(f"breaker.tripped.{kind}", 1)
        trace.event("breaker_trip", op_kind=kind, failures=n)
        if self._run_info is not None:
            self._run_info["breaker_trips"] = \
                self._run_info.get("breaker_trips", 0) + 1
        if conf.flight_dir:
            # black-box dossier at the moment of the trip — the query
            # usually survives (rerouted to fallback), so the end-of-run
            # hook would never see this incident
            from blaze_tpu.runtime import flight_recorder

            qid = trace.current_context().get("query_id")
            if qid:
                flight_recorder.capture(
                    "breaker_trip", qid,
                    error=exc if isinstance(exc, Exception) else None,
                    detail={"op_kind": kind, "failures": n})

    def tripped(self) -> FrozenSet[str]:
        with self._lock:
            return frozenset(self._tripped)

    def should_reroute(self, op_kinds: FrozenSet[str]) -> bool:
        if not op_kinds:
            return False
        with self._lock:
            return not self._tripped.isdisjoint(op_kinds)


class ProcessPeer:
    """One supervised executor process: the PID twin of TaskAttempt.
    `beat()` is bumped by ANY inbound control-socket frame (push beats
    included), the same no-second-instrument posture as the thread
    heartbeat; `poll` is the owner's reaper (subprocess.Popen.poll) so a
    zombie child is seen as dead even though os.kill(pid, 0) still
    succeeds on it."""

    __slots__ = ("key", "pid", "last_beat", "poll", "on_death", "dead",
                 "draining", "stale_ms")

    def __init__(self, key: str, pid: int,
                 on_death: Callable[["ProcessPeer", str, Optional[int]],
                                    None],
                 poll: Optional[Callable[[], Optional[int]]] = None,
                 stale_ms: Optional[int] = None) -> None:
        self.key = key
        self.pid = pid
        self.last_beat = time.monotonic()
        self.poll = poll
        self.on_death = on_death
        self.dead = False
        self.draining = False
        # per-peer staleness override: None -> conf.executor_death_ms;
        # 0 -> pid-liveness ONLY (a peer that never beats this watchdog
        # — the standby watching its primary — must not be declared
        # heartbeat-dead for silence that is perfectly healthy)
        self.stale_ms = stale_ms

    def beat(self) -> None:
        self.last_beat = time.monotonic()


class ProcessWatchdog:
    """Executor-death detector: the thread watchdog's heartbeat/staleness
    scan generalized to PIDs (ROADMAP item 1). A peer is declared dead
    when its process is reaped/vanished (reason "exit", with the exit
    code — negative = killing signal) or when its heartbeat goes stale
    past conf.executor_death_ms (reason "heartbeat" — the process may
    still be RUNNING; the owner must fence its epoch so its late results
    are rejected). Each peer's on_death fires exactly once, off-thread
    from the socket readers, and must never raise."""

    _TICK = 0.05

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._peers: Dict[str, ProcessPeer] = {}
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, key: str, pid: int, on_death,
                 poll=None, stale_ms=None) -> ProcessPeer:
        peer = ProcessPeer(key, pid, on_death, poll=poll,
                           stale_ms=stale_ms)
        with self._lock:
            self._peers[key] = peer
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="blz-procdog", daemon=True)
                self._thread.start()
        return peer

    def unregister(self, key: str) -> None:
        with self._lock:
            self._peers.pop(key, None)

    def beat(self, key: str) -> None:
        with self._lock:
            peer = self._peers.get(key)
        if peer is not None:
            peer.beat()

    def mark_draining(self, key: str) -> None:
        """Flag a peer as gracefully decommissioning: its clean exit
        (rc 0) routes to on_death(reason="drained") with NO
        executor_death event/telemetry — an orderly drain is not a
        death."""
        with self._lock:
            peer = self._peers.get(key)
        if peer is not None:
            peer.draining = True

    def _pid_gone(self, peer: ProcessPeer) -> Tuple[bool, Optional[int]]:
        if peer.poll is not None:
            rc = peer.poll()
            if rc is not None:
                return True, rc
            return False, None
        from blaze_tpu.runtime.artifacts import _pid_alive

        return (not _pid_alive(peer.pid)), None

    def _loop(self) -> None:
        while not self._closed.is_set():
            death_ms = max(int(conf.executor_death_ms), 1)
            self._closed.wait(min(self._TICK, death_ms / 4000.0))
            try:
                self._scan()
            except Exception:  # noqa: BLE001 — watchdog must never die
                pass

    def _scan(self) -> None:
        now = time.monotonic()
        stale_s = max(int(conf.executor_death_ms), 1) / 1000.0
        with self._lock:
            peers = list(self._peers.values())
        for peer in peers:
            if peer.dead:
                continue
            gone, rc = self._pid_gone(peer)
            peer_stale_s = (stale_s if peer.stale_ms is None
                            else max(int(peer.stale_ms), 0) / 1000.0)
            if gone:
                reason = "exit"
            elif peer.draining:
                continue  # a draining peer may idle past staleness
            elif peer_stale_s > 0 and now - peer.last_beat > peer_stale_s:
                reason, rc = "heartbeat", None
            else:
                continue
            peer.dead = True
            self.unregister(peer.key)
            if peer.stale_ms == 0:
                # a pid-liveness-only peer is a SILENT watch on a
                # non-heartbeating process (the standby watching its
                # primary, standby.StandbyDriver) — route the death to
                # the owner but do not account it as an executor death
                try:
                    peer.on_death(peer, reason, rc)
                except Exception:  # noqa: BLE001 — must not kill scan
                    pass
                continue
            if peer.draining and rc in (0, None):
                # clean exit of a decommissioning worker: route to the
                # owner as "drained", no dossier, no death accounting
                try:
                    peer.on_death(peer, "drained", rc)
                except Exception:  # noqa: BLE001 — must not kill scan
                    pass
                continue
            faults.TELEMETRY.add("executor_deaths", 1)
            trace.event("executor_death", exec_id=peer.key, pid=peer.pid,
                        reason=reason, exit_code=rc,
                        stale_ms=round((now - peer.last_beat) * 1000))
            try:
                peer.on_death(peer, reason, rc)
            except Exception:  # noqa: BLE001 — callback must not kill scan
                pass

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            thread = self._thread
            self._peers.clear()
        if thread is not None:
            thread.join(timeout=1.0)


class _SessionQueue:
    """FairScheduler-internal per-session run queue (stride scheduling
    state): FIFO within the session, virtual time across sessions."""

    __slots__ = ("tenant_id", "query_id", "weight", "vt", "items")

    def __init__(self, tenant_id: str, query_id: str, weight: float,
                 vt: float) -> None:
        self.tenant_id = tenant_id
        self.query_id = query_id
        self.weight = max(float(weight), 1e-6)
        self.vt = vt
        self.items: collections.deque = collections.deque()


class FairScheduler:
    """Shared worker pool dispatching TaskSpecs across live query
    sessions with deficit-weighted round robin (stride scheduling).

    The single-query Supervisor submits FIFO into its own pool; under
    the multi-tenant service every live query submits HERE instead, and
    each free worker runs the head of the non-empty session queue with
    the smallest virtual time, then advances that queue's clock by
    1/weight (weight = the tenant's conf.tenant_priority_spec entry).
    Under contention a weight-3 tenant gets ~3x the dispatch share of a
    weight-1 tenant, order within one session stays submission order,
    and no session starves (every dispatch monotonically advances the
    running queue's clock past its peers'). A session entering mid-run
    starts at the scheduler's current clock — it competes from now on,
    it does not get retroactive catch-up dispatches."""

    def __init__(self, width: int) -> None:
        self.width = max(1, int(width))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[str, _SessionQueue] = {}
        self._vclock = 0.0
        self._closed = False
        # (tenant_id, query_id, what) per dispatch, in dispatch order —
        # how tests observe weighted fairness without timing assertions
        self.dispatch_log: List[Tuple[str, str, str]] = []
        self._threads = [
            threading.Thread(target=self._worker, name=f"blz-svc-{i}",
                             daemon=True)
            for i in range(self.width)]
        for t in self._threads:
            t.start()

    def queue_depth(self) -> int:
        with self._lock:
            return sum(len(q.items) for q in self._queues.values())

    def submit(self, session, fn: Callable[[], Any],
               what: str = "") -> Future:
        """Enqueue fn under the session's queue; returns a Future that a
        worker completes (cancel() works while still queued)."""
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("FairScheduler is closed")
            q = self._queues.get(session.query_id)
            if q is None:
                q = _SessionQueue(session.tenant_id, session.query_id,
                                  session.priority, self._vclock)
                self._queues[session.query_id] = q
            # 4th element: enqueue timestamp — dispatch wait (submitted
            # -> picked) is the "sched_queue" critical-path term
            q.items.append((fut, fn, what, time.monotonic()))
            self._cond.notify()
        return fut

    def forget(self, session) -> None:
        """Drop a finished session's queue (cancelling stragglers)."""
        with self._cond:
            q = self._queues.pop(session.query_id, None)
        if q is not None:
            for fut, _fn, _what, _t0 in q.items:
                fut.cancel()

    def _pick_locked(self) -> Optional[tuple]:
        ready = [q for q in self._queues.values() if q.items]
        if not ready:
            return None
        q = min(ready, key=lambda s: (s.vt, s.query_id))
        item = q.items.popleft()
        q.vt += 1.0 / q.weight
        if q.vt > self._vclock:
            self._vclock = q.vt
        self.dispatch_log.append((q.tenant_id, q.query_id, item[2]))
        # per-query dispatch-wait attribution (runtime/doctor.py term
        # "sched_queue"); explicit qid — workers have no trace context
        wait_ns = int((time.monotonic() - item[3]) * 1e9)
        if wait_ns > 0 and conf.monitor_enabled:
            from blaze_tpu.runtime import monitor

            monitor.count_time("sched_queue", wait_ns, qid=q.query_id)
        return item

    def _worker(self) -> None:
        while True:
            with self._cond:
                item = self._pick_locked()
                while item is None and not self._closed:
                    self._cond.wait()
                    item = self._pick_locked()
                if item is None:
                    return  # closed and drained
            fut, fn, _what, _t0 = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — relay via future
                fut.set_exception(e)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            for q in self._queues.values():
                for fut, _fn, _what, _t0 in q.items:
                    fut.cancel()
                q.items.clear()
            self._queues.clear()
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)


@dataclasses.dataclass
class TaskSpec:
    """One schedulable unit handed to Supervisor.run_tasks.

    `attempt_fn(ctx)` must be a FULL re-runnable attempt (decode plan ->
    execute -> commit) — it is invoked once per attempt with a fresh
    ExecContext carrying that attempt's kill flag and the task's commit
    gate. `fallback_fn()` is the rung-3 row-interpreter route (also used
    by breaker reroutes). `op_kinds` is the set of operator names in the
    task's plan, for breaker matching."""

    what: str
    attempt_fn: Callable[[ExecContext], Any]
    partition: int = 0
    num_partitions: int = 1
    fallback_fn: Optional[Callable[[], Any]] = None
    op_kinds: FrozenSet[str] = frozenset()
    speculatable: bool = True


class _Task:
    """Supervisor-internal task state: the spec, its commit gate, the
    live attempts (primary + at most one speculative) and the
    first-finish-wins outcome."""

    def __init__(self, spec: TaskSpec, stage_key, deadline: Optional[float],
                 trace_ctx: Optional[Dict[str, Any]] = None,
                 session=None) -> None:
        self.spec = spec
        self.stage_key = stage_key
        self.deadline = deadline
        self.session = session
        self.gate = CommitGate()
        self.done = threading.Event()
        self._lock = threading.Lock()
        self.outcome: Optional[Tuple[str, Any]] = None
        self.live_attempts: List[TaskAttempt] = []
        self.speculated = False
        self.cancelled = False
        self.primary_started: Optional[float] = None
        # driver-thread trace context (query_id/stage_id) captured at
        # submit, replayed inside pool/speculative/watchdog emissions so
        # cross-thread records stay correlated; task_id = spec.what
        self.trace_ctx: Dict[str, Any] = dict(trace_ctx or {})
        self.trace_ctx["task_id"] = spec.what
        # the submitting thread's resolved conf overlay
        # (config.overlay_scope): replayed around every attempt so pool
        # workers and speculative twins read the same per-query conf as
        # the driver thread — one query's overlay never leaks into a
        # concurrent query's tasks
        self.conf_overlay = config.current_overlay()
        self.conf_provenance = config.current_provenance()
        self._attempt_seq = itertools.count(1)

    def next_attempt_id(self) -> int:
        return next(self._attempt_seq)

    @property
    def finished(self) -> bool:
        return self.done.is_set()

    def finish(self, kind: str, value: Any) -> bool:
        """Record the outcome; only the FIRST finisher wins."""
        with self._lock:
            if self.outcome is not None:
                return False
            self.outcome = (kind, value)
        self.done.set()
        return True

    def attach(self, att: TaskAttempt) -> None:
        with self._lock:
            self.live_attempts.append(att)
            if not att.speculative and self.primary_started is None:
                self.primary_started = att.started

    def detach(self, att: TaskAttempt) -> None:
        with self._lock:
            try:
                self.live_attempts.remove(att)
            except ValueError:
                pass

    def live(self) -> List[TaskAttempt]:
        with self._lock:
            return list(self.live_attempts)

    def kill_attempts(self, reason: str,
                      speculative: Optional[bool] = None) -> None:
        for att in self.live():
            if speculative is None or att.speculative == speculative:
                att.kill(reason)


class Supervisor:
    """Per-query task supervisor. Create one per run_plan invocation,
    call `run_tasks` per stage, `close()` in the run's finally."""

    _WATCHDOG_TICK = 0.05
    _ABANDON_GRACE = 2.0  # slack past a deadline before abandoning a thread

    def __init__(self, run_info: Optional[dict] = None,
                 session=None) -> None:
        self.run_info = run_info
        self.session = session
        self.enabled = bool(conf.enable_supervisor)
        self.breaker = CircuitBreaker(run_info)
        self.query_deadline: Optional[float] = None
        if session is not None and session.deadline_at is not None:
            # admission-aware budget: the service stamped the absolute
            # deadline when the query ARRIVED, so time parked in the
            # admission queue counts against conf.query_deadline_ms
            self.query_deadline = session.deadline_at
        elif conf.query_deadline_ms and conf.query_deadline_ms > 0:
            self.query_deadline = (time.monotonic()
                                   + conf.query_deadline_ms / 1000.0)
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._tasks: List[_Task] = []
        self._durations: Dict[Any, List[float]] = {}
        self._spec_threads: List[threading.Thread] = []
        self._closed = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        self._abandoned = False

    # -- budgets -----------------------------------------------------------

    def deadline(self) -> Optional[float]:
        """Absolute monotonic deadline for a task launched NOW: the
        tighter of the per-task and remaining per-query budgets."""
        cands = []
        if conf.task_deadline_ms and conf.task_deadline_ms > 0:
            cands.append(time.monotonic() + conf.task_deadline_ms / 1000.0)
        if self.query_deadline is not None:
            cands.append(self.query_deadline)
        return min(cands) if cands else None

    # -- pool / watchdog ---------------------------------------------------

    def _pool_width(self) -> int:
        spec = conf.fault_injection_spec
        if spec and not spec.get("concurrent"):
            # deterministic chaos replay: thread interleavings would make
            # the global nth/fail_times counters consume in racy order
            return 1
        return max(1, int(conf.max_concurrent_tasks))

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._pool_width(),
                    thread_name_prefix="blz-task")
            return self._pool

    def _watchdog_needed(self) -> bool:
        return (self.query_deadline is not None
                or (conf.task_deadline_ms or 0) > 0
                or (conf.hang_detect_ms or 0) > 0
                or (conf.speculation_multiplier or 0) > 0)

    def _ensure_watchdog(self) -> None:
        if not self._watchdog_needed():
            return
        with self._lock:
            if self._watchdog is not None:
                return
            t = threading.Thread(target=self._watchdog_loop,
                                 name="blz-watchdog", daemon=True)
            self._watchdog = t
        t.start()

    def _watchdog_loop(self) -> None:
        while not self._closed.is_set():
            tick = self._WATCHDOG_TICK
            hang_ms = conf.hang_detect_ms or 0
            if hang_ms > 0:
                tick = min(tick, hang_ms / 4000.0)
            self._closed.wait(max(tick, 0.005))
            try:
                self._scan()
            except Exception:  # noqa: BLE001 — watchdog must never die
                pass

    def _scan(self) -> None:
        now = time.monotonic()
        hang_s = (conf.hang_detect_ms or 0) / 1000.0
        with self._lock:
            tasks = list(self._tasks)
        for task in tasks:
            if task.finished:
                continue
            for att in task.live():
                if att.deadline is not None and now > att.deadline:
                    if att.kill("deadline"):
                        self._note("deadline_kills")
                        trace.event("deadline_kill",
                                    attempt_id=att.attempt_id,
                                    **task.trace_ctx)
                        self._stash_stacks(task, "deadline")
                elif hang_s > 0 and now - att.last_beat > hang_s:
                    if att.kill("hung"):
                        self._note("hangs_detected")
                        # a heartbeat miss: the attempt's batch-boundary
                        # check went stale past conf.hang_detect_ms
                        trace.event("hang_detected",
                                    attempt_id=att.attempt_id,
                                    stale_ms=round((now - att.last_beat)
                                                   * 1000),
                                    **task.trace_ctx)
                        self._stash_stacks(task, "hung")
            self._maybe_speculate(task, now)

    def _stash_stacks(self, task: _Task, reason: str) -> None:
        """Snapshot every thread's stack AT detection time for the
        flight recorder: by the time the query unwinds and the dossier
        is written, the hung/overrunning frames are long gone."""
        if not conf.flight_dir:
            return
        qid = task.trace_ctx.get("query_id")
        if not qid:
            return
        from blaze_tpu.runtime import flight_recorder

        flight_recorder.record_stacks(qid, reason)

    def _note(self, key: str, n: int = 1) -> None:
        faults.TELEMETRY.add(key, n)
        if self.run_info is not None:
            self.run_info[key] = self.run_info.get(key, 0) + n

    # -- duration stats (speculation threshold) ----------------------------

    def _record_duration(self, stage_key, seconds: float) -> None:
        with self._lock:
            self._durations.setdefault(stage_key, []).append(seconds)

    def _median_duration(self, stage_key) -> Optional[float]:
        with self._lock:
            ds = self._durations.get(stage_key)
            if not ds or len(ds) < 2:
                return None  # no basis to call anything a straggler yet
            return statistics.median(ds)

    # -- speculation -------------------------------------------------------

    def _maybe_speculate(self, task: _Task, now: float) -> None:
        mult = float(conf.speculation_multiplier or 0)
        if mult <= 0 or task.speculated or task.cancelled or task.finished:
            return
        if not task.spec.speculatable or task.primary_started is None:
            return
        med = self._median_duration(task.stage_key)
        if med is None or now - task.primary_started <= mult * med:
            return
        with task._lock:
            if task.speculated or task.outcome is not None:
                return
            task.speculated = True
        self._note("speculations_launched")
        trace.event("speculation_launch",
                    elapsed_ms=round((now - task.primary_started) * 1000),
                    median_ms=round(med * 1000), **task.trace_ctx)
        t = threading.Thread(target=self._run_speculative, args=(task,),
                             name="blz-speculative", daemon=True)
        with self._lock:
            self._spec_threads.append(t)
        t.start()

    def _run_speculative(self, task: _Task) -> None:
        """The twin: ONE bare attempt, no ladder — if it fails the
        primary's ladder is still driving recovery, and if it wins the
        primary is killed with reason "speculation_lost"."""
        try:
            started = time.monotonic()
            value = self._attempt_once(task, speculative=True)
        except BaseException as e:  # noqa: BLE001 — twin failure non-fatal
            trace.event("speculation_loss", loser="speculative",
                        reason=type(e).__name__, **task.trace_ctx)
            return
        if task.finish("ok", value):
            self._note("speculations_won")
            # the twin won the first-commit-wins race; the primary is
            # killed and records the loss side of the same pair
            trace.event("speculation_win", winner="speculative",
                        **task.trace_ctx)
            self._record_duration(task.stage_key,
                                  time.monotonic() - started)
            trace.record_value("task_latency_us",
                               int((time.monotonic() - started) * 1e6))
            task.kill_attempts("speculation_lost", speculative=False)

    # -- attempt execution -------------------------------------------------

    def _attempt_once(self, task: _Task, speculative: bool) -> Any:
        """Run the spec's attempt function once under a fresh
        TaskAttempt. Supervisor-initiated kills are translated at this
        boundary: "hung" relaunches under the ladder (HungError, its
        own relaunch budget),
        "deadline" is terminal (DeadlineError), everything else —
        speculation_lost / sibling_failed / shutdown — stays killed."""
        if task.cancelled:
            raise TaskKilledError(f"{task.spec.what}: cancelled")
        att = TaskAttempt(task, speculative)
        task.attach(att)
        if conf.progress_enabled:
            from blaze_tpu.runtime import progress
            progress.attempt_update(task.trace_ctx, att.attempt_id,
                                    "running", speculative=speculative)
        else:
            progress = None
        prev_att = getattr(_current, "attempt", None)
        prev_task = getattr(_current, "task", None)
        _current.attempt, _current.task = att, task
        try:
            # replay the driver's correlation ids on THIS thread (pool or
            # speculative twin) and record the attempt as a span — every
            # record inside inherits query/stage/task/attempt ids
            with trace.context(**task.trace_ctx):
                with trace.span("task_attempt",
                                attempt_id=att.attempt_id,
                                partition=task.spec.partition,
                                speculative=speculative) as sp:
                    ctx = ExecContext(
                        partition=task.spec.partition,
                        num_partitions=task.spec.num_partitions,
                        is_running=att.is_running,
                        commit_gate=task.gate)
                    try:
                        if task.conf_overlay:
                            with config.overlay_scope(
                                    task.conf_overlay,
                                    task.conf_provenance):
                                return task.spec.attempt_fn(ctx)
                        return task.spec.attempt_fn(ctx)
                    finally:
                        if att.kill_reason:
                            sp.set(kill_reason=att.kill_reason)
        except TaskKilledError as e:
            if att.kill_reason == "hung":
                raise faults.HungError(
                    f"{task.spec.what}: attempt hung (no heartbeat for "
                    f"{conf.hang_detect_ms}ms), killed and relaunching"
                ) from e
            if att.kill_reason == "deadline":
                raise faults.DeadlineError(
                    f"{task.spec.what}: deadline exceeded") from e
            raise
        finally:
            _current.attempt, _current.task = prev_att, prev_task
            task.detach(att)
            if progress is not None:
                if att.kill_reason:
                    state = f"killed:{att.kill_reason}"
                elif sys.exc_info()[1] is not None:
                    state = "failed"
                else:
                    state = "ok"
                progress.attempt_update(task.trace_ctx, att.attempt_id,
                                        state, speculative=speculative)

    def _run_supervised(self, task: _Task) -> Any:
        """Pool-worker body: breaker reroute, then the PR-2 resilience
        ladder around `_attempt_once`, racing any speculative twin
        through the task's outcome slot."""
        from blaze_tpu.runtime.executor import run_task_with_resilience

        prev_task = getattr(_current, "task", None)
        _current.task = task
        _active_delta(1)
        try:
            # context on the WORKER thread so the executor's retry/ladder
            # events (emitted between attempts, outside _attempt_once's
            # span) still carry the query/stage/task ids
            with trace.context(**task.trace_ctx):
                self._run_supervised_inner(task, run_task_with_resilience)
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, TaskKilledError) and not task.finished:
                # killed by a twin/sibling that should be finishing the
                # task — give it a bounded window, then own the failure
                # (e.g. the twin claimed the gate and then died)
                task.done.wait(self._twin_grace(task))
            if not task.finish("err", e):
                pass  # a twin already finished; its outcome stands
        finally:
            _active_delta(-1)
            _current.task = prev_task
        task.done.wait()
        kind, value = task.outcome  # type: ignore[misc]
        if kind == "err":
            raise value
        return value

    def _run_supervised_inner(self, task: _Task, run_task_with_resilience
                              ) -> None:
        spec = task.spec

        def attempt():
            # breaker check at EVERY attempt boundary, not just task
            # start: a kind that trips mid-ladder (its own failures
            # count) reroutes this task's next retry instead of
            # burning the remaining budget on a doomed operator
            if (spec.fallback_fn is not None
                    and self.breaker.should_reroute(spec.op_kinds)):
                self._note("breaker_reroutes")
                return spec.fallback_fn()
            return self._attempt_once(task, speculative=False)

        started = time.monotonic()
        value = run_task_with_resilience(
            attempt, what=spec.what, run_info=self.run_info,
            fallback=spec.fallback_fn, deadline=task.deadline,
            on_error=self.breaker.note_failure, session=self.session)
        if task.finish("ok", value):
            self._record_duration(task.stage_key,
                                  time.monotonic() - started)
            trace.record_value(
                "task_latency_us",
                int((time.monotonic() - started) * 1e6))
            if task.speculated:
                # primary beat its own twin: the launched speculation
                # lost the race
                trace.event("speculation_loss", loser="speculative",
                            reason="primary_finished",
                            **task.trace_ctx)
        task.kill_attempts("speculation_lost", speculative=True)

    def _twin_grace(self, task: _Task) -> float:
        if task.deadline is not None:
            return max(0.0, task.deadline - time.monotonic()) \
                + self._ABANDON_GRACE
        return 30.0

    # -- public API --------------------------------------------------------

    def run_tasks(self, stage_key, specs: List[TaskSpec]) -> List[Any]:
        """Run a stage's tasks, returning their values in spec order.
        Raises the first task error after killing the stage's siblings;
        a task that outlives its deadline without cooperating is
        abandoned on its thread and relayed as DeadlineError."""
        if not specs:
            return []
        if not self.enabled:
            return [self._run_sequential(spec) for spec in specs]
        deadline = self.deadline()
        # snapshot the driver's query/stage ids here, on the submitting
        # thread — pool workers and twins replay them via task.trace_ctx
        ctx_snap = trace.current_context()
        tasks = [_Task(spec, stage_key, deadline, ctx_snap,
                       session=self.session)
                 for spec in specs]
        with self._lock:
            self._tasks.extend(tasks)
        self._ensure_watchdog()
        sched = (self.session.scheduler
                 if self.session is not None else None)
        if sched is not None:
            # multi-tenant service: the SHARED pool interleaves this
            # stage's tasks with other live queries', weighted by tenant
            # priority (FairScheduler) — not this query's private FIFO
            futures = [sched.submit(self.session,
                                    lambda t=t: self._run_supervised(t),
                                    what=t.spec.what)
                       for t in tasks]
        else:
            pool = self._ensure_pool()
            futures = [pool.submit(self._run_supervised, t)
                       for t in tasks]
        results: List[Any] = [None] * len(tasks)
        first_err: Optional[BaseException] = None
        for i, (task, fut) in enumerate(zip(tasks, futures)):
            timeout = None
            if task.deadline is not None:
                timeout = max(0.0, task.deadline - time.monotonic()) \
                    + self._ABANDON_GRACE
            try:
                results[i] = fut.result(timeout=timeout)
            except (TimeoutError, FutureTimeoutError):
                # (futures.TimeoutError is a distinct class until py3.11)
                # non-cooperative hang: kill (in case it ever wakes),
                # abandon the thread, relay as a deadline failure
                task.cancelled = True
                task.kill_attempts("deadline")
                self._abandoned = True
                trace.event("task_abandoned", **task.trace_ctx)
                if first_err is None:
                    first_err = faults.DeadlineError(
                        f"{task.spec.what}: task exceeded its deadline "
                        f"without cooperating; attempt abandoned")
                    self._cancel_siblings(tasks, futures, skip=i)
            except BaseException as e:  # noqa: BLE001
                if first_err is None:
                    first_err = e
                    self._cancel_siblings(tasks, futures, skip=i)
        if first_err is not None:
            raise first_err
        return results

    def _cancel_siblings(self, tasks: List[_Task], futures, skip: int
                         ) -> None:
        for j, (t, f) in enumerate(zip(tasks, futures)):
            if j == skip:
                continue
            f.cancel()  # queued-but-unstarted siblings never run
            t.cancelled = True
            t.kill_attempts("sibling_failed")

    def _run_sequential(self, spec: TaskSpec) -> Any:
        """conf.enable_supervisor=False: the PR-2 inline path (plus the
        breaker and deadline clamps, which cost one lookup each)."""
        from blaze_tpu.runtime.executor import run_task_with_resilience

        ctx = ExecContext(partition=spec.partition,
                          num_partitions=spec.num_partitions)

        def attempt():
            # same per-attempt breaker check as the supervised path
            if (spec.fallback_fn is not None
                    and self.breaker.should_reroute(spec.op_kinds)):
                self._note("breaker_reroutes")
                return spec.fallback_fn()
            return spec.attempt_fn(ctx)

        # sequential path runs on the driver thread: only task_id needs
        # pushing, the query/stage ids are already on this thread's stack
        with trace.context(task_id=spec.what):
            started = time.monotonic()
            _active_delta(1)
            try:
                value = run_task_with_resilience(
                    attempt, what=spec.what,
                    run_info=self.run_info, fallback=spec.fallback_fn,
                    ctx=ctx, deadline=self.deadline(),
                    on_error=self.breaker.note_failure,
                    session=self.session)
            finally:
                _active_delta(-1)
            trace.record_value("task_latency_us",
                               int((time.monotonic() - started) * 1e6))
            return value

    def close(self) -> None:
        """Kill every live attempt, stop the watchdog, drain the pool.
        Safe to call twice; called from the runner's finally."""
        if self._closed.is_set():
            return
        self._closed.set()
        with self._lock:
            tasks = list(self._tasks)
            pool = self._pool
            spec_threads = list(self._spec_threads)
            watchdog = self._watchdog
        for task in tasks:
            task.cancelled = True
            task.kill_attempts("shutdown")
        if pool is not None:
            # after an abandon the stuck thread may never exit; don't
            # let close() inherit its hang
            try:
                pool.shutdown(wait=not self._abandoned,
                              cancel_futures=True)
            except TypeError:  # pragma: no cover — pre-3.9 signature
                pool.shutdown(wait=not self._abandoned)
        for t in spec_threads:
            t.join(timeout=1.0)
        if watchdog is not None:
            watchdog.join(timeout=1.0)
