"""Durable exactly-once micro-batch streaming (ROADMAP items 1 + 5).

A `StreamingQuery` turns the batch engine into a long-lived incremental
aggregation: a `TailSource` tails a growing parquet directory (new
immutable files published by rename, the classic micro-batch file-source
contract), each tick's unconsumed files become one micro-batch plan —
scan -> partial hash agg -> shuffle -> final hash agg — run through the
EXISTING driver path (pipeline, supervisor, executor pool, service
admission), and the per-batch partial aggregates are merged into the
stream's in-memory state with associative merge functions (sum / count /
min / max), so the state after N batches equals one batch over the full
input.

The robustness headline is the checkpoint protocol. After a micro-batch
commits, `(consumed source offsets, serialized aggregation state, batch
epoch)` travel together in ONE `stream_checkpoint` record appended
crash-atomically through runtime/journal.py (heal torn tail -> write ->
flush -> fsync). Because offsets and state are atomic, every crash —
executor SIGKILL mid-batch, driver SIGKILL mid-checkpoint, PR-16 standby
takeover — resumes EXACTLY-ONCE by construction:

  * a crash BEFORE the checkpoint re-processes the in-flight batch from
    the previous checkpoint's offsets INTO the previous checkpoint's
    state — nothing was merged twice, nothing dropped;
  * a crash MID-checkpoint leaves a torn tail that `load_records` skips
    and the next append heals — recovery falls back to the last
    parseable checkpoint, same story;
  * a crash AFTER the checkpoint resumes past the committed batch — no
    batch is ever re-emitted (checkpoint epochs are strictly monotone).

Stream journals are never billed `driver_restart` by the recovery scan
and never pruned by retention until a GRACEFUL stop settles them
(journal.is_stream / _stream_settled): they are ADOPTED — the scan
registers dead-writer stream journals, standby takeover reports them,
and `resume_stream()` reconstructs the TailSource + StreamSpec from the
journal's `stream_open` record and picks up at the last checkpoint.

Knobs: `stream_poll_ms` (tick cadence when caught up),
`stream_checkpoint_interval` (batches per fsync),
`stream_max_lag_ms` (lag objective: sustained lag past it cuts a
`stream_stall` flight dossier once per stream and a doctor `stream_lag`
finding).
"""

from __future__ import annotations

import json
import fnmatch
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from blaze_tpu.columnar import types as T
from blaze_tpu.config import conf
from blaze_tpu.exprs.ir import col
from blaze_tpu.runtime import faults, journal, trace
from blaze_tpu.spark import plan_model as P

__all__ = ["TailSource", "StreamSpec", "StreamingQuery", "open_stream",
           "resume_stream", "adoptable_streams", "stream_stats",
           "live_streams", "reset"]

_DTYPES = {"int32": T.INT32, "int64": T.INT64,
           "float64": T.FLOAT64, "string": T.STRING}
_DTYPE_NAMES = {v: k for k, v in _DTYPES.items()}

_registry_lock = threading.Lock()
_streams: Dict[str, "StreamingQuery"] = {}


def _is_missing(v: Any) -> bool:
    """None / NaN — parquet nulls surface as either depending on the
    column's numpy dtype."""
    if v is None:
        return True
    try:
        return math.isnan(v)
    except TypeError:
        return False


def _scalar(v: Any) -> Any:
    """JSON-able python scalar from a numpy/arrow cell value."""
    if _is_missing(v):
        return None
    if isinstance(v, bytes):
        return v.decode()
    if hasattr(v, "item"):
        return v.item()
    return v


# merge(state_value, batch_value) -> state_value; batch_value is the
# partial aggregate over THIS batch's new rows only, so merging is exact
# for any associative fn. A missing batch value (all-null group) leaves
# the state untouched; a missing state value adopts the batch value —
# this reproduces pandas sum(min_count=1) semantics at the stream level.
_MERGE = {
    "sum": lambda s, b: b if s is None else (s if b is None else s + b),
    "count": lambda s, b: (s or 0) + (b or 0),
    "min": lambda s, b: b if s is None else (s if b is None else min(s, b)),
    "max": lambda s, b: b if s is None else (s if b is None else max(s, b)),
}


class StreamSpec:
    """Serializable incremental group-by aggregation spec.

    keys: [{"col": input column, "name": output name}]
    aggs: [{"fn": sum|count|min|max, "col": input column,
            "name": output name}] — mergeable fns only (derive avg from
    sum/count downstream; a non-associative fn cannot be checkpointed as
    per-group scalars).

    The spec round-trips through JSON (`to_doc`/`from_doc`) so a stream
    can be reconstructed from its journal's `stream_open` record at
    adoption time, by a process that never saw the original plan."""

    def __init__(self, schema: T.Schema, keys: List[Dict[str, str]],
                 aggs: List[Dict[str, str]]) -> None:
        if not keys or not aggs:
            raise ValueError("StreamSpec needs >= 1 key and >= 1 agg")
        for a in aggs:
            if a["fn"] not in _MERGE:
                raise ValueError(
                    f"agg fn {a['fn']!r} is not mergeable "
                    f"(have: {sorted(_MERGE)})")
        self.schema = schema
        self.keys = [dict(k) for k in keys]
        self.aggs = [dict(a) for a in aggs]

    # -- serialization ---------------------------------------------------

    def to_doc(self) -> Dict[str, Any]:
        return {
            "fields": [{"name": f.name, "dtype": _DTYPE_NAMES[f.dtype]}
                       for f in self.schema.fields],
            "keys": [dict(k) for k in self.keys],
            "aggs": [dict(a) for a in self.aggs],
        }

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "StreamSpec":
        schema = T.Schema([T.Field(f["name"], _DTYPES[f["dtype"]])
                           for f in doc["fields"]])
        return cls(schema, doc["keys"], doc["aggs"])

    # -- plan construction ----------------------------------------------

    def _dtype_of(self, name: str) -> T.DataType:
        return self.schema.fields[self.schema.index_of(name)].dtype

    def _agg_dtype(self, a: Dict[str, str]) -> T.DataType:
        return T.INT64 if a["fn"] == "count" else self._dtype_of(a["col"])

    def key_names(self) -> List[str]:
        return [k["name"] for k in self.keys]

    def agg_names(self) -> List[str]:
        return [a["name"] for a in self.aggs]

    def build_plan(self, files: List[str], shuffle_parts: int):
        """The per-batch plan over exactly `files`: two-phase hash agg
        with a shuffle on the first key (the q2 shape, validator.py)."""
        sc = P.scan(self.schema, [(p, []) for p in files])
        group = [col(k["col"]) for k in self.keys]
        names = self.key_names()
        key_fields = [T.Field(k["name"], self._dtype_of(k["col"]))
                      for k in self.keys]
        aggs = [{"fn": a["fn"], "args": [col(a["col"])],
                 "dtype": self._agg_dtype(a), "name": a["name"]}
                for a in self.aggs]
        partial = P.hash_agg(sc, "partial", group, names, aggs,
                             T.Schema(key_fields))
        x = P.shuffle_exchange(partial, [col(names[0])], shuffle_parts)
        final_fields = key_fields + [T.Field(a["name"], self._agg_dtype(a))
                                     for a in self.aggs]
        return P.hash_agg(x, "final", group, names, aggs,
                          T.Schema(final_fields))


class TailSource:
    """Tails a growing directory of immutable parquet files.

    Contract (Spark FileStreamSource posture): writers publish each file
    ATOMICALLY (write a temp name, os.rename into place) and never
    append to a published file — so a file name is a complete, immutable
    unit of input and `{file name: row count}` is a complete offset.
    `publish()` wraps that idiom for producers."""

    def __init__(self, directory: str, pattern: str = "*.parquet") -> None:
        self.directory = directory
        self.pattern = pattern

    def _matched(self) -> List[str]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(n for n in names if fnmatch.fnmatch(n, self.pattern))

    def discover(self, consumed: Dict[str, int]) -> List[str]:
        """Basenames of published-but-unconsumed files, oldest-first
        (name order — producers number their files)."""
        return [n for n in self._matched() if n not in consumed]

    def lag_ms(self, consumed: Dict[str, int],
               now: Optional[float] = None) -> float:
        """End-to-end lag: age of the OLDEST unconsumed file (0 when
        caught up) — the stream's watermark distance."""
        pending = self.discover(consumed)
        if not pending:
            return 0.0
        now = time.time() if now is None else now
        oldest = min(self._mtime(n) for n in pending)
        return max(now - oldest, 0.0) * 1000.0

    def _mtime(self, name: str) -> float:
        try:
            return os.path.getmtime(os.path.join(self.directory, name))
        except OSError:
            return time.time()

    def path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def rows_in(self, name: str) -> int:
        import pyarrow.parquet as pq

        return int(pq.ParquetFile(self.path(name)).metadata.num_rows)

    def publish(self, name: str, table) -> str:
        """Producer helper: write `table` (pyarrow Table) under a temp
        name, fsync-rename into `name` — readers never see a torn file."""
        import pyarrow.parquet as pq

        os.makedirs(self.directory, exist_ok=True)
        final = self.path(name)
        tmp = final + ".inprogress"
        pq.write_table(table, tmp)
        os.rename(tmp, final)
        return final

    def to_doc(self) -> Dict[str, str]:
        return {"directory": self.directory, "pattern": self.pattern}

    @classmethod
    def from_doc(cls, doc: Dict[str, str]) -> "TailSource":
        return cls(doc["directory"], doc.get("pattern", "*.parquet"))


class StreamingQuery:
    """One long-lived micro-batch aggregation with durable checkpoints.

    Construct (or `service.open_stream(...)` / `resume_stream(...)`),
    then `.start()`. Each micro-batch runs through `service.run()` when
    a QueryService is attached — admission weight, per-tenant quota,
    fair scheduling and per-batch SLO scoring all apply to every batch —
    else directly through local_runner.run_plan. `result_rows()` is the
    current aggregation state; `stop()` ends the loop (graceful=True
    settles the journal so retention may prune it; graceful=False leaves
    it adoptable)."""

    def __init__(self, stream_id: str, source: TailSource, spec: StreamSpec,
                 tenant_id: str = "", service=None, num_partitions: int = 2,
                 shuffle_parts: int = 2, work_dir: Optional[str] = None,
                 mesh_exchange: str = "off",
                 journal_dir: Optional[str] = None) -> None:
        self.stream_id = stream_id
        self.source = source
        self.spec = spec
        self.tenant_id = tenant_id
        self.service = service
        self.num_partitions = num_partitions
        self.shuffle_parts = shuffle_parts
        self.work_dir = work_dir
        self.mesh_exchange = mesh_exchange
        self._journal_dir = journal_dir or conf.journal_dir
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # exactly-once core: offsets + state + epoch move together, in
        # memory here and on disk in one checkpoint record
        self.offsets: Dict[str, int] = {}
        self.state: Dict[Tuple, Dict[str, Any]] = {}
        self.epoch = 0
        self.rows_total = 0
        self.batches_total = 0
        self.batch_failures = 0
        self.resumed_batches = 0
        self.resumed_from_epoch: Optional[int] = None
        self.checkpoint_bytes = 0
        self.last_checkpoint_epoch = 0
        self.lag_ms = 0.0
        self._prev_lag_ms = 0.0
        self._resumed = False
        self._journal: Optional[journal.QueryJournal] = None
        self.error: Optional[str] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "StreamingQuery":
        if self._journal_dir:
            jnl = journal.QueryJournal(self.stream_id, self._journal_dir)
            resumed = self._restore_from_checkpoint(jnl)
            # pid re-stamp: the LAST admitted record is the liveness tag
            # the recovery scan keys on, so an adopter owns the journal
            jnl.admitted(tenant_id=self.tenant_id)
            jnl.record(
                "stream_open", pid=os.getpid(), tenant_id=self.tenant_id,
                spec=self.spec.to_doc(), source=self.source.to_doc(),
                num_partitions=self.num_partitions,
                shuffle_parts=self.shuffle_parts,
                mesh_exchange=self.mesh_exchange,
                resumed_from_epoch=resumed)
        with _registry_lock:
            _streams[self.stream_id] = self
        if conf.progress_enabled:
            from blaze_tpu.runtime import progress

            progress.begin_stream(self.stream_id, self.tenant_id)
        self._thread = threading.Thread(
            target=self._loop, name=f"blz-stream-{self.stream_id}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, graceful: bool = True, timeout: float = 30.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)
        if graceful:
            with self._lock:
                if self._journal is not None:
                    if self.epoch > self.last_checkpoint_epoch:
                        self._checkpoint_locked()
                    self._journal.complete("ok")
                    self._journal = None
        with _registry_lock:
            if _streams.get(self.stream_id) is self:
                del _streams[self.stream_id]
        if conf.progress_enabled:
            from blaze_tpu.runtime import progress

            progress.finish_query(self.stream_id)

    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- resume ----------------------------------------------------------

    def _restore_from_checkpoint(
            self, jnl: journal.QueryJournal) -> Optional[int]:
        """Adopt the last parseable checkpoint (torn tails were already
        skipped by load_records — the mid-checkpoint-SIGKILL fallback).
        Returns the restored epoch, or None if nothing was durable."""
        records = journal.load_records(jnl.path)
        ckpt = None
        for r in records:
            if r.get("kind") == "stream_checkpoint":
                ckpt = r
        with self._lock:
            self._journal = jnl
            if ckpt is None:
                return None
            self.offsets = {str(k): int(v)
                            for k, v in (ckpt.get("offsets") or {}).items()}
            self.state = {tuple(k): dict(v)
                          for k, v in (ckpt.get("state") or [])}
            self.epoch = int(ckpt.get("epoch", 0))
            self.last_checkpoint_epoch = self.epoch
            self.rows_total = int(ckpt.get("rows_total", 0))
            self.resumed_from_epoch = self.epoch
            self._resumed = True
            epoch, files = self.epoch, len(self.offsets)
            rows, groups = self.rows_total, len(self.state)
        trace.event("stream_resume", query_id=self.stream_id,
                    epoch=epoch, files_consumed=files,
                    rows_total=rows, groups=groups)
        return epoch

    # -- the micro-batch loop --------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                consumed = dict(self.offsets)
            new = self.source.discover(consumed)
            lag = self.source.lag_ms(consumed)
            with self._lock:
                self._prev_lag_ms, self.lag_ms = self.lag_ms, lag
            if conf.progress_enabled:
                from blaze_tpu.runtime import progress

                progress.stream_lag(self.stream_id, lag)
            self._maybe_stall(lag, pending=len(new))
            if not new:
                self._stop.wait(max(conf.stream_poll_ms, 1) / 1000.0)
                continue
            try:
                self._run_batch(new, lag)
            except faults.AdmissionRejected:
                # shed batch: input stays unconsumed; lag grows until
                # admission relents (the stall dossier tells the story)
                self._stop.wait(max(conf.stream_poll_ms, 1) / 1000.0)
            except Exception as e:  # noqa: BLE001 — retry next tick
                with self._lock:
                    self.batch_failures += 1
                    self.error = f"{type(e).__name__}: {e}"
                self._stop.wait(max(conf.stream_poll_ms, 1) / 1000.0)
            # a successful batch loops straight back to discover so a
            # backlog drains at full speed, not one file per poll tick

    def _run_batch(self, names: List[str], lag: float) -> None:
        t0 = time.time()
        batch_rows = {n: self.source.rows_in(n) for n in names}
        plan = self.spec.build_plan([self.source.path(n) for n in names],
                                    self.shuffle_parts)
        with self._lock:
            epoch = self.epoch + 1
            prev_lag = self._prev_lag_ms
        run_info: Dict[str, Any] = {"stream": {
            "stream_id": self.stream_id, "epoch": epoch,
            "lag_ms": round(lag, 1),
            "prev_lag_ms": round(prev_lag, 1),
            "max_lag_ms": conf.stream_max_lag_ms,
            "files": len(names)}}
        if self.service is not None:
            out = self.service.run(
                plan, self.tenant_id, run_info=run_info,
                num_partitions=self.num_partitions,
                work_dir=self.work_dir, mesh_exchange=self.mesh_exchange)
        else:
            from blaze_tpu.spark.local_runner import run_plan

            out = run_plan(plan, num_partitions=self.num_partitions,
                           work_dir=self.work_dir,
                           mesh_exchange=self.mesh_exchange,
                           run_info=run_info)
        rows = sum(batch_rows.values())
        batch_ms = (time.time() - t0) * 1000.0
        with self._lock:
            self._merge_locked(out)
            self.offsets.update(batch_rows)
            self.epoch = epoch
            self.rows_total += rows
            self.batches_total += 1
            if self._resumed:
                self.resumed_batches += 1
            self.lag_ms = self.source.lag_ms(self.offsets)
            lag_now = self.lag_ms
            resumed = self._resumed
            due = (epoch - self.last_checkpoint_epoch
                   >= max(int(conf.stream_checkpoint_interval), 1))
            if due and self._journal is not None:
                self._checkpoint_locked()
        trace.event("stream_batch", query_id=self.stream_id, epoch=epoch,
                    rows=rows, files=len(names),
                    batch_ms=round(batch_ms, 1), lag_ms=round(lag, 1),
                    resumed=resumed)
        if conf.progress_enabled:
            from blaze_tpu.runtime import progress

            progress.stream_batch(self.stream_id, epoch, rows, lag_now,
                                  batch_ms, resumed=resumed)

    def _merge_locked(self, batch) -> None:
        d = batch.to_numpy()
        keys = self.spec.key_names()
        n = len(next(iter(d.values()))) if d else 0
        for i in range(n):
            k = tuple(_scalar(d[name][i]) for name in keys)
            slot = self.state.setdefault(
                k, {a: None for a in self.spec.agg_names()})
            for a in self.spec.aggs:
                name = a["name"]
                slot[name] = _MERGE[a["fn"]](slot[name],
                                             _scalar(d[name][i]))

    # -- durability ------------------------------------------------------

    def _checkpoint_locked(self) -> None:
        """ONE crash-atomic record carrying offsets + state + epoch: the
        exactly-once invariant is that these three never part ways."""
        state_doc = [[list(k), v] for k, v in
                     sorted(self.state.items(),
                            key=lambda kv: json.dumps(kv[0], default=str))]
        fields = {"epoch": self.epoch, "offsets": dict(self.offsets),
                  "state": state_doc, "rows_total": self.rows_total}
        self.checkpoint_bytes = len(json.dumps(fields, default=str))
        self._journal.record("stream_checkpoint",
                             state_bytes=self.checkpoint_bytes, **fields)
        self.last_checkpoint_epoch = self.epoch
        trace.event("stream_checkpoint", query_id=self.stream_id,
                    epoch=self.epoch, state_bytes=self.checkpoint_bytes,
                    files_consumed=len(self.offsets),
                    groups=len(self.state))

    def _maybe_stall(self, lag: float, pending: int) -> None:
        """Sustained lag past the objective with work pending — cut ONE
        stream_stall dossier per stream (flight_recorder dedups on
        (query_id, trigger))."""
        if not pending or lag <= max(float(conf.stream_max_lag_ms), 0.0):
            return
        from blaze_tpu.runtime import flight_recorder

        if not flight_recorder.enabled("stream_stall"):
            return
        with self._lock:
            epoch, failures = self.epoch, self.batch_failures
            last_error = self.error
        flight_recorder.capture(
            "stream_stall", self.stream_id, tenant_id=self.tenant_id or None,
            detail={"lag_ms": round(lag, 1),
                    "max_lag_ms": conf.stream_max_lag_ms,
                    "pending_files": pending, "epoch": epoch,
                    "batch_failures": failures,
                    "last_error": last_error})

    # -- introspection ---------------------------------------------------

    def result_rows(self) -> List[Dict[str, Any]]:
        """Current state as sorted rows (key cols + agg cols) — the
        stream-level answer a pandas replay of the full input must
        equal."""
        keys = self.spec.key_names()
        with self._lock:
            items = list(self.state.items())
        items.sort(key=lambda kv: json.dumps(kv[0], default=str))
        return [dict(zip(keys, k), **v) for k, v in items]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "stream_id": self.stream_id,
                "tenant_id": self.tenant_id,
                "epoch": self.epoch,
                "lag_ms": round(self.lag_ms, 3),
                "batches_total": self.batches_total,
                "batch_failures": self.batch_failures,
                "rows_total": self.rows_total,
                "checkpoint_bytes": self.checkpoint_bytes,
                "resumed_batches": self.resumed_batches,
                "resumed_from_epoch": self.resumed_from_epoch,
                "files_consumed": len(self.offsets),
                "groups": len(self.state),
            }

    def wait_consumed(self, files: int, timeout: float = 60.0) -> bool:
        """Block until >= `files` source files are consumed AND
        checkpointed (or timeout) — the test/chaos synchronization
        point."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if (len(self.offsets) >= files
                        and self.last_checkpoint_epoch >= self.epoch):
                    return True
            if not self.alive():
                return False
            time.sleep(0.02)
        return False


# -- module-level registry / adoption ----------------------------------------


def live_streams() -> List[str]:
    with _registry_lock:
        return sorted(_streams)


def get(stream_id: str) -> Optional[StreamingQuery]:
    with _registry_lock:
        return _streams.get(stream_id)


def stream_stats() -> Dict[str, Dict[str, Any]]:
    """Per-live-stream counters for the monitor gauges
    (blaze_stream_lag_ms / _batches_total / _checkpoint_bytes) and the
    blaze_top streams rows."""
    with _registry_lock:
        streams = list(_streams.values())
    return {s.stream_id: s.stats() for s in streams}


def open_stream(source: TailSource, spec: StreamSpec, *,
                stream_id: Optional[str] = None, tenant_id: str = "",
                service=None, **kwargs: Any) -> StreamingQuery:
    """Construct + start a stream (the QueryService wiring calls this)."""
    sid = stream_id or f"stream-{trace.new_query_id()}"
    return StreamingQuery(sid, source, spec, tenant_id=tenant_id,
                          service=service, **kwargs).start()


def adoptable_streams() -> Dict[str, str]:
    """{stream_id: journal path} registered by the recovery scan —
    dead-writer stream journals waiting for an adopter."""
    return journal.adoptable_streams()


def resume_stream(stream_id: str, *, journal_dir: Optional[str] = None,
                  service=None, work_dir: Optional[str] = None,
                  tenant_id: Optional[str] = None) -> StreamingQuery:
    """Adopt a dead writer's stream: reconstruct the TailSource +
    StreamSpec from the journal's stream_open record, restore the last
    checkpoint, re-stamp the writer pid, and resume ticking. Used by the
    standby driver after takeover and by a restarted embedder."""
    d = journal_dir or conf.journal_dir
    if not d:
        raise ValueError("resume_stream needs a journal directory")
    journal.claim_adoptable_stream(stream_id)  # consume the registration
    records = journal.load_records(journal.journal_path(stream_id, d))
    opened = None
    for r in records:
        if r.get("kind") == "stream_open":
            opened = r
    if opened is None:
        raise ValueError(f"no stream_open record for {stream_id!r} in {d}")
    sq = StreamingQuery(
        stream_id,
        TailSource.from_doc(opened["source"]),
        StreamSpec.from_doc(opened["spec"]),
        tenant_id=(tenant_id if tenant_id is not None
                   else opened.get("tenant_id", "")),
        service=service,
        num_partitions=int(opened.get("num_partitions", 2)),
        shuffle_parts=int(opened.get("shuffle_parts", 2)),
        work_dir=work_dir,
        mesh_exchange=opened.get("mesh_exchange", "off"),
        journal_dir=d)
    return sq.start()


def reset() -> None:
    """Stop + drop every live stream (test isolation); journals are left
    alone (adoptable, like the rest of the durability layer)."""
    with _registry_lock:
        streams = list(_streams.values())
        _streams.clear()
    for s in streams:
        s._stop.set()
    for s in streams:
        t = s._thread
        if t is not None:
            t.join(timeout=5.0)
