"""URI-scheme filesystem routing (the Hadoop-FS indirection analog).

Ref: the reference routes ALL file IO through the JVM's Hadoop
`FileSystem` resolved per URI (datafusion-ext-commons/src/hadoop_fs.rs:
23-132; parquet_exec.rs:218-301 opens via FsProvider), so scans and sinks
work against hdfs://, s3a://, etc. Out of process the equivalent
resolver is fsspec: any path carrying a `scheme://` opens through
`fsspec.open`, plain paths stay on the local fast path (pyarrow opens
them directly). An explicit `fs_resource_id` on the operator still takes
precedence — that hook is the embedding's per-deployment override, this
module is the default resolver behind it.
"""

from __future__ import annotations

import re
from typing import Optional

# scheme per RFC 3986; single letters excluded so C:\windows paths and
# the degenerate "a:b" stay local
_SCHEME_RE = re.compile(r"^([A-Za-z][A-Za-z0-9+.-]+)://")


def path_scheme(path: str) -> Optional[str]:
    m = _SCHEME_RE.match(path)
    if not m:
        return None
    s = m.group(1).lower()
    return None if s == "file" else s


def open_input(path: str):
    """An open readable binary handle for a remote URI, or the path
    itself for local files (callers hand either to pyarrow)."""
    if path_scheme(path) is None:
        return path.removeprefix("file://")
    import fsspec

    return fsspec.open(path, "rb").open()


def open_output(path: str):
    if path_scheme(path) is None:
        return path.removeprefix("file://")
    import fsspec

    return fsspec.open(path, "wb").open()


def exists(path: str) -> bool:
    import os

    s = path_scheme(path)
    if s is None:
        return os.path.exists(path.removeprefix("file://"))
    import fsspec

    fs, p = fsspec.core.url_to_fs(path)
    return fs.exists(p)


def size(path: str) -> int:
    import os

    s = path_scheme(path)
    if s is None:
        p = path.removeprefix("file://")
        return os.path.getsize(p) if os.path.exists(p) else 0
    import fsspec

    fs, p = fsspec.core.url_to_fs(path)
    return int(fs.size(p)) if fs.exists(p) else 0
