"""Process-isolated executor pool: crash containment for the runtime.

Ref: Spark's executor model (PAPER.md §1 — Spark remains the
distributed runtime; executors die, the driver detects it, lost
partitions are re-executed from persisted shuffle artifacts). This
module is that driver/executor split for the local runtime: N worker
PROCESSES, each owning a virtual device slice, receive TaskSpecs over a
length-prefixed control socket (the serde frame discipline —
runtime/shuffle_server.py holds the shared framing) and read upstream
shuffle input from the driver's ShuffleServer, so one hard fault (OOM
kill, segfault, wedged interpreter) costs ONE process, not the service.

The robustness path, not the transport, is the point:

  heartbeat   every worker pushes beats over the control socket; ANY
              inbound frame refreshes liveness (supervisor.ProcessPeer —
              the thread heartbeat posture generalized to PIDs).

  death       supervisor.ProcessWatchdog declares an executor dead on
              reap/exit (exact exit code / killing signal) or heartbeat
              staleness past conf.executor_death_ms — the latter may be
              a ZOMBIE that is still running.

  fencing     every task attempt carries an epoch (artifacts.EpochFence)
              stamped into its TaskSpec, its shuffle artifact names
              (`shuffle_0_1.e2.data`) and the result accounting: a
              re-queue advances the fence, so a zombie's late result is
              rejected at the driver (never double-counted) and its late
              files land on stale names that get swept — they can never
              overwrite the retried attempt's artifacts.

  lineage     only the LOST partitions re-execute: completed map outputs
              live in driver-committed .data/.index files served by the
              ShuffleServer, so surviving artifacts are re-read, not
              recomputed. Re-queues are bounded with exponential backoff.

  degradation on a death the pool's membership callbacks fire — the
              QueryService recomputes admission capacity as
              live_executors x conf.executor_slots, parks (re-queues)
              displaced arrivals instead of failing them, and restores
              capacity when the replacement process (bounded by
              conf.executor_restart_max, backed off) rejoins.

  telemetry   the cross-process observability plane (ISSUE 14). Each
              worker runs its own bounded TraceLog ring
              (conf.executor_trace_events) and monitor counters, stamps
              records with the driver-issued correlation ids replayed
              from the task payload, and ships batched deltas back as
              "telemetry" frames on the control socket — every
              conf.telemetry_ship_ms AND immediately before each result
              frame, so counters are federated before the driver closes
              the stage span that reads them. Before every ship the
              batch is spilled crash-atomically to a per-worker sidecar
              file (<token>.telemetry); on a death the driver recovers
              the unshipped tail from the sidecar, idempotently (batch
              seq watermark), marking the records truncated=true. A
              clock-offset estimate from the hello echo (bounded by
              conf.clock_skew_bound_ms, refined by the min observed
              transit) rebases worker monotonic timestamps onto the
              driver's, so one merged Chrome trace renders a pid row
              per executor. Frames from a declared-dead (zombie) handle
              are dropped — the sidecar already covered them; accepting
              both would double-count.

Worker processes are spawned as `python -m
blaze_tpu.runtime.executor_pool --worker` with their identity and socket
paths in the environment; the driver-side conf snapshot rides along so
knobs agree across the process boundary.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from blaze_tpu.config import KNOBS, conf
from blaze_tpu.runtime import shuffle_server as ss

_ENV_TOKEN = "BLAZE_EXEC_TOKEN"
_ENV_SEAT = "BLAZE_EXEC_SEAT"
_ENV_CTL = "BLAZE_EXEC_SOCK"
_ENV_SHUFFLE = "BLAZE_EXEC_SHUFFLE_SOCK"
_ENV_CONF = "BLAZE_TPU_WORKER_CONF"

# knobs a worker must NOT inherit verbatim: a worker never spawns its own
# pool, never serves metrics, and never EXPORTS traces/dossiers/history
# (the driver owns exporting; worker-side trace records buffer in the
# local ring and ship back over the control socket — _spawn additionally
# sets trace_enabled/trace_buffer_events dynamically from the driver's
# tracing state)
_WORKER_CONF_OVERRIDES = {
    "executor_count": 0,
    "metrics_port": 0,
    "trace_export_dir": "",
    "history_dir": "",
    "flight_dir": "",
    "progress_enabled": False,
    "fault_injection_spec": {},
    # only the driver journals (one journal per query) or replays them
    "journal_dir": "",
    "recovery_enabled": False,
}


def _clamp_offset(offset_ns: int) -> int:
    """Bound a clock-offset estimate to ±conf.clock_skew_bound_ms: one
    bad echo (a worker descheduled mid-handshake) must not scramble
    merged-trace ordering by seconds."""
    bound = max(int(conf.clock_skew_bound_ms), 0) * 1_000_000
    return max(-bound, min(bound, int(offset_ns)))


class PoolTaskSpec:
    """One schedulable unit for the process pool (the TaskSpec twin for
    the process boundary: everything must be serializable). `key` is the
    fence key — unique per logical task; `payload` is the JSON header the
    worker dispatches on; `blob` carries the plan proto bytes."""

    __slots__ = ("key", "kind", "payload", "blob", "what")

    def __init__(self, key: str, kind: str, payload: Optional[dict] = None,
                 blob: bytes = b"", what: str = "") -> None:
        self.key = key
        self.kind = kind
        self.payload = dict(payload or {})
        self.blob = blob
        self.what = what or key


class _PoolTask:
    """Pool-internal task state: current epoch, retry/death budgets, and
    the terminal outcome."""

    __slots__ = ("spec", "epoch", "state", "result", "error", "tries",
                 "death_requeues", "not_before", "executor")

    def __init__(self, spec: PoolTaskSpec, epoch: int) -> None:
        self.spec = spec
        self.epoch = epoch
        self.state = "queued"  # queued | running | done | error
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.tries = 0
        self.death_requeues = 0
        self.not_before = 0.0
        self.executor: Optional["ExecutorHandle"] = None

    @property
    def finished(self) -> bool:
        return self.state in ("done", "error")


class ExecutorHandle:
    """Driver-side view of one executor process."""

    def __init__(self, seat: int, generation: int, token: str, pid: int,
                 proc: Optional[subprocess.Popen],
                 conn: socket.socket) -> None:
        self.seat = seat
        self.generation = generation
        self.token = token
        self.pid = pid
        self.proc = proc
        self.conn = conn
        self.send_lock = threading.Lock()
        self.inflight: Dict[str, _PoolTask] = {}  # guarded by pool lock
        self.dead = False                         # guarded by pool lock
        self.closing = False
        # partition tolerance (guarded by pool lock): conn_broken marks
        # a transport error on a seat whose PROCESS is still alive — the
        # seat keeps its in-flight tasks and waits for the worker's
        # resume handshake, bounded by the watchdog's heartbeat
        # staleness (executor_death_ms). draining marks a seat finishing
        # in-flight work before a graceful exit; drained marks the drain
        # completed (seat removed without a death).
        self.conn_broken = False
        self.draining = False
        # drain barrier (guarded by send_lock, NOT the pool lock): set
        # just before the drain_ack frame goes on the wire. A dispatch
        # that acquires send_lock and finds it set must NOT send — the
        # worker may sample idle and exit the moment it reads the ack,
        # and the control socket is FIFO, so anything sent after the
        # ack can be lost without a requeue signal.
        self.drain_acked = False
        self.drained = False
        self.decommissioned = False
        self.reconnects = 0
        self.joined_at = time.monotonic()
        self.last_beat = self.joined_at
        # telemetry federation state (guarded by pool lock):
        # clock_offset_ns rebases this worker's monotonic timestamps
        # onto the driver's; tel_seq is the highest batch ingested (the
        # sidecar-recovery dedup watermark)
        self.clock_offset_ns = 0
        self.tel_seq = 0
        self.tel_bytes = 0
        self.tel_records = 0
        self.tel_dropped = 0
        self.tasks_done = 0

    @property
    def exec_id(self) -> str:
        return f"exec{self.seat}"


class PoolUnavailableError(ConnectionError):
    """No live executor can run a queued task and no replacement is
    pending: callers degrade to the in-process runtime."""


class ExecutorPool:
    """Spawns, supervises, feeds and buries executor processes.

    Lifecycle: `start()` spawns conf.executor_count workers and waits
    for their control-socket handshakes; `run_tasks(specs)` executes a
    batch with epoch-fenced re-queue on executor death; `close()` tears
    everything down. `activate(pool)` publishes the pool process-wide so
    the local runner routes eligible stages here and the service derives
    its admission capacity from membership."""

    _READY_TIMEOUT = 90.0
    _HELLO_TIMEOUT = 30.0

    def __init__(self, count: Optional[int] = None,
                 slots: Optional[int] = None) -> None:
        self.count = int(count if count is not None
                         else conf.executor_count)
        self.slots = max(1, int(slots if slots is not None
                                else conf.executor_slots))
        from blaze_tpu.runtime import artifacts, supervisor

        self.fence = artifacts.EpochFence()
        self.watchdog = supervisor.ProcessWatchdog()
        self._dir = tempfile.mkdtemp(prefix="blzex-")
        # pool-unique token prefix: two pools in one process (tests, a
        # service restart) must not collide in the flight recorder's
        # (query_id, trigger) exactly-once dedup or the watchdog registry
        self._pool_id = os.path.basename(self._dir)[len("blzex-"):]
        self._ctl_path = os.path.join(self._dir, "ctl.sock")
        self.server = ss.ShuffleServer(os.path.join(self._dir, "shf.sock"))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._seats: Dict[int, ExecutorHandle] = {}
        # declared-dead handles: a heartbeat-dead ZOMBIE's socket stays
        # open (its late results must arrive to be fenced) and its
        # process may still run — close() reaps whatever is left here
        self._graveyard: List[ExecutorHandle] = []
        self._awaiting: Dict[str, tuple] = {}  # token -> (seat, gen, proc)
        self._queue: List[_PoolTask] = []
        self._running: Dict[str, _PoolTask] = {}
        # task key -> winning attempt epoch, recorded at completion:
        # lets _on_result tell a re-delivered duplicate of the winner
        # (files are LIVE — keep) from a zombie's stale epoch (sweep)
        self._done_epochs: "OrderedDict[str, int]" = OrderedDict()
        self._seat_restarts: Dict[int, int] = {}
        self._respawns_pending = 0
        # seat indexes with a replacement in flight (the count above
        # can't answer "is THIS seat coming back" — spawn() must not
        # hand an autoscaler a seat the respawn path is about to fill)
        self._respawn_seats: set = set()
        # next free generation per seat: tokens must never repeat (the
        # watchdog registry and the flight recorder's exactly-once
        # dedup key on them), even across decommission + re-spawn
        self._next_gen: Dict[int, int] = {}
        # standby takeover (rebind): manifest seats whose process was
        # alive at takeover — token -> (seat, generation, pid); their
        # resume hello adopts them instead of being refused
        self._adoptable: Dict[str, tuple] = {}
        self.adopted_total = 0
        self._membership_cbs: List[Callable[["ExecutorPool"], None]] = []
        self._closed = False
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self.deaths_total = 0
        self.restarts_total = 0
        self.reconnects_total = 0
        self.drains_total = 0
        # tasks a drain's grace period cut off (requeued, no death
        # budget). The rolling-restart gate demands this stays 0: a
        # graceful drain must FINISH its in-flight work, not shed it.
        self.drain_requeues_total = 0
        self.tasks_done = 0
        self.telemetry_bytes_total = 0
        self.telemetry_records_total = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ExecutorPool":
        with self._lock:
            count = self.count          # spawn() grows it under _lock
        if count <= 0:
            raise ValueError("executor pool needs count >= 1")
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self._ctl_path)
        listener.listen(count * 2 + 4)
        self._listener = listener
        self.server.start()
        for name, target in (("blz-pool-accept", self._accept_loop),
                             ("blz-pool-dispatch", self._dispatch_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        for seat in range(count):
            self._spawn(seat, 0)
        deadline = time.monotonic() + self._READY_TIMEOUT
        with self._cv:
            while (len([h for h in self._seats.values() if not h.dead])
                   < self.count):
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"executor pool: {len(self._seats)}/{self.count} "
                        f"workers joined within {self._READY_TIMEOUT}s")
                self._cv.wait(min(left, 0.25))
        return self

    # -- elastic fleet & driver HA -------------------------------------

    def spawn(self) -> Optional[int]:
        """Scale-up actuator (runtime/autoscaler.py): start one NEW
        worker on the lowest seat index that is neither occupied, nor
        awaiting its hello, nor about to be refilled by a respawn.
        Returns the seat (None when the pool is closed); the seat joins
        capacity when its handshake lands — callers watch membership
        callbacks rather than blocking here."""
        with self._cv:
            if self._closed:
                return None
            taken = set(self._seats)
            taken.update(s for s, _g, _p in self._awaiting.values())
            taken.update(self._respawn_seats)
            seat = 0
            while seat in taken:
                seat += 1
            self.count = max(self.count, seat + 1)
        self._spawn(seat, 0)
        return seat

    def manifest(self) -> dict:
        """Fleet manifest for the warm standby (runtime/standby.py):
        enough topology to rebind the control plane after a driver
        death. The socket DIRECTORY outlives the driver process, and
        surviving workers keep re-dialing ctl_path until their lease
        expires — so a standby that binds the same path inside the
        lease window inherits the fleet."""
        with self._lock:
            seats = [{"seat": h.seat, "generation": h.generation,
                      "token": h.token, "pid": h.pid}
                     for h in self._seats.values() if not h.dead]
            count = self.count
        return {"pool_id": self._pool_id, "dir": self._dir,
                "ctl_path": self._ctl_path,
                "shuffle_path": self.server.sock_path,
                "count": count, "slots": self.slots,
                "pid": os.getpid(), "seats": seats}

    @classmethod
    def rebind(cls, manifest: dict) -> "ExecutorPool":
        """Standby takeover, step 1: construct a pool wired to the DEAD
        primary's socket topology instead of a fresh temp dir. Call
        start_rebound() (not start()) to bind and adopt."""
        pool = cls(count=max(int(manifest.get("count", 1)), 1),
                   slots=int(manifest.get("slots", conf.executor_slots)))
        shutil.rmtree(pool._dir, ignore_errors=True)  # unused fresh dir
        pool._dir = manifest["dir"]
        pool._pool_id = (manifest.get("pool_id")
                         or os.path.basename(pool._dir))
        pool._ctl_path = manifest["ctl_path"]
        pool.server = ss.ShuffleServer(manifest["shuffle_path"])
        for s in manifest.get("seats") or []:
            pool._adoptable[s["token"]] = (int(s["seat"]),
                                           int(s["generation"]),
                                           int(s["pid"]))
            pool._next_gen[int(s["seat"])] = int(s["generation"]) + 1
        return pool

    def start_rebound(self, adopt_window_s: float = 5.0
                      ) -> "ExecutorPool":
        """Standby takeover, step 2: bind listener + shuffle server at
        the dead primary's socket paths (unlinking its stale socket
        FILES — the fds died with it) and re-own the fleet. Manifest
        seats whose pid is already gone are respawned fresh under a
        bumped generation; live ones are adopted as their bounded
        reconnect loop re-dials ctl_path (_resume). Seats still
        unclaimed after the adoption window get fresh workers too — a
        hung or partitioned survivor will self-fence on its own lease
        and must not hold a seat hostage."""
        from blaze_tpu.runtime import artifacts

        with self._lock:
            count = self.count
        if count <= 0:
            raise ValueError("executor pool needs count >= 1")
        for path in (self._ctl_path, self.server.sock_path):
            try:
                os.unlink(path)
            except OSError:
                pass
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self._ctl_path)
        listener.listen(count * 2 + 4)
        self._listener = listener
        self.server.start()
        for name, target in (("blz-pool-accept", self._accept_loop),
                             ("blz-pool-dispatch", self._dispatch_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        with self._cv:
            adoptable = dict(self._adoptable)
        for token, (seat, generation, pid) in sorted(adoptable.items()):
            if not artifacts._pid_alive(pid):
                with self._cv:
                    self._adoptable.pop(token, None)
                self._spawn(seat, generation + 1)
        deadline = time.monotonic() + max(adopt_window_s, 0.0)
        with self._cv:
            while self._adoptable and time.monotonic() < deadline:
                self._cv.wait(0.1)
            unclaimed, self._adoptable = dict(self._adoptable), {}
        for token, (seat, generation, _pid) in sorted(unclaimed.items()):
            self._spawn(seat, generation + 1)
        deadline = time.monotonic() + self._READY_TIMEOUT
        with self._cv:
            while (len([h for h in self._seats.values() if not h.dead])
                   < self.count):
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"rebound pool: {len(self._seats)}/{self.count} "
                        f"workers joined within {self._READY_TIMEOUT}s")
                self._cv.wait(min(left, 0.25))
        return self

    def _spawn(self, seat: int, generation: int) -> None:
        with self._lock:
            generation = max(generation, self._next_gen.get(seat, 0))
            self._next_gen[seat] = generation + 1
        token = f"exec{seat}g{generation}.{self._pool_id}"
        env = dict(os.environ)
        env[_ENV_TOKEN] = token
        env[_ENV_SEAT] = str(seat)
        env[_ENV_CTL] = self._ctl_path
        env[_ENV_SHUFFLE] = self.server.sock_path
        snapshot = {name: getattr(conf, name) for name in KNOBS}
        snapshot.update(_WORKER_CONF_OVERRIDES)
        # the worker traces exactly when the driver does — into its own
        # SMALL bounded ring (the driver-sized ring would let a chatty
        # worker hold megabytes of unshipped records)
        snapshot["trace_enabled"] = bool(conf.trace_enabled)
        snapshot["trace_buffer_events"] = int(conf.executor_trace_events)
        env[_ENV_CONF] = json.dumps(snapshot)
        # the worker resolves blaze_tpu by module name regardless of the
        # driver's cwd (pytest may chdir into a tmp dir)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        err_path = os.path.join(self._dir, f"{token}.err")
        with open(err_path, "ab") as err:
            proc = subprocess.Popen(
                [sys.executable, "-m", "blaze_tpu.runtime.executor_pool",
                 "--worker"],
                env=env, stdin=subprocess.DEVNULL,
                stdout=subprocess.DEVNULL, stderr=err)
        with self._cv:
            self._awaiting[token] = (seat, generation, proc)
        from blaze_tpu.runtime import trace

        trace.event("executor_spawn", exec_id=f"exec{seat}",
                    generation=generation, pid=proc.pid)

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._handshake, args=(conn,),
                             name="blz-pool-hello", daemon=True).start()

    def _handshake(self, conn: socket.socket) -> None:
        conn.settimeout(self._HELLO_TIMEOUT)
        try:
            msg, _blob = ss.recv_msg(conn)
        except (ConnectionError, OSError):
            conn.close()
            return
        conn.settimeout(None)
        token = msg.get("token", "")
        if msg.get("type") == "hello" and msg.get("resume"):
            self._resume(conn, token, msg)
            return
        with self._cv:
            pending = self._awaiting.pop(token, None)
        if msg.get("type") != "hello" or pending is None:
            conn.close()
            return
        seat, generation, proc = pending
        handle = ExecutorHandle(seat, generation, token,
                                int(msg.get("pid", proc.pid)), proc, conn)
        # clock-offset estimate from the hello echo: the worker stamps
        # its monotonic clock into the hello; (driver_now - worker_then)
        # = true offset + one-way transit, so the estimate is inflated
        # by transit and refined downward by later frames (_on_telemetry
        # keeps the minimum candidate — least transit, closest to truth)
        mono = msg.get("mono_ns")
        if mono is not None:
            handle.clock_offset_ns = _clamp_offset(
                time.monotonic_ns() - int(mono))
        with self._cv:
            if self._closed:
                handle.closing = True
            self._seats[seat] = handle
            self._cv.notify_all()
        if handle.closing:
            conn.close()
            return
        self.watchdog.register(
            token, handle.pid,
            lambda peer, reason, rc, h=handle: self._on_peer_death(
                h, reason, rc),
            poll=proc.poll)
        t = threading.Thread(target=self._reader, args=(handle, conn),
                             name=f"blz-pool-rd-{seat}", daemon=True)
        t.start()
        self._threads.append(t)
        self._notify_membership()

    def _resume(self, conn: socket.socket, token: str, msg: dict) -> None:
        """Session-resume handshake: a worker that survived a control-
        socket transport error reconnects with its token; the driver
        swaps the connection under the SAME handle (generation, epoch
        fence, telemetry watermark all continue) and re-sends every
        in-flight TaskSpec — the worker dedupes re-delivered specs by
        (task_id, epoch) and replies from its result cache for any it
        already finished. A blip costs a retry, not a seat."""
        from blaze_tpu.runtime import trace

        with self._cv:
            handle = next((h for h in self._seats.values()
                           if h.token == token and not h.dead), None)
            if handle is None or self._closed:
                handle = None
            else:
                old = handle.conn
                handle.conn = conn
                handle.conn_broken = False
                handle.last_beat = time.monotonic()
                handle.reconnects += 1
                self.reconnects_total += 1
                inflight = list(handle.inflight.values())
                self._cv.notify_all()
        if handle is None:
            if self._adopt(conn, token, msg):
                return
            # the seat was already declared dead (or the pool closed):
            # refusing the resume makes the worker's lease the authority
            conn.close()
            return
        try:
            old.close()
        except OSError:
            pass
        self.watchdog.beat(token)
        mono = msg.get("mono_ns")
        if mono is not None:
            cand = _clamp_offset(time.monotonic_ns() - int(mono))
            if cand < handle.clock_offset_ns:
                handle.clock_offset_ns = cand
        trace.event("control_reconnect", exec_id=handle.exec_id,
                    generation=handle.generation,
                    reconnects=handle.reconnects,
                    resent_tasks=len(inflight),
                    worker_tel_seq=int(msg.get("tel_seq", 0)))
        for task in inflight:
            header = {"type": "task", "task": task.spec.key,
                      "epoch": task.epoch, "kind": task.spec.kind,
                      "payload": task.spec.payload}
            try:
                ss.send_msg(conn, header, task.spec.blob,
                            lock=handle.send_lock)
            except (ConnectionError, OSError):
                self._conn_broken(handle, conn, "resume_send")
                return
        if handle.draining:
            # a decommission issued while the conn was broken never
            # reached the worker: re-deliver the drain order
            try:
                ss.send_msg(conn, {"type": "drain"},
                            lock=handle.send_lock)
            except (ConnectionError, OSError):
                self._conn_broken(handle, conn, "resume_send")
                return
        t = threading.Thread(target=self._reader, args=(handle, conn),
                             name=f"blz-pool-rd-{handle.seat}", daemon=True)
        t.start()
        self._threads.append(t)

    def _adopt(self, conn: socket.socket, token: str,
               msg: dict) -> bool:
        """Standby takeover: a surviving worker of the DEAD primary
        re-dialed the rebound listener with its resume hello. Its token
        matches no live handle here — but it does match the fleet
        manifest, so instead of refusing (which would self-fence a
        perfectly healthy process mid-task) the rebound pool adopts it:
        a fresh handle with proc=None (no child to reap — the watchdog
        falls back to pid-liveness), the worker's telemetry watermark
        carried over so sidecar recovery stays exactly-once."""
        from blaze_tpu.runtime import trace

        with self._cv:
            pending = self._adoptable.pop(token, None)
            if pending is None or self._closed:
                return False
            seat, generation, pid = pending
            cur = self._seats.get(seat)
            if cur is not None and not cur.dead:
                return False  # seat already refilled; lease buries it
        handle = ExecutorHandle(seat, generation, token,
                                int(msg.get("pid", pid)), None, conn)
        handle.tel_seq = int(msg.get("tel_seq", 0))
        mono = msg.get("mono_ns")
        if mono is not None:
            handle.clock_offset_ns = _clamp_offset(
                time.monotonic_ns() - int(mono))
        with self._cv:
            if self._closed:
                handle.closing = True
            self._seats[seat] = handle
            self._cv.notify_all()
        if handle.closing:
            conn.close()
            return True
        self.watchdog.register(
            token, handle.pid,
            lambda peer, reason, rc, h=handle: self._on_peer_death(
                h, reason, rc))
        t = threading.Thread(target=self._reader, args=(handle, conn),
                             name=f"blz-pool-rd-{seat}", daemon=True)
        t.start()
        self._threads.append(t)
        self.adopted_total += 1
        trace.event("executor_adopted", exec_id=handle.exec_id,
                    token=token, pid=handle.pid,
                    generation=generation,
                    worker_tel_seq=handle.tel_seq)
        self._notify_membership()
        return True

    # -- socket reader -------------------------------------------------

    def _reader(self, handle: ExecutorHandle, conn: socket.socket) -> None:
        """Per-executor inbound loop (one per CONNECTION — a resume
        starts a fresh reader on the new socket). Keeps reading a
        heartbeat-declared zombie's socket so its late results arrive —
        and get fenced — instead of rotting in the kernel buffer."""
        while True:
            rule = ss.net_rule("net.control.recv")
            try:
                msg, _blob = ss.recv_msg(conn, net_fault=rule)
            except (ConnectionError, OSError):
                break
            handle.last_beat = time.monotonic()
            self.watchdog.beat(handle.token)
            # "dup" at the recv point is a delivery property: the frame
            # arrives once, the message is processed twice — result and
            # telemetry dedup (epoch fence / running-map / seq
            # watermark) must absorb the double delivery
            for _ in range(2 if rule and rule.get("kind") == "dup" else 1):
                mtype = msg.get("type")
                if mtype == "result":
                    self._on_result(handle, msg)
                elif mtype == "telemetry":
                    self._on_telemetry(handle, msg)
                elif mtype == "draining":
                    self._on_draining(handle)
                elif mtype == "drained":
                    self._finish_drain(handle, msg)
        if not handle.closing:
            self._conn_broken(handle, conn, "recv")

    def _conn_broken(self, handle: ExecutorHandle, conn: socket.socket,
                     why: str) -> None:
        """Transport error triage: distinguish a BROKEN CONNECTION from a
        DEAD PROCESS before burning the seat. A reaped pid (or already-
        stale heartbeat) is a death; a draining seat's EOF is the drain
        completing; otherwise the seat enters conn_broken limbo — tasks
        stay in flight awaiting the worker's resume handshake, and the
        still-registered watchdog turns unresumed limbo into a heartbeat
        death after executor_death_ms."""
        from blaze_tpu.runtime import trace

        with self._cv:
            if handle.dead or self._closed or handle.conn is not conn:
                return  # already buried / resumed onto a newer socket
            draining = handle.draining
        rc = handle.proc.poll() if handle.proc else None
        if draining:
            # a draining worker exits after its "drained" frame; EOF
            # (or a crash mid-drain, caught by rc below) ends the drain
            if rc is None or rc == 0:
                self._finish_drain(handle, {})
            else:
                self._declare_dead(handle, "exit", rc)
            return
        if rc is not None:
            self._declare_dead(handle, "exit", rc)
            return
        stale_ms = (time.monotonic() - handle.last_beat) * 1000.0
        if stale_ms > max(int(conf.executor_death_ms), 1):
            self._declare_dead(handle, "heartbeat", None)
            return
        with self._cv:
            if handle.dead or handle.conn is not conn:
                return
            handle.conn_broken = True
            self._cv.notify_all()
        trace.event("partition_suspected", exec_id=handle.exec_id,
                    why=why, pid=handle.pid,
                    heartbeat_age_ms=round(stale_ms))
        try:
            conn.close()
        except OSError:
            pass

    def _on_peer_death(self, handle: ExecutorHandle, reason: str,
                       rc: Optional[int]) -> None:
        """Watchdog callback: route a clean exit of a DRAINING worker to
        drain completion (no dossier, no death accounting); everything
        else is a real death."""
        if reason == "drained" or (handle.draining and reason == "exit"
                                   and (rc == 0 or rc is None)):
            self._finish_drain(handle, {})
            return
        self._declare_dead(handle, reason, rc, emit_event=False)

    def _on_result(self, handle: ExecutorHandle, msg: dict) -> None:
        from blaze_tpu.runtime import artifacts

        key, epoch = msg.get("task", ""), int(msg.get("epoch", 0))
        if not self.fence.admit(key, epoch):
            # Rejected result: a ZOMBIE's stale-epoch files are losers
            # and must be swept — but a duplicate of the WINNER's reply
            # (the resume handshake re-delivers unacked results, and the
            # fence forgets keys at batch teardown) names the LIVE
            # committed artifacts a downstream read may be consuming.
            # The done-epoch ledger tells them apart.
            with self._cv:
                winner = self._done_epochs.get(key)
            if winner != epoch:
                for p in (msg.get("data_path"), msg.get("index_path")):
                    if p and artifacts.epoch_of(p) == epoch:
                        artifacts._unlink_quiet(p)
            return
        with self._cv:
            task = self._running.get(key)
            if task is None or task.epoch != epoch:
                return
            del self._running[key]
            handle.inflight.pop(key, None)
            if msg.get("ok"):
                task.state, task.result = "done", msg
                self.tasks_done += 1
                handle.tasks_done += 1
                # remember the winning epoch so late duplicates of this
                # very result are not mistaken for zombies (bounded)
                self._done_epochs[key] = epoch
                while len(self._done_epochs) > 4096:
                    self._done_epochs.popitem(last=False)
            else:
                self._handle_task_failure_locked(task, msg)
            self._cv.notify_all()

    # -- telemetry federation ------------------------------------------

    def _on_telemetry(self, handle: ExecutorHandle, msg: dict) -> None:
        """Ingest one batched telemetry frame from a live executor.

        Zombie posture mirrors _on_result: frames from a declared-dead
        handle are DROPPED — its unshipped tail was already recovered
        from the sidecar at death, and accepting the late socket copy
        too would double-count it. The batch seq watermark makes the
        sidecar recovery idempotent in the other direction (a sidecar
        whose batch already arrived over the socket is skipped)."""
        rule = ss.net_rule("net.telemetry")
        if rule:
            kind = rule.get("kind")
            if kind == "delay":
                time.sleep(float(rule.get("ms", 25)) / 1000.0)
            elif kind in ("reset", "blackhole", "torn"):
                # batch lost in transit: the worker's sidecar spill and
                # death-time recovery cover the gap — dropping telemetry
                # must never corrupt answers, only delay observability
                return
            # "dup": ingest twice below — the seq watermark must reject
            # the second copy
        for _ in range(2 if rule and rule.get("kind") == "dup" else 1):
            self._on_telemetry_inner(handle, msg)

    def _on_telemetry_inner(self, handle: ExecutorHandle,
                            msg: dict) -> None:
        with self._cv:
            if handle.dead or self._closed:
                return
            seq = int(msg.get("seq", 0))
            if seq <= handle.tel_seq:
                return  # duplicate / reordered batch
            handle.tel_seq = seq
            # refine the clock offset: every frame carries the worker's
            # send-time monotonic clock; the minimum candidate has the
            # least transit inflation
            mono = msg.get("mono_ns")
            if mono is not None:
                cand = _clamp_offset(time.monotonic_ns() - int(mono))
                if cand < handle.clock_offset_ns:
                    handle.clock_offset_ns = cand
        self._ingest_batch(handle, msg, truncated=False)

    def _ingest_batch(self, handle: ExecutorHandle, msg: dict,
                      truncated: bool) -> None:
        """Federate one telemetry batch (socket frame or recovered
        sidecar) into the driver's observability plane: trace records
        rebased + stamped into the ring, counter deltas merged into the
        per-query roll-ups, histogram deltas folded in."""
        from blaze_tpu.runtime import monitor, trace

        records = msg.get("records") or []
        n = trace.ingest_remote(records, exec_id=handle.exec_id,
                                pid=handle.pid,
                                offset_ns=handle.clock_offset_ns,
                                truncated=truncated)
        monitor.merge_remote(msg.get("counters") or {})
        monitor.merge_zerocopy(msg.get("zerocopy") or {})
        trace.ingest_histograms(msg.get("histograms") or {})
        if conf.profile_enabled and (msg.get("profile")
                                     or msg.get("profile_duty")):
            from blaze_tpu.runtime import profiler

            if msg.get("profile"):
                profiler.merge_remote(msg["profile"],
                                      exec_id=handle.exec_id,
                                      recovered=truncated)
            if msg.get("profile_duty"):
                profiler.merge_duty(msg["profile_duty"])
        nbytes = int(msg.get("nbytes") or 0)
        with self._lock:
            handle.tel_records += len(records)
            handle.tel_bytes += nbytes
            handle.tel_dropped = int(msg.get("dropped") or 0)
            self.telemetry_records_total += len(records)
            self.telemetry_bytes_total += nbytes
        if truncated:
            trace.event("telemetry_recovered", exec_id=handle.exec_id,
                        records=n, seq=int(msg.get("seq", 0)),
                        nbytes=nbytes)
        else:
            trace.event("telemetry_shipped", exec_id=handle.exec_id,
                        records=n, seq=int(msg.get("seq", 0)),
                        nbytes=nbytes)

    def _handle_task_failure_locked(self, task: _PoolTask,
                                    msg: dict) -> None:
        from blaze_tpu.runtime import faults, trace

        category = msg.get("category", "fatal")
        retryable = category in ("retryable", "resource")
        if retryable and task.tries < int(conf.max_task_retries):
            task.tries += 1
            task.epoch = self.fence.advance(task.spec.key)
            task.not_before = (time.monotonic()
                               + conf.retry_backoff_ms
                               * (2 ** (task.tries - 1)) / 1000.0)
            task.state = "queued"
            task.executor = None
            self._queue.append(task)
            trace.event("executor_task_requeued", task=task.spec.key,
                        cause="error", category=category,
                        epoch=task.epoch, tries=task.tries)
            return
        cls = faults.CATEGORY_CLASSES.get(category, faults.FatalError)
        task.state = "error"
        task.error = cls(
            f"{task.spec.what}: executor task failed "
            f"[{msg.get('error', '?')}] {msg.get('message', '')}")

    # -- death & recovery ----------------------------------------------

    def _declare_dead(self, handle: ExecutorHandle, reason: str,
                      rc: Optional[int], emit_event: bool = True) -> None:
        """Idempotent executor-death path: fence + re-queue the in-flight
        tasks, record the dossier, recompute capacity, schedule the
        replacement. Runs from the watchdog, a reader EOF, or a failed
        send — first caller wins."""
        from blaze_tpu.runtime import faults, trace

        now = time.monotonic()
        with self._cv:
            if handle.dead or self._closed:
                return
            handle.dead = True
            displaced = list(handle.inflight.values())
            handle.inflight.clear()
            self.deaths_total += 1
            recovery: Dict[str, str] = {}
            for task in displaced:
                self._running.pop(task.spec.key, None)
                if (task.death_requeues
                        < max(1, int(conf.executor_restart_max))):
                    task.death_requeues += 1
                    task.epoch = self.fence.advance(task.spec.key)
                    task.not_before = (
                        now + conf.retry_backoff_ms
                        * (2 ** (task.death_requeues - 1)) / 1000.0)
                    task.state = "queued"
                    task.executor = None
                    self._queue.append(task)
                    recovery[task.spec.key] = "re-queued"
                else:
                    task.state = "error"
                    task.error = faults.FatalError(
                        f"{task.spec.what}: lost to repeated executor "
                        f"deaths ({task.death_requeues} re-queues)")
                    recovery[task.spec.key] = "shed"
            self._graveyard.append(handle)
            restarts = self._seat_restarts.get(handle.seat, 0)
            will_respawn = restarts < int(conf.executor_restart_max)
            if will_respawn:
                self._seat_restarts[handle.seat] = restarts + 1
                self._respawns_pending += 1
                self._respawn_seats.add(handle.seat)
            self._cv.notify_all()
        self.watchdog.unregister(handle.token)
        if emit_event:
            # the watchdog path already emitted its executor_death event
            trace.event("executor_death", exec_id=handle.token,
                        pid=handle.pid, reason=reason, exit_code=rc)
        for task in displaced:
            if recovery.get(task.spec.key) == "re-queued":
                trace.event("executor_task_requeued", task=task.spec.key,
                            cause="executor_death", epoch=task.epoch)
        recovered = self._recover_sidecar(handle)
        self._capture_death_dossier(handle, reason, rc, displaced,
                                    recovery, now, recovered)
        self._notify_membership()
        if will_respawn:
            threading.Thread(
                target=self._respawn, args=(handle.seat, restarts,
                                            handle.generation + 1),
                name="blz-pool-respawn", daemon=True).start()
        else:
            trace.event("degrade", what="executor_retired",
                        exec_id=handle.exec_id, restarts=restarts)

    def _recover_sidecar(self, handle: ExecutorHandle) -> List[dict]:
        """Crash recovery for the telemetry plane: a SIGKILL'd worker's
        unshipped ring tail survives in its crash-atomic sidecar spill
        (written tmp+rename BEFORE every ship). Ingest it exactly once —
        the batch seq watermark skips a sidecar whose batch DID arrive
        over the socket before death — marking every recovered record
        truncated=true (the span stream ended mid-flight). Returns the
        recovered records for the death dossier."""
        path = os.path.join(self._dir, f"{handle.token}.telemetry")
        try:
            nbytes = os.path.getsize(path)
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return []
        if not isinstance(doc, dict):
            return []
        if int(doc.get("seq", 0)) <= handle.tel_seq:
            return []  # tail already shipped over the socket
        handle.tel_seq = int(doc.get("seq", 0))
        doc.setdefault("nbytes", nbytes)
        self._ingest_batch(handle, doc, truncated=True)
        return list(doc.get("records") or [])

    def _capture_death_dossier(self, handle: ExecutorHandle, reason: str,
                               rc: Optional[int], displaced, recovery,
                               now: float,
                               recovered: Optional[List[dict]] = None
                               ) -> None:
        if not conf.flight_dir:
            return
        from blaze_tpu.runtime import flight_recorder

        signal_no = -rc if (rc is not None and rc < 0) else None
        # one dossier per kill: keyed on the executor GENERATION token,
        # so a seat's successive deaths each capture exactly once
        flight_recorder.capture(
            "executor_death", handle.token, detail={
                "exec_id": handle.exec_id,
                "seat": handle.seat,
                "generation": handle.generation,
                "pid": handle.pid,
                "reason": reason,
                "exit_code": rc,
                "signal": signal_no,
                "last_heartbeat_age_ms": round(
                    (now - handle.last_beat) * 1000),
                "tasks_in_flight": [t.spec.what for t in displaced],
                "recovery": recovery,
                "live_executors": self.live_count(),
                "capacity": self.capacity(),
                # the dead worker's own last spans as spilled (raw
                # worker-clock ts; clock_offset_ms above rebases them;
                # the driver ring holds the rebased truncated copies) —
                # bounded: a dossier is a summary, not a trace export
                "clock_offset_ms": round(
                    handle.clock_offset_ns / 1e6, 3),
                "executor_trace": list(recovered or [])[-200:],
            })


    def _respawn(self, seat: int, restarts: int, generation: int) -> None:
        backoff = (conf.executor_restart_backoff_ms
                   * (2 ** restarts) / 1000.0)
        time.sleep(backoff)
        with self._cv:
            self._respawns_pending -= 1
            if self._closed:
                self._respawn_seats.discard(seat)
                return
        self.restarts_total += 1
        self._spawn(seat, generation)
        with self._cv:
            self._respawn_seats.discard(seat)

    # -- graceful decommission -----------------------------------------

    def decommission(self, seat: int) -> bool:
        """Driver-initiated graceful drain of one seat: the worker
        finishes its in-flight tasks (bounded by
        conf.executor_drain_grace_ms), flushes its telemetry sidecar and
        exits; the seat leaves capacity immediately but fires no
        executor_death. The seat is NOT respawned — decommission removes
        it (SIGTERM-initiated drains respawn, for rolling restarts)."""
        from blaze_tpu.runtime import trace

        with self._cv:
            handle = self._seats.get(seat)
            if (handle is None or handle.dead or handle.draining
                    or self._closed):
                return False
            handle.draining = True
            handle.decommissioned = True
            self._cv.notify_all()
        self.watchdog.mark_draining(handle.token)
        trace.event("executor_drain", exec_id=handle.exec_id,
                    phase="begin", initiator="decommission",
                    inflight=len(handle.inflight))
        self._notify_membership()  # draining seats leave capacity now
        try:
            ss.send_msg(handle.conn, {"type": "drain"},
                        lock=handle.send_lock)
        except (ConnectionError, OSError):
            self._conn_broken(handle, handle.conn, "drain_send")
        return True

    def _on_draining(self, handle: ExecutorHandle) -> None:
        """Worker announced drain mode (SIGTERM delivered out-of-band,
        or echoing the driver's own drain order): mirror the
        decommission bookkeeping so the seat leaves capacity without a
        death — but respawn it once drained (a rolling restart wants
        the seat back). Then ack on the FIFO control socket: the ack
        is the drain BARRIER. A dispatch already holding send_lock
        lands its spec BEFORE the ack; once the flag is up no further
        spec may follow it, and the worker only samples idleness after
        reading the ack — so no spec can slip into a seat that is
        about to exit and get silently requeued."""
        from blaze_tpu.runtime import trace

        with self._cv:
            if handle.dead or self._closed:
                return
            first = not handle.draining
            handle.draining = True
            self._cv.notify_all()
        if first:
            self.watchdog.mark_draining(handle.token)
            trace.event("executor_drain", exec_id=handle.exec_id,
                        phase="begin", initiator="sigterm",
                        inflight=len(handle.inflight))
        with handle.send_lock:
            acked, handle.drain_acked = handle.drain_acked, True
            if not acked:
                try:
                    ss.send_msg(handle.conn, {"type": "drain_ack"})
                except (ConnectionError, OSError):
                    pass  # broken conn: drain completes via EOF triage
        if first:
            self._notify_membership()

    def _finish_drain(self, handle: ExecutorHandle, msg: dict) -> None:
        """Drain completed (the worker's "drained" frame, its clean exit
        or its EOF): retire the seat with NO dossier and NO death
        accounting; re-queue any in-flight leftovers the grace period
        cut off (cause executor_drain — they consume no death budget)."""
        from blaze_tpu.runtime import trace

        now = time.monotonic()
        with self._cv:
            if handle.dead or self._closed:
                return
            handle.dead = True
            handle.drained = True
            self.drains_total += 1
            self.drain_requeues_total += len(handle.inflight)
            leftovers = list(handle.inflight.values())
            handle.inflight.clear()
            for task in leftovers:
                self._running.pop(task.spec.key, None)
                task.epoch = self.fence.advance(task.spec.key)
                task.not_before = now
                task.state = "queued"
                task.executor = None
                self._queue.append(task)
            if self._seats.get(handle.seat) is handle:
                del self._seats[handle.seat]
            self._graveyard.append(handle)
            respawn = not handle.decommissioned
            if respawn:
                self._respawns_pending += 1
                self._respawn_seats.add(handle.seat)
            self._cv.notify_all()
        self.watchdog.unregister(handle.token)
        for task in leftovers:
            trace.event("executor_task_requeued", task=task.spec.key,
                        cause="executor_drain", epoch=task.epoch)
        self._recover_sidecar(handle)
        trace.event("executor_drain", exec_id=handle.exec_id,
                    phase="complete", initiator=("decommission"
                                                 if handle.decommissioned
                                                 else "sigterm"),
                    requeued=len(leftovers),
                    rids_returned=len(msg.get("rids") or []))
        self._notify_membership()
        if respawn:
            threading.Thread(
                target=self._respawn_drained,
                args=(handle.seat, handle.generation + 1),
                name="blz-pool-redrain", daemon=True).start()

    def _respawn_drained(self, seat: int, generation: int) -> None:
        """Replace a SIGTERM-drained seat (rolling restart): no backoff,
        no restart-budget charge — the drain was orderly, not a death."""
        with self._cv:
            self._respawns_pending -= 1
            if self._closed:
                self._respawn_seats.discard(seat)
                return
        self._spawn(seat, generation)
        with self._cv:
            self._respawn_seats.discard(seat)

    # -- membership / capacity -----------------------------------------

    def on_membership(self, cb: Callable[["ExecutorPool"], None]) -> None:
        with self._lock:
            self._membership_cbs.append(cb)

    def _notify_membership(self) -> None:
        with self._lock:
            cbs = list(self._membership_cbs)
        for cb in cbs:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — listeners must not wedge us
                pass

    def live_handles(self) -> List[ExecutorHandle]:
        with self._lock:
            return [h for h in self._seats.values() if not h.dead]

    def live_count(self) -> int:
        return len(self.live_handles())

    def capacity(self) -> int:
        """Admission capacity: serving (live, non-draining) seats x
        slots. A draining seat finishes its in-flight work but accepts
        no new dispatch, so it leaves capacity the moment the drain
        begins — without firing executor_death."""
        with self._lock:
            serving = sum(1 for h in self._seats.values()
                          if not h.dead and not h.draining)
        return serving * self.slots

    def executors(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            return [{"exec_id": h.exec_id, "pid": h.pid,
                     "generation": h.generation, "up": not h.dead,
                     "draining": h.draining,
                     "conn_broken": h.conn_broken,
                     "reconnects": h.reconnects,
                     "inflight": len(h.inflight),
                     "heartbeat_age_ms": round(
                         (now - h.last_beat) * 1000),
                     "tasks_done": h.tasks_done,
                     "telemetry_bytes": h.tel_bytes,
                     "telemetry_records": h.tel_records,
                     "telemetry_dropped": h.tel_dropped,
                     "clock_offset_ms": round(h.clock_offset_ns / 1e6, 3)}
                    for h in self._seats.values()]

    def stats(self) -> dict:
        with self._lock:
            live = sum(1 for h in self._seats.values() if not h.dead)
            draining = sum(1 for h in self._seats.values()
                           if not h.dead and h.draining)
            inflight = sum(len(h.inflight) for h in self._seats.values())
            deaths, restarts = self.deaths_total, self.restarts_total
            reconnects, drains = self.reconnects_total, self.drains_total
            drain_requeues = self.drain_requeues_total
            done = self.tasks_done
            tel_bytes = self.telemetry_bytes_total
            tel_records = self.telemetry_records_total
            shuffle_dropped = self.server.conns_dropped
            count = self.count
        return {"count": count, "live": live,
                "draining": draining,
                "capacity": (live - draining) * self.slots,
                "slots": self.slots,
                "inflight": inflight, "deaths_total": deaths,
                "restarts_total": restarts,
                "reconnects_total": reconnects,
                "drains_total": drains,
                "drain_requeues_total": drain_requeues,
                "shuffle_conns_dropped": shuffle_dropped,
                "fenced_total": self.fence.fenced_total,
                "tasks_done": done,
                "telemetry_bytes_total": tel_bytes,
                "telemetry_records_total": tel_records}

    # -- dispatch ------------------------------------------------------

    def _pick_locked(self) -> Optional[tuple]:
        now = time.monotonic()
        # conn_broken seats keep their in-flight tasks (awaiting resume)
        # but take no NEW work; draining seats reject all new dispatch
        handles = [h for h in self._seats.values()
                   if not h.dead and not h.conn_broken and not h.draining
                   and len(h.inflight) < self.slots]
        if not handles:
            return None
        for i, task in enumerate(self._queue):
            if task.not_before <= now:
                handle = min(handles, key=lambda h: (len(h.inflight),
                                                     h.seat))
                self._queue.pop(i)
                task.state = "running"
                task.executor = handle
                handle.inflight[task.spec.key] = task
                self._running[task.spec.key] = task
                return task, handle
        return None

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                picked = self._pick_locked()
                while picked is None and not self._closed:
                    timeout = 0.05 if self._queue else None
                    self._cv.wait(timeout)
                    picked = self._pick_locked()
                if picked is None:
                    return  # closed
            task, handle = picked
            header = {"type": "task", "task": task.spec.key,
                      "epoch": task.epoch, "kind": task.spec.kind,
                      "payload": task.spec.payload}
            conn = handle.conn
            try:
                with handle.send_lock:
                    if handle.drain_acked:
                        # the drain barrier closed between pick and
                        # send: the ack is already on the wire, so this
                        # spec must not follow it (the worker may
                        # sample idle and exit any moment). Un-assign
                        # silently — the spec was never sent, so no
                        # epoch advance and no drain-requeue count.
                        with self._cv:
                            handle.inflight.pop(task.spec.key, None)
                            self._running.pop(task.spec.key, None)
                            task.state = "queued"
                            task.executor = None
                            self._queue.insert(0, task)
                            self._cv.notify_all()
                        continue
                    ss.send_msg(conn, header, task.spec.blob,
                                net_fault=ss.net_rule(
                                    "net.control.send"))
            except (ConnectionError, OSError):
                # broken pipe: triage connection-broken vs process-dead.
                # Either way the task is safe — it sits in
                # handle.inflight, re-sent on resume or re-queued on
                # death. (If the conn was swapped by a concurrent
                # resume, the resume already re-sent the inflight set,
                # this task included.)
                self._conn_broken(handle, conn, "send")

    # -- public task API -----------------------------------------------

    def run_tasks(self, specs: List[PoolTaskSpec],
                  timeout: Optional[float] = None) -> List[dict]:
        """Run a batch of tasks, returning their result messages in spec
        order. Raises the first task error (classified), or
        PoolUnavailableError when every executor seat is retired —
        callers degrade to the in-process runtime."""
        if not specs:
            return []
        from blaze_tpu.runtime import faults

        tasks = [_PoolTask(spec, self.fence.advance(spec.key))
                 for spec in specs]
        deadline = (time.monotonic() + timeout) if timeout else None
        try:
            with self._cv:
                if self._closed:
                    raise RuntimeError("executor pool is closed")
                self._queue.extend(tasks)
                self._cv.notify_all()
                while True:
                    if all(t.finished for t in tasks):
                        break
                    if self._closed:
                        raise RuntimeError(
                            "executor pool closed mid-stage")
                    alive = any(not h.dead
                                for h in self._seats.values())
                    if (not alive and self._respawns_pending == 0
                            and not self._awaiting):
                        self._abandon_locked(tasks)
                        raise PoolUnavailableError(
                            "no live executors and no replacement "
                            "pending")
                    if (deadline is not None
                            and time.monotonic() > deadline):
                        self._abandon_locked(tasks)
                        raise faults.DeadlineError(
                            "executor pool stage timed out")
                    self._cv.wait(0.1)
            errs = [t for t in tasks if t.state == "error"]
            if errs:
                raise errs[0].error
            return [t.result for t in tasks]
        finally:
            # a straggler result after this point finds no fence entry
            # (missing key == epoch 0) and is rejected like any stale
            # attempt, so forgetting keeps the fence bounded per batch
            for spec in specs:
                self.fence.forget(spec.key)

    def _abandon_locked(self, tasks: List[_PoolTask]) -> None:
        """Drop a failed batch: unqueue its pending tasks and fence its
        running ones so straggler results are rejected."""
        for t in tasks:
            if t.state == "queued":
                try:
                    self._queue.remove(t)
                except ValueError:
                    pass
                t.state = "error"
                if t.error is None:
                    from blaze_tpu.runtime import faults

                    t.error = faults.FaultError("sibling task failed")
            elif t.state == "running":
                self._running.pop(t.spec.key, None)
                if t.executor is not None:
                    t.executor.inflight.pop(t.spec.key, None)
                self.fence.advance(t.spec.key)  # fence the straggler

    # -- chaos / test hooks --------------------------------------------

    def hang_executor(self, seat: int, ms: int) -> bool:
        """Ask a worker to stop heartbeating (and defer sends) for `ms`
        without dying — the hung/zombie fault for the chaos soak."""
        with self._lock:
            handle = self._seats.get(seat)
        if handle is None or handle.dead:
            return False
        try:
            ss.send_msg(handle.conn, {"type": "hang", "ms": int(ms)},
                        lock=handle.send_lock)
            return True
        except (ConnectionError, OSError):
            return False

    def partition_executor(self, seat: int, ms: int) -> bool:
        """Simulate an ASYMMETRIC partition for `ms`: the worker keeps
        running but every worker->driver send fails (beats, results,
        telemetry, reconnect attempts) while driver->worker delivery
        still works. Past executor_death_ms the driver declares a
        heartbeat death (fencing the epoch) and the worker's lease
        expires (self-fence, exit code 17) — the two ends of the
        partition-tolerance contract, exercised deterministically."""
        with self._lock:
            handle = self._seats.get(seat)
        if handle is None or handle.dead:
            return False
        try:
            ss.send_msg(handle.conn, {"type": "partition",
                                      "ms": int(ms)},
                        lock=handle.send_lock)
            return True
        except (ConnectionError, OSError):
            return False

    def break_conn(self, seat: int) -> bool:
        """Sever one seat's control connection driver-side (transport
        blip, process untouched): the reader's EOF routes through
        _conn_broken and the worker's bounded reconnect + resume
        handshake must restore the seat without a death."""
        with self._lock:
            handle = self._seats.get(seat)
        if handle is None or handle.dead:
            return False
        try:
            # shutdown wakes BOTH ends' blocked reads immediately (a
            # bare close only errors future calls on this fd)
            handle.conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            handle.conn.close()
        except OSError:
            return False
        return True

    def pids(self) -> Dict[int, int]:
        with self._lock:
            return {h.seat: h.pid for h in self._seats.values()
                    if not h.dead}

    def busy_pids(self) -> Dict[int, int]:
        with self._lock:
            return {h.seat: h.pid for h in self._seats.values()
                    if not h.dead and h.inflight}

    # -- teardown ------------------------------------------------------

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            handles = list(self._seats.values())
            graveyard = list(self._graveyard)
            for h in handles + graveyard:
                h.closing = True
            self._cv.notify_all()
        for h in handles:
            try:
                ss.send_msg(h.conn, {"type": "shutdown"},
                            lock=h.send_lock)
            except (ConnectionError, OSError):
                pass
        for h in handles:
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    pass
        for h in graveyard:
            # a heartbeat-dead zombie may STILL be running: reap it now
            if h.proc is not None and h.proc.poll() is None:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    pass
        for h in handles + graveyard:
            try:
                h.conn.close()
            except OSError:
                pass
        self.watchdog.close()
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        self.server.close()
        shutil.rmtree(self._dir, ignore_errors=True)
        deactivate(self)


# ---------------------------------------------------------------------------
# Process-wide active pool (the local runner / service / monitor hook)
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active_pool: Optional[ExecutorPool] = None


def activate(pool: ExecutorPool) -> ExecutorPool:
    global _active_pool
    with _active_lock:
        _active_pool = pool
    return pool


def deactivate(pool: Optional[ExecutorPool] = None) -> None:
    global _active_pool
    with _active_lock:
        if pool is None or _active_pool is pool:
            _active_pool = None


def active() -> Optional[ExecutorPool]:
    with _active_lock:
        return _active_pool


def pool_stats() -> Optional[dict]:
    """Monitor-facing snapshot: None when no pool is active (gauges are
    omitted entirely in that mode — the in-process runtime has no
    executor topology to report)."""
    pool = active()
    if pool is None:
        return None
    stats = pool.stats()
    stats["executors"] = pool.executors()
    return stats


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------


def _merge_counter_deltas(dst: Dict[str, dict],
                          src: Dict[str, dict]) -> None:
    """Fold freshly-drained monitor deltas into the worker's pending
    (unshipped) counters — a ship failure keeps pending populated, so
    successive drains must accumulate, not replace."""
    for qid, d in src.items():
        qd = dst.setdefault(qid, {})
        for sect, vals in d.items():
            s = qd.setdefault(sect, {})
            if sect == "stage_time_ns":
                for sk, cats in vals.items():
                    sc = s.setdefault(sk, {})
                    for cat, n in cats.items():
                        sc[cat] = sc.get(cat, 0) + n
            else:
                for k, n in vals.items():
                    s[k] = s.get(k, 0) + n


def _merge_hist_snaps(dst: Dict[str, dict], src: Dict[str, dict]) -> None:
    """Fold histogram snapshot deltas (bucket-count sums) into pending."""
    for name, s in src.items():
        cur = dst.get(name)
        if cur is None:
            dst[name] = dict(s)
            continue
        counts = list(cur.get("counts") or ())
        for i, n in enumerate(s.get("counts") or ()):
            if i < len(counts):
                counts[i] += n
            else:
                counts.append(n)
        cur["counts"] = counts
        cur["count"] = int(cur.get("count") or 0) + int(s.get("count") or 0)
        cur["total"] = int(cur.get("total") or 0) + int(s.get("total") or 0)
        for key, pick in (("min", min), ("max", max)):
            a, b = cur.get(key), s.get(key)
            cur[key] = b if a is None else (a if b is None else pick(a, b))


class _Worker:
    """Executor-process main object: control-socket loop + beat thread.
    Task handlers run on their own threads (the driver bounds concurrency
    at conf.executor_slots); heavy engine imports are deferred to the
    first plan task so protocol-only workers stay cheap."""

    # self-fence exit code: dossiers/logs distinguish "lease expired,
    # aborted my own work" from crashes and clean exits
    _LEASE_EXIT = 17

    def __init__(self) -> None:
        self.token = os.environ[_ENV_TOKEN]
        self.ctl_path = os.environ[_ENV_CTL]
        self.shuffle_path = os.environ.get(_ENV_SHUFFLE, "")
        self.sock: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.stop = threading.Event()
        # hang fault (chaos): beats stop and outbound sends stall until
        # this monotonic instant — the process neither exits nor beats
        self.hang_until = 0.0
        # asymmetric-partition fault (chaos): every outbound send raises
        # until this instant, while inbound delivery still works — the
        # deterministic trigger for lease-expiry self-fencing
        self.partition_until = 0.0
        # the lease: monotonic time of the last send that REACHED the
        # driver. No successful send for executor_death_ms means the
        # driver has (or will have) declared us dead and fenced our
        # epoch — commit nothing more, serve nothing stale, exit.
        self._lease_at = time.monotonic()
        # reentrant: _reconnect holds it across the retry ladder and
        # re-enters for _lease_deadline; it also guards sock/_lease_at
        # swaps so senders always read the freshest connection
        self._reconn_lock = threading.RLock()
        # resume-handshake dedupe: (task, epoch) currently executing,
        # plus a bounded cache of finished replies so a re-delivered
        # TaskSpec is answered from cache instead of re-executed
        self._task_lock = threading.Lock()
        self._task_running: set = set()
        self._task_done: "OrderedDict" = OrderedDict()
        self._draining = False
        # drain barrier: set when the driver's drain_ack frame arrives.
        # The control socket is FIFO, so once the reader has processed
        # the ack, every spec dispatched before the driver marked this
        # seat draining is already in _task_running — only then may
        # the drain sample idleness and exit.
        self._drain_ack = threading.Event()
        self._client: Optional[ss.ShuffleClient] = None
        self._client_lock = threading.Lock()
        self._rid_refs: Dict[str, int] = {}
        self._rid_lock = threading.Lock()
        # telemetry shipping state: pending holds drained-but-unshipped
        # records/counters (a failed send keeps them; the sidecar spill
        # already covers them on disk), seq is the batch watermark the
        # driver dedups sidecar recovery against
        self._tel_lock = threading.Lock()
        self._tel_seq = 0
        self._tel_pending: List[dict] = []
        self._tel_counters: Dict[str, dict] = {}
        self._tel_zerocopy: Dict[str, int] = {}
        self._tel_hists: Dict[str, dict] = {}
        self._tel_profile: List[list] = []
        self._tel_profile_last = 0.0  # last profiler drain (monotonic)
        self._tel_duty_mark = (0.0, 0.0)  # duty (cost, wall) shipped so far
        self._sidecar = os.path.join(os.path.dirname(self.ctl_path),
                                     f"{self.token}.telemetry")

    # -- plumbing ------------------------------------------------------

    def _send(self, header: dict, blob: bytes = b"") -> None:
        wait = self.hang_until - time.monotonic()
        if wait > 0:
            # a hung executor's results arrive LATE — after the driver
            # declared it dead and fenced its epoch
            time.sleep(wait)
        if time.monotonic() < self.partition_until:
            raise ConnectionError("partitioned (injected): driver "
                                  "unreachable")
        with self._reconn_lock:
            cur = self.sock
        ss.send_msg(cur, header, blob, lock=self.send_lock)
        with self._reconn_lock:
            self._lease_at = time.monotonic()

    # -- lease / reconnect / self-fence --------------------------------

    def _lease_deadline(self) -> float:
        """The lease expires executor_death_ms after the last send that
        reached the driver — mirroring the driver's heartbeat-staleness
        clock, so both ends give up on the SAME schedule. A hang (chaos)
        extends the lease to hang end: a truly wedged process could not
        run lease logic either, and the late-result zombie path must
        stay reachable for the driver-side fence to be tested."""
        death_s = max(int(conf.executor_death_ms), 1) / 1000.0
        with self._reconn_lock:
            lease_at = self._lease_at
        return max(lease_at, self.hang_until) + death_s

    def _self_fence(self, why: str) -> None:
        """Lease expired (or the control channel is unrecoverable):
        abort in-flight attempts, stop committing/serving, and exit with
        the fence code. The driver has fenced our epoch by now — any
        work we finished would be rejected anyway; dying fast wastes no
        compute and can never serve a stale read. The unshipped
        telemetry tail is spilled (not shipped — the driver is
        unreachable) so the death dossier recovers it."""
        from blaze_tpu.runtime import trace

        with self._reconn_lock:
            lease_at = self._lease_at
        try:
            trace.event("lease_expired", exec_id=self.token, why=why,
                        lease_age_ms=round(
                            (time.monotonic() - lease_at) * 1000))
        except Exception:  # noqa: BLE001 — fencing must not fail
            pass
        try:
            self._flush_telemetry(ship=False)
        except Exception:  # noqa: BLE001
            pass
        self.stop.set()
        os._exit(self._LEASE_EXIT)

    def _reconnect(self, broken: Optional[socket.socket]) -> bool:
        """Bounded reconnect-and-resume after a transport error: a fast
        exponential ladder (conf.control_reconnect_max attempts, base
        conf.control_reconnect_backoff_ms), then slow probes until the
        LEASE decides. Returns True with self.sock swapped to the
        resumed connection, False when the lease expired first (the
        caller self-fences). The resume hello carries the token, pid and
        telemetry watermark; the driver re-sends our in-flight TaskSpecs
        which the dedupe cache absorbs."""
        with self._reconn_lock:
            if self.sock is not broken:
                return True  # another thread already resumed
            if self.stop.is_set():
                return False
            base = max(int(conf.control_reconnect_backoff_ms), 1) / 1000.0
            max_att = max(int(conf.control_reconnect_max), 1)
            attempt = 0
            while not self.stop.is_set():
                left = self._lease_deadline() - time.monotonic()
                if left <= 0:
                    return False
                delay = base * (2 ** min(attempt, max_att))
                time.sleep(min(delay, max(left, 0.001), 0.5))
                attempt += 1
                if time.monotonic() < self.partition_until:
                    continue  # injected partition: stay unreachable
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    s.connect(self.ctl_path)
                    ss.send_msg(s, {"type": "hello", "resume": True,
                                    "token": self.token,
                                    "pid": os.getpid(),
                                    "tel_seq": self._tel_seq,
                                    "mono_ns": time.monotonic_ns()})
                except OSError:
                    try:
                        s.close()
                    except OSError:
                        pass
                    continue
                old, self.sock = self.sock, s
                try:
                    old.close()
                except OSError:
                    pass
                self._lease_at = time.monotonic()
                return True
            return False

    def _beat_loop(self) -> None:
        period = max(int(conf.executor_heartbeat_ms), 10) / 1000.0
        while not self.stop.wait(period):
            now = time.monotonic()
            if now < self.hang_until:
                continue  # hung: silence, but stay alive
            if now < self.partition_until:
                # asymmetric partition: outbound is gone, the lease is
                # the only authority left on this side
                if now > self._lease_deadline():
                    self._self_fence("partition")
                continue
            with self._reconn_lock:
                cur = self.sock
            try:
                ss.send_msg(cur, {"type": "beat"}, lock=self.send_lock)
                with self._reconn_lock:
                    self._lease_at = time.monotonic()
            except (ConnectionError, OSError):
                if not self._reconnect(cur):
                    self._self_fence("beat send failed, lease expired")

    # -- telemetry shipping --------------------------------------------

    def _flush_telemetry(self, ship: bool = True) -> None:
        """Stage the unshipped ring tail + counter/histogram deltas,
        spill them crash-atomically to the sidecar, then ship ONE
        batched "telemetry" frame. Ordering matters twice: the spill
        lands BEFORE the send (a SIGKILL between the two loses nothing
        the driver can't recover), and _run_task flushes BEFORE each
        result send on the same socket (frames are processed in order,
        so the driver merges this batch's counters before the stage
        span that reads them closes). A failed send keeps the batch
        pending — same seq, retried next tick — so the driver's seq
        watermark stays exactly-once. ship=False spills WITHOUT
        sending (the self-fence path: the driver is unreachable, but
        the death dossier recovers the sidecar)."""
        from blaze_tpu.runtime import monitor, profiler, trace

        if not (conf.trace_enabled or conf.monitor_enabled
                or conf.profile_enabled):
            return
        with self._tel_lock:
            self._tel_pending.extend(trace.TRACE.drain())
            _merge_counter_deltas(self._tel_counters,
                                  monitor.drain_remote_deltas())
            for k, v in monitor.drain_zerocopy().items():
                self._tel_zerocopy[k] = self._tel_zerocopy.get(k, 0) + v
            _merge_hist_snaps(self._tel_hists,
                              trace.histograms_snapshot(reset=True))
            if conf.profile_enabled:
                # profiler rows have no before-the-span-closes ordering
                # requirement (they merge by query id whenever), so only
                # the timer-paced ships and the fence/exit flush drain
                # them — NOT the flush that runs before every task
                # result, which must stay a no-op when trace/monitor
                # are off or profiling would tax each task with a
                # spill+ship
                now = time.monotonic()
                period_s = max(int(conf.telemetry_ship_ms), 10) / 1000.0
                if not ship or now - self._tel_profile_last >= period_s:
                    self._tel_profile.extend(profiler.drain_remote())
                    self._tel_profile_last = now
            if not (self._tel_pending or self._tel_counters
                    or self._tel_zerocopy or self._tel_hists
                    or self._tel_profile):
                return
            seq = self._tel_seq + 1
            doc = {"type": "telemetry", "seq": seq,
                   "records": self._tel_pending,
                   "counters": self._tel_counters,
                   "zerocopy": self._tel_zerocopy,
                   "histograms": self._tel_hists,
                   "profile": self._tel_profile,
                   "dropped": trace.TRACE.dropped,
                   "mono_ns": time.monotonic_ns()}
            if conf.profile_enabled:
                # duty ledger rides the frame as a watermarked delta so
                # the driver can prove the fleet-wide sampling overhead
                cost, wall = profiler.duty_snapshot()
                c0, w0 = self._tel_duty_mark
                if cost > c0 or wall > w0:
                    doc["profile_duty"] = {"cost_s": cost - c0,
                                           "wall_s": wall - w0}
                    self._tel_duty_mark = (cost, wall)
            payload = json.dumps(doc, default=str)
            doc["nbytes"] = len(payload)
            tmp = self._sidecar + ".tmp"
            try:
                with open(tmp, "w") as f:
                    f.write(payload)
                os.replace(tmp, self._sidecar)
            except OSError:
                pass  # spill is best-effort; the socket ship still runs
            if not ship:
                return  # fence path: the spill is the delivery
            try:
                self._send(doc)
            except (ConnectionError, OSError):
                return  # keep pending; beat loop notices a dead driver
            self._tel_seq = seq
            self._tel_pending = []
            self._tel_counters = {}
            self._tel_zerocopy = {}
            self._tel_hists = {}
            self._tel_profile = []

    def _ship_loop(self) -> None:
        period_ms = int(conf.telemetry_ship_ms)
        if period_ms <= 0:
            return  # timer disabled; results still carry their flush
        period = max(period_ms, 10) / 1000.0
        while not self.stop.wait(period):
            if time.monotonic() < self.hang_until:
                continue  # hung: the telemetry plane stalls with beats
            try:
                self._flush_telemetry()
            except Exception:  # noqa: BLE001 — never kill the worker
                pass

    def shuffle_client(self) -> ss.ShuffleClient:
        with self._client_lock:
            if self._client is None:
                self._client = ss.ShuffleClient(self.shuffle_path)
            return self._client

    # -- task handlers -------------------------------------------------

    def _acquire_rid(self, rid: str, provider) -> None:
        from blaze_tpu.runtime import resources

        with self._rid_lock:
            n = self._rid_refs.get(rid, 0)
            self._rid_refs[rid] = n + 1
            if n == 0:
                resources.put(rid, provider)

    def _release_rid(self, rid: str) -> None:
        from blaze_tpu.runtime import resources

        with self._rid_lock:
            n = self._rid_refs.get(rid, 1) - 1
            if n <= 0:
                self._rid_refs.pop(rid, None)
                resources.pop(rid)
            else:
                self._rid_refs[rid] = n

    def _run_plan(self, payload: dict, blob: bytes, epoch: int) -> dict:
        from blaze_tpu.ops.base import ExecContext
        from blaze_tpu.plan import plan_pb2 as pb
        from blaze_tpu.runtime import artifacts
        from blaze_tpu.runtime.executor import run_pool_plan

        node = pb.PlanNode()
        node.ParseFromString(blob)
        # the fence stamp: this attempt's artifacts land on epoch-named
        # files, so even a zombie's completed write can't collide with a
        # retried attempt's output
        data_path = artifacts.stamp_epoch(node.shuffle_writer.data_file,
                                          epoch)
        index_path = artifacts.stamp_epoch(node.shuffle_writer.index_file,
                                           epoch)
        node.shuffle_writer.data_file = data_path
        node.shuffle_writer.index_file = index_path
        client = self.shuffle_client()
        rids = list(payload.get("rids") or [])
        rid_parts = dict(payload.get("rid_parts") or {})

        def make_provider(rid):
            # exactly one positional param: _call_provider passes the
            # task partition to 1-arg providers (a default-arg closure
            # would be miscounted as 2-arg and handed num_partitions)
            if rid.endswith(":all"):
                # build-side whole-relation read: chain every partition
                # of the base rid (count shipped in the payload — the
                # server registers outputs under the base rid only)
                base = rid[:-len(":all")]
                nparts = int(rid_parts.get(rid, 0))

                def provider(partition):
                    for p in range(nparts):
                        for frame in client.fetch_frames(base, p):
                            yield frame
                return provider

            def provider(partition):
                # fetch_frames prefers the same-host zero-copy mmap path
                # (memoryview slices of the committed .data file) and
                # falls back to the socket stream transparently
                return iter(client.fetch_frames(rid, partition))
            return provider

        for rid in rids:
            self._acquire_rid(rid, make_provider(rid))
        try:
            ctx = ExecContext(partition=int(payload.get("partition", 0)),
                              num_partitions=int(
                                  payload.get("num_partitions", 1)))
            # the in-process resilience ladder runs INSIDE the worker:
            # transient faults retry here before costing the driver a
            # cross-process re-queue (runtime/executor.run_pool_plan)
            op = run_pool_plan(node, ctx,
                               what=payload.get("what", "pool_plan"))
            logical = int(op.metrics.values.get("shuffle_logical_bytes",
                                                0))
            return {"data_path": data_path, "index_path": index_path,
                    "logical_bytes": logical}
        finally:
            for rid in rids:
                self._release_rid(rid)

    def _run_flaky(self, payload: dict) -> dict:
        """Test handler: fail the first `times` attempts (counted in a
        driver-provided file so the count survives this process dying),
        then succeed."""
        from blaze_tpu.runtime import faults

        marker = payload["marker"]
        n = 0
        try:
            with open(marker, "r") as f:
                n = int(f.read().strip() or 0)
        except (OSError, ValueError):
            n = 0
        if n < int(payload.get("times", 1)):
            with open(marker, "w") as f:
                f.write(str(n + 1))
            cls = faults.CATEGORY_CLASSES.get(
                payload.get("category", "retryable"), faults.FatalError)
            raise cls(f"flaky task (attempt {n + 1})")
        return {"attempts_failed": n}

    def _run_task(self, msg: dict, blob: bytes) -> None:
        from blaze_tpu.runtime import monitor, trace

        key, epoch = msg.get("task", ""), int(msg.get("epoch", 0))
        kind = msg.get("kind", "")
        payload = msg.get("payload") or {}
        # replay the driver-issued correlation ids: every worker-side
        # record (the task_attempt span, nested events, counter
        # attribution) then carries the same query/stage/task ids the
        # driver's records do — the federation join key
        ids = {k: payload.get(k) for k in trace.ID_KEYS
               if payload.get(k) is not None}
        if ids.get("query_id"):
            monitor.ensure_query(ids["query_id"])
        try:
            with trace.context(**ids):
                with trace.span("task_attempt",
                                attempt_id=f"{key}#e{epoch}",
                                pool_kind=kind,
                                what=payload.get("what", key)):
                    if kind == "plan":
                        result = self._run_plan(payload, blob, epoch)
                    elif kind == "echo":
                        result = {"value": payload.get("value")}
                    elif kind == "sleep":
                        end = (time.monotonic()
                               + float(payload.get("ms", 0)) / 1e3)
                        while (time.monotonic() < end
                               and not self.stop.is_set()):
                            time.sleep(0.01)
                        result = {}
                    elif kind == "flaky":
                        result = self._run_flaky(payload)
                    else:
                        raise ValueError(f"unknown task kind: {kind}")
        except BaseException as e:  # noqa: BLE001 — classified + relayed
            from blaze_tpu.runtime import faults

            reply = {"type": "result", "task": key, "epoch": epoch,
                     "ok": False, "category": faults.classify(e),
                     "error": type(e).__name__,
                     "message": str(e)[:500]}
            self._finish_task(key, epoch, reply)
            return
        reply = {"type": "result", "task": key, "epoch": epoch,
                 "ok": True}
        reply.update(result)
        self._finish_task(key, epoch, reply)

    def _finish_task(self, key: str, epoch: int, reply: dict) -> None:
        """Cache the reply (resume-handshake dedupe: a re-delivered spec
        is answered from here instead of re-executed), flush telemetry
        BEFORE the result — same socket, in-order processing, so the
        driver has this task's spans/counters federated before the
        stage span that reads them closes — then send. A send that
        fails is NOT a loss: the reply stays cached, and the driver's
        resume handshake re-delivers the spec, which replays it."""
        with self._task_lock:
            self._task_running.discard((key, epoch))
            self._task_done[(key, epoch)] = reply
            while len(self._task_done) > 64:
                self._task_done.popitem(last=False)
        self._flush_telemetry()
        try:
            self._send(reply)
        except (ConnectionError, OSError):
            pass

    def _dispatch_task(self, msg: dict, blob: bytes) -> None:
        """Dedupe-by-(task_id, epoch) in front of execution: a spec
        re-delivered by the resume handshake (or a dup-delivery wire
        fault) executes ONCE — finished work replies from the result
        cache, running work stays single-flight."""
        key = (msg.get("task", ""), int(msg.get("epoch", 0)))
        with self._task_lock:
            cached = self._task_done.get(key)
            if cached is None and key in self._task_running:
                return  # already executing: its reply will cover this
            if cached is None:
                self._task_running.add(key)
        if cached is not None:
            try:
                self._send(cached)
            except (ConnectionError, OSError):
                pass  # stays cached; the next re-delivery replays it
            return
        threading.Thread(target=self._run_task, args=(msg, blob),
                         name="blz-wk-task", daemon=True).start()

    def _begin_drain(self, initiator: str) -> None:
        """Enter drain mode (driver's drain order or SIGTERM): announce
        "draining" (so the driver reassigns capacity without a death),
        finish in-flight tasks bounded by conf.executor_drain_grace_ms,
        flush the telemetry sidecar, hand the registered shuffle rids
        back, send "drained", exit 0."""
        with self._task_lock:
            if self._draining:
                return
            self._draining = True
        try:
            self._send({"type": "draining", "initiator": initiator})
        except (ConnectionError, OSError):
            pass  # the driver learns from our exit instead
        threading.Thread(target=self._drain_and_exit,
                         name="blz-wk-drain", daemon=True).start()

    def _drain_and_exit(self) -> None:
        grace = max(int(conf.executor_drain_grace_ms), 0) / 1000.0
        # drain barrier: wait for the driver's ack before sampling
        # idleness, so a spec the driver sent just before it marked us
        # draining cannot land after the idle check and die with the
        # process. Bounded: a broken conn (or a driver that never
        # acks) must not wedge the drain.
        self._drain_ack.wait(min(grace, 2.0))
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            with self._task_lock:
                idle = not self._task_running
            if idle:
                break
            time.sleep(0.01)
        try:
            self._flush_telemetry()
        except Exception:  # noqa: BLE001 — the drain must complete
            pass
        with self._rid_lock:
            rids = sorted(self._rid_refs)
        try:
            self._send({"type": "drained", "rids": rids})
        except (ConnectionError, OSError):
            pass  # EOF tells the driver the same thing
        self.stop.set()
        os._exit(0)

    # -- main loop -----------------------------------------------------

    def run(self) -> int:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(self.ctl_path)
        with self._reconn_lock:
            self.sock = sock
        ss.send_msg(sock, {"type": "hello", "token": self.token,
                           "pid": os.getpid(),
                           # clock echo: the driver estimates this
                           # worker's monotonic offset from it
                           "mono_ns": time.monotonic_ns()},
                    lock=self.send_lock)
        beat = threading.Thread(target=self._beat_loop, name="blz-wk-beat",
                                daemon=True)
        beat.start()
        ship = threading.Thread(target=self._ship_loop, name="blz-wk-ship",
                                daemon=True)
        ship.start()
        try:
            while not self.stop.is_set():
                with self._reconn_lock:
                    cur = self.sock
                try:
                    msg, blob = ss.recv_msg(cur)
                except (ConnectionError, OSError):
                    # transport error, not an order to die: bounded
                    # reconnect + resume, self-fence once the lease says
                    # the driver side has already buried us
                    if self._reconnect(cur):
                        continue
                    self._self_fence("control recv failed, lease "
                                     "expired")
                    break
                mtype = msg.get("type")
                if mtype == "task":
                    self._dispatch_task(msg, blob)
                elif mtype == "ping":
                    self._send({"type": "pong"})
                elif mtype == "hang":
                    self.hang_until = (time.monotonic()
                                       + int(msg.get("ms", 0)) / 1000.0)
                elif mtype == "partition":
                    self.partition_until = (
                        time.monotonic() + int(msg.get("ms", 0)) / 1000.0)
                elif mtype == "drain":
                    self._begin_drain("drain_msg")
                elif mtype == "drain_ack":
                    self._drain_ack.set()
                elif mtype == "shutdown":
                    break
        finally:
            try:
                # last chance to ship buffered telemetry on a clean
                # shutdown (send errors are swallowed inside)
                self._flush_telemetry()
            except Exception:  # noqa: BLE001 — teardown must proceed
                pass
            self.stop.set()
            with self._client_lock:
                client, self._client = self._client, None
            if client is not None:
                client.close()
            with self._reconn_lock:
                cur = self.sock
            try:
                cur.close()
            except OSError:
                pass
        return 0


def _worker_main() -> int:
    overrides = os.environ.get(_ENV_CONF, "")
    if overrides:
        for name, value in json.loads(overrides).items():
            if name in KNOBS:
                setattr(conf, name, value)
    if conf.profile_enabled:
        # the worker samples its own threads; folded-stack deltas ship
        # driver-ward with _flush_telemetry (sidecar-recoverable)
        from blaze_tpu.runtime import profiler

        profiler.ensure_started()
    worker = _Worker()
    # SIGTERM is a decommission order, not a kill: drain in-flight work,
    # flush telemetry, hand shuffle rids back, then exit 0.
    signal.signal(signal.SIGTERM,
                  lambda signum, frame: worker._begin_drain("sigterm"))
    return worker.run()


if __name__ == "__main__":
    if "--worker" in sys.argv:
        sys.exit(_worker_main())
    sys.exit("executor_pool is a library; run with --worker as a pool "
             "child process")
