"""Process-isolated executor pool: crash containment for the runtime.

Ref: Spark's executor model (PAPER.md §1 — Spark remains the
distributed runtime; executors die, the driver detects it, lost
partitions are re-executed from persisted shuffle artifacts). This
module is that driver/executor split for the local runtime: N worker
PROCESSES, each owning a virtual device slice, receive TaskSpecs over a
length-prefixed control socket (the serde frame discipline —
runtime/shuffle_server.py holds the shared framing) and read upstream
shuffle input from the driver's ShuffleServer, so one hard fault (OOM
kill, segfault, wedged interpreter) costs ONE process, not the service.

The robustness path, not the transport, is the point:

  heartbeat   every worker pushes beats over the control socket; ANY
              inbound frame refreshes liveness (supervisor.ProcessPeer —
              the thread heartbeat posture generalized to PIDs).

  death       supervisor.ProcessWatchdog declares an executor dead on
              reap/exit (exact exit code / killing signal) or heartbeat
              staleness past conf.executor_death_ms — the latter may be
              a ZOMBIE that is still running.

  fencing     every task attempt carries an epoch (artifacts.EpochFence)
              stamped into its TaskSpec, its shuffle artifact names
              (`shuffle_0_1.e2.data`) and the result accounting: a
              re-queue advances the fence, so a zombie's late result is
              rejected at the driver (never double-counted) and its late
              files land on stale names that get swept — they can never
              overwrite the retried attempt's artifacts.

  lineage     only the LOST partitions re-execute: completed map outputs
              live in driver-committed .data/.index files served by the
              ShuffleServer, so surviving artifacts are re-read, not
              recomputed. Re-queues are bounded with exponential backoff.

  degradation on a death the pool's membership callbacks fire — the
              QueryService recomputes admission capacity as
              live_executors x conf.executor_slots, parks (re-queues)
              displaced arrivals instead of failing them, and restores
              capacity when the replacement process (bounded by
              conf.executor_restart_max, backed off) rejoins.

Worker processes are spawned as `python -m
blaze_tpu.runtime.executor_pool --worker` with their identity and socket
paths in the environment; the driver-side conf snapshot rides along so
knobs agree across the process boundary.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from blaze_tpu.config import KNOBS, conf
from blaze_tpu.runtime import shuffle_server as ss

_ENV_TOKEN = "BLAZE_EXEC_TOKEN"
_ENV_SEAT = "BLAZE_EXEC_SEAT"
_ENV_CTL = "BLAZE_EXEC_SOCK"
_ENV_SHUFFLE = "BLAZE_EXEC_SHUFFLE_SOCK"
_ENV_CONF = "BLAZE_TPU_WORKER_CONF"

# knobs a worker must NOT inherit verbatim: a worker never spawns its own
# pool, never serves metrics, and never exports traces/dossiers/history
# (the driver owns observability; worker task stats ride the result msg)
_WORKER_CONF_OVERRIDES = {
    "executor_count": 0,
    "metrics_port": 0,
    "trace_enabled": False,
    "trace_export_dir": "",
    "history_dir": "",
    "flight_dir": "",
    "progress_enabled": False,
    "fault_injection_spec": {},
    # only the driver journals (one journal per query) or replays them
    "journal_dir": "",
    "recovery_enabled": False,
}


class PoolTaskSpec:
    """One schedulable unit for the process pool (the TaskSpec twin for
    the process boundary: everything must be serializable). `key` is the
    fence key — unique per logical task; `payload` is the JSON header the
    worker dispatches on; `blob` carries the plan proto bytes."""

    __slots__ = ("key", "kind", "payload", "blob", "what")

    def __init__(self, key: str, kind: str, payload: Optional[dict] = None,
                 blob: bytes = b"", what: str = "") -> None:
        self.key = key
        self.kind = kind
        self.payload = dict(payload or {})
        self.blob = blob
        self.what = what or key


class _PoolTask:
    """Pool-internal task state: current epoch, retry/death budgets, and
    the terminal outcome."""

    __slots__ = ("spec", "epoch", "state", "result", "error", "tries",
                 "death_requeues", "not_before", "executor")

    def __init__(self, spec: PoolTaskSpec, epoch: int) -> None:
        self.spec = spec
        self.epoch = epoch
        self.state = "queued"  # queued | running | done | error
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.tries = 0
        self.death_requeues = 0
        self.not_before = 0.0
        self.executor: Optional["ExecutorHandle"] = None

    @property
    def finished(self) -> bool:
        return self.state in ("done", "error")


class ExecutorHandle:
    """Driver-side view of one executor process."""

    def __init__(self, seat: int, generation: int, token: str, pid: int,
                 proc: Optional[subprocess.Popen],
                 conn: socket.socket) -> None:
        self.seat = seat
        self.generation = generation
        self.token = token
        self.pid = pid
        self.proc = proc
        self.conn = conn
        self.send_lock = threading.Lock()
        self.inflight: Dict[str, _PoolTask] = {}  # guarded by pool lock
        self.dead = False                         # guarded by pool lock
        self.closing = False
        self.joined_at = time.monotonic()
        self.last_beat = self.joined_at

    @property
    def exec_id(self) -> str:
        return f"exec{self.seat}"


class PoolUnavailableError(ConnectionError):
    """No live executor can run a queued task and no replacement is
    pending: callers degrade to the in-process runtime."""


class ExecutorPool:
    """Spawns, supervises, feeds and buries executor processes.

    Lifecycle: `start()` spawns conf.executor_count workers and waits
    for their control-socket handshakes; `run_tasks(specs)` executes a
    batch with epoch-fenced re-queue on executor death; `close()` tears
    everything down. `activate(pool)` publishes the pool process-wide so
    the local runner routes eligible stages here and the service derives
    its admission capacity from membership."""

    _READY_TIMEOUT = 90.0
    _HELLO_TIMEOUT = 30.0

    def __init__(self, count: Optional[int] = None,
                 slots: Optional[int] = None) -> None:
        self.count = int(count if count is not None
                         else conf.executor_count)
        self.slots = max(1, int(slots if slots is not None
                                else conf.executor_slots))
        from blaze_tpu.runtime import artifacts, supervisor

        self.fence = artifacts.EpochFence()
        self.watchdog = supervisor.ProcessWatchdog()
        self._dir = tempfile.mkdtemp(prefix="blzex-")
        # pool-unique token prefix: two pools in one process (tests, a
        # service restart) must not collide in the flight recorder's
        # (query_id, trigger) exactly-once dedup or the watchdog registry
        self._pool_id = os.path.basename(self._dir)[len("blzex-"):]
        self._ctl_path = os.path.join(self._dir, "ctl.sock")
        self.server = ss.ShuffleServer(os.path.join(self._dir, "shf.sock"))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._seats: Dict[int, ExecutorHandle] = {}
        # declared-dead handles: a heartbeat-dead ZOMBIE's socket stays
        # open (its late results must arrive to be fenced) and its
        # process may still run — close() reaps whatever is left here
        self._graveyard: List[ExecutorHandle] = []
        self._awaiting: Dict[str, tuple] = {}  # token -> (seat, gen, proc)
        self._queue: List[_PoolTask] = []
        self._running: Dict[str, _PoolTask] = {}
        self._seat_restarts: Dict[int, int] = {}
        self._respawns_pending = 0
        self._membership_cbs: List[Callable[["ExecutorPool"], None]] = []
        self._closed = False
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self.deaths_total = 0
        self.restarts_total = 0
        self.tasks_done = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ExecutorPool":
        if self.count <= 0:
            raise ValueError("executor pool needs count >= 1")
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self._ctl_path)
        listener.listen(self.count * 2 + 4)
        self._listener = listener
        self.server.start()
        for name, target in (("blz-pool-accept", self._accept_loop),
                             ("blz-pool-dispatch", self._dispatch_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        for seat in range(self.count):
            self._spawn(seat, 0)
        deadline = time.monotonic() + self._READY_TIMEOUT
        with self._cv:
            while (len([h for h in self._seats.values() if not h.dead])
                   < self.count):
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"executor pool: {len(self._seats)}/{self.count} "
                        f"workers joined within {self._READY_TIMEOUT}s")
                self._cv.wait(min(left, 0.25))
        return self

    def _spawn(self, seat: int, generation: int) -> None:
        token = f"exec{seat}g{generation}.{self._pool_id}"
        env = dict(os.environ)
        env[_ENV_TOKEN] = token
        env[_ENV_SEAT] = str(seat)
        env[_ENV_CTL] = self._ctl_path
        env[_ENV_SHUFFLE] = self.server.sock_path
        snapshot = {name: getattr(conf, name) for name in KNOBS}
        snapshot.update(_WORKER_CONF_OVERRIDES)
        env[_ENV_CONF] = json.dumps(snapshot)
        # the worker resolves blaze_tpu by module name regardless of the
        # driver's cwd (pytest may chdir into a tmp dir)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        err_path = os.path.join(self._dir, f"{token}.err")
        with open(err_path, "ab") as err:
            proc = subprocess.Popen(
                [sys.executable, "-m", "blaze_tpu.runtime.executor_pool",
                 "--worker"],
                env=env, stdin=subprocess.DEVNULL,
                stdout=subprocess.DEVNULL, stderr=err)
        with self._cv:
            self._awaiting[token] = (seat, generation, proc)
        from blaze_tpu.runtime import trace

        trace.event("executor_spawn", exec_id=f"exec{seat}",
                    generation=generation, pid=proc.pid)

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._handshake, args=(conn,),
                             name="blz-pool-hello", daemon=True).start()

    def _handshake(self, conn: socket.socket) -> None:
        conn.settimeout(self._HELLO_TIMEOUT)
        try:
            msg, _blob = ss.recv_msg(conn)
        except (ConnectionError, OSError):
            conn.close()
            return
        conn.settimeout(None)
        token = msg.get("token", "")
        with self._cv:
            pending = self._awaiting.pop(token, None)
        if msg.get("type") != "hello" or pending is None:
            conn.close()
            return
        seat, generation, proc = pending
        handle = ExecutorHandle(seat, generation, token,
                                int(msg.get("pid", proc.pid)), proc, conn)
        with self._cv:
            if self._closed:
                handle.closing = True
            self._seats[seat] = handle
            self._cv.notify_all()
        if handle.closing:
            conn.close()
            return
        self.watchdog.register(
            token, handle.pid,
            lambda peer, reason, rc, h=handle: self._declare_dead(
                h, reason, rc, emit_event=False),
            poll=proc.poll)
        t = threading.Thread(target=self._reader, args=(handle,),
                             name=f"blz-pool-rd-{seat}", daemon=True)
        t.start()
        self._threads.append(t)
        self._notify_membership()

    # -- socket reader -------------------------------------------------

    def _reader(self, handle: ExecutorHandle) -> None:
        """Per-executor inbound loop. Keeps reading a heartbeat-declared
        zombie's socket so its late results arrive — and get fenced —
        instead of rotting in the kernel buffer."""
        while True:
            try:
                msg, _blob = ss.recv_msg(handle.conn)
            except (ConnectionError, OSError):
                break
            handle.last_beat = time.monotonic()
            self.watchdog.beat(handle.token)
            if msg.get("type") == "result":
                self._on_result(handle, msg)
        if not handle.closing:
            # EOF before shutdown: the process died (or is dying) — don't
            # wait the heartbeat staleness out
            self._declare_dead(handle, "exit",
                               handle.proc.poll() if handle.proc else None)

    def _on_result(self, handle: ExecutorHandle, msg: dict) -> None:
        from blaze_tpu.runtime import artifacts

        key, epoch = msg.get("task", ""), int(msg.get("epoch", 0))
        if not self.fence.admit(key, epoch):
            # the zombie's late write: reject the result and sweep its
            # stale-named files; the ledger never sees it
            for p in (msg.get("data_path"), msg.get("index_path")):
                if p and artifacts.epoch_of(p) == epoch:
                    artifacts._unlink_quiet(p)
            return
        with self._cv:
            task = self._running.get(key)
            if task is None or task.epoch != epoch:
                return
            del self._running[key]
            handle.inflight.pop(key, None)
            if msg.get("ok"):
                task.state, task.result = "done", msg
                self.tasks_done += 1
            else:
                self._handle_task_failure_locked(task, msg)
            self._cv.notify_all()

    def _handle_task_failure_locked(self, task: _PoolTask,
                                    msg: dict) -> None:
        from blaze_tpu.runtime import faults, trace

        category = msg.get("category", "fatal")
        retryable = category in ("retryable", "resource")
        if retryable and task.tries < int(conf.max_task_retries):
            task.tries += 1
            task.epoch = self.fence.advance(task.spec.key)
            task.not_before = (time.monotonic()
                               + conf.retry_backoff_ms
                               * (2 ** (task.tries - 1)) / 1000.0)
            task.state = "queued"
            task.executor = None
            self._queue.append(task)
            trace.event("executor_task_requeued", task=task.spec.key,
                        cause="error", category=category,
                        epoch=task.epoch, tries=task.tries)
            return
        cls = faults.CATEGORY_CLASSES.get(category, faults.FatalError)
        task.state = "error"
        task.error = cls(
            f"{task.spec.what}: executor task failed "
            f"[{msg.get('error', '?')}] {msg.get('message', '')}")

    # -- death & recovery ----------------------------------------------

    def _declare_dead(self, handle: ExecutorHandle, reason: str,
                      rc: Optional[int], emit_event: bool = True) -> None:
        """Idempotent executor-death path: fence + re-queue the in-flight
        tasks, record the dossier, recompute capacity, schedule the
        replacement. Runs from the watchdog, a reader EOF, or a failed
        send — first caller wins."""
        from blaze_tpu.runtime import faults, trace

        now = time.monotonic()
        with self._cv:
            if handle.dead or self._closed:
                return
            handle.dead = True
            displaced = list(handle.inflight.values())
            handle.inflight.clear()
            self.deaths_total += 1
            recovery: Dict[str, str] = {}
            for task in displaced:
                self._running.pop(task.spec.key, None)
                if (task.death_requeues
                        < max(1, int(conf.executor_restart_max))):
                    task.death_requeues += 1
                    task.epoch = self.fence.advance(task.spec.key)
                    task.not_before = (
                        now + conf.retry_backoff_ms
                        * (2 ** (task.death_requeues - 1)) / 1000.0)
                    task.state = "queued"
                    task.executor = None
                    self._queue.append(task)
                    recovery[task.spec.key] = "re-queued"
                else:
                    task.state = "error"
                    task.error = faults.FatalError(
                        f"{task.spec.what}: lost to repeated executor "
                        f"deaths ({task.death_requeues} re-queues)")
                    recovery[task.spec.key] = "shed"
            self._graveyard.append(handle)
            restarts = self._seat_restarts.get(handle.seat, 0)
            will_respawn = restarts < int(conf.executor_restart_max)
            if will_respawn:
                self._seat_restarts[handle.seat] = restarts + 1
                self._respawns_pending += 1
            self._cv.notify_all()
        self.watchdog.unregister(handle.token)
        if emit_event:
            # the watchdog path already emitted its executor_death event
            trace.event("executor_death", exec_id=handle.token,
                        pid=handle.pid, reason=reason, exit_code=rc)
        for task in displaced:
            if recovery.get(task.spec.key) == "re-queued":
                trace.event("executor_task_requeued", task=task.spec.key,
                            cause="executor_death", epoch=task.epoch)
        self._capture_death_dossier(handle, reason, rc, displaced,
                                    recovery, now)
        self._notify_membership()
        if will_respawn:
            threading.Thread(
                target=self._respawn, args=(handle.seat, restarts,
                                            handle.generation + 1),
                name="blz-pool-respawn", daemon=True).start()
        else:
            trace.event("degrade", what="executor_retired",
                        exec_id=handle.exec_id, restarts=restarts)

    def _capture_death_dossier(self, handle: ExecutorHandle, reason: str,
                               rc: Optional[int], displaced, recovery,
                               now: float) -> None:
        if not conf.flight_dir:
            return
        from blaze_tpu.runtime import flight_recorder

        signal_no = -rc if (rc is not None and rc < 0) else None
        # one dossier per kill: keyed on the executor GENERATION token,
        # so a seat's successive deaths each capture exactly once
        flight_recorder.capture(
            "executor_death", handle.token, detail={
                "exec_id": handle.exec_id,
                "seat": handle.seat,
                "generation": handle.generation,
                "pid": handle.pid,
                "reason": reason,
                "exit_code": rc,
                "signal": signal_no,
                "last_heartbeat_age_ms": round(
                    (now - handle.last_beat) * 1000),
                "tasks_in_flight": [t.spec.what for t in displaced],
                "recovery": recovery,
                "live_executors": self.live_count(),
                "capacity": self.capacity(),
            })


    def _respawn(self, seat: int, restarts: int, generation: int) -> None:
        backoff = (conf.executor_restart_backoff_ms
                   * (2 ** restarts) / 1000.0)
        time.sleep(backoff)
        with self._cv:
            self._respawns_pending -= 1
            if self._closed:
                return
        self.restarts_total += 1
        self._spawn(seat, generation)

    # -- membership / capacity -----------------------------------------

    def on_membership(self, cb: Callable[["ExecutorPool"], None]) -> None:
        with self._lock:
            self._membership_cbs.append(cb)

    def _notify_membership(self) -> None:
        with self._lock:
            cbs = list(self._membership_cbs)
        for cb in cbs:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — listeners must not wedge us
                pass

    def live_handles(self) -> List[ExecutorHandle]:
        with self._lock:
            return [h for h in self._seats.values() if not h.dead]

    def live_count(self) -> int:
        return len(self.live_handles())

    def capacity(self) -> int:
        return self.live_count() * self.slots

    def executors(self) -> List[dict]:
        with self._lock:
            return [{"exec_id": h.exec_id, "pid": h.pid,
                     "generation": h.generation, "up": not h.dead,
                     "inflight": len(h.inflight)}
                    for h in self._seats.values()]

    def stats(self) -> dict:
        with self._lock:
            live = sum(1 for h in self._seats.values() if not h.dead)
            inflight = sum(len(h.inflight) for h in self._seats.values())
            deaths, restarts = self.deaths_total, self.restarts_total
            done = self.tasks_done
        return {"count": self.count, "live": live,
                "capacity": live * self.slots, "slots": self.slots,
                "inflight": inflight, "deaths_total": deaths,
                "restarts_total": restarts,
                "fenced_total": self.fence.fenced_total,
                "tasks_done": done}

    # -- dispatch ------------------------------------------------------

    def _pick_locked(self) -> Optional[tuple]:
        now = time.monotonic()
        handles = [h for h in self._seats.values()
                   if not h.dead and len(h.inflight) < self.slots]
        if not handles:
            return None
        for i, task in enumerate(self._queue):
            if task.not_before <= now:
                handle = min(handles, key=lambda h: (len(h.inflight),
                                                     h.seat))
                self._queue.pop(i)
                task.state = "running"
                task.executor = handle
                handle.inflight[task.spec.key] = task
                self._running[task.spec.key] = task
                return task, handle
        return None

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                picked = self._pick_locked()
                while picked is None and not self._closed:
                    timeout = 0.05 if self._queue else None
                    self._cv.wait(timeout)
                    picked = self._pick_locked()
                if picked is None:
                    return  # closed
            task, handle = picked
            header = {"type": "task", "task": task.spec.key,
                      "epoch": task.epoch, "kind": task.spec.kind,
                      "payload": task.spec.payload}
            try:
                ss.send_msg(handle.conn, header, task.spec.blob,
                            lock=handle.send_lock)
            except (ConnectionError, OSError):
                # broken pipe: the executor is gone; death handling
                # re-queues this task (it is in handle.inflight)
                self._declare_dead(handle, "send_error",
                                   handle.proc.poll() if handle.proc
                                   else None)

    # -- public task API -----------------------------------------------

    def run_tasks(self, specs: List[PoolTaskSpec],
                  timeout: Optional[float] = None) -> List[dict]:
        """Run a batch of tasks, returning their result messages in spec
        order. Raises the first task error (classified), or
        PoolUnavailableError when every executor seat is retired —
        callers degrade to the in-process runtime."""
        if not specs:
            return []
        from blaze_tpu.runtime import faults

        tasks = [_PoolTask(spec, self.fence.advance(spec.key))
                 for spec in specs]
        deadline = (time.monotonic() + timeout) if timeout else None
        try:
            with self._cv:
                if self._closed:
                    raise RuntimeError("executor pool is closed")
                self._queue.extend(tasks)
                self._cv.notify_all()
                while True:
                    if all(t.finished for t in tasks):
                        break
                    if self._closed:
                        raise RuntimeError(
                            "executor pool closed mid-stage")
                    alive = any(not h.dead
                                for h in self._seats.values())
                    if (not alive and self._respawns_pending == 0
                            and not self._awaiting):
                        self._abandon_locked(tasks)
                        raise PoolUnavailableError(
                            "no live executors and no replacement "
                            "pending")
                    if (deadline is not None
                            and time.monotonic() > deadline):
                        self._abandon_locked(tasks)
                        raise faults.DeadlineError(
                            "executor pool stage timed out")
                    self._cv.wait(0.1)
            errs = [t for t in tasks if t.state == "error"]
            if errs:
                raise errs[0].error
            return [t.result for t in tasks]
        finally:
            # a straggler result after this point finds no fence entry
            # (missing key == epoch 0) and is rejected like any stale
            # attempt, so forgetting keeps the fence bounded per batch
            for spec in specs:
                self.fence.forget(spec.key)

    def _abandon_locked(self, tasks: List[_PoolTask]) -> None:
        """Drop a failed batch: unqueue its pending tasks and fence its
        running ones so straggler results are rejected."""
        for t in tasks:
            if t.state == "queued":
                try:
                    self._queue.remove(t)
                except ValueError:
                    pass
                t.state = "error"
                if t.error is None:
                    from blaze_tpu.runtime import faults

                    t.error = faults.FaultError("sibling task failed")
            elif t.state == "running":
                self._running.pop(t.spec.key, None)
                if t.executor is not None:
                    t.executor.inflight.pop(t.spec.key, None)
                self.fence.advance(t.spec.key)  # fence the straggler

    # -- chaos / test hooks --------------------------------------------

    def hang_executor(self, seat: int, ms: int) -> bool:
        """Ask a worker to stop heartbeating (and defer sends) for `ms`
        without dying — the hung/zombie fault for the chaos soak."""
        with self._lock:
            handle = self._seats.get(seat)
        if handle is None or handle.dead:
            return False
        try:
            ss.send_msg(handle.conn, {"type": "hang", "ms": int(ms)},
                        lock=handle.send_lock)
            return True
        except (ConnectionError, OSError):
            return False

    def pids(self) -> Dict[int, int]:
        with self._lock:
            return {h.seat: h.pid for h in self._seats.values()
                    if not h.dead}

    def busy_pids(self) -> Dict[int, int]:
        with self._lock:
            return {h.seat: h.pid for h in self._seats.values()
                    if not h.dead and h.inflight}

    # -- teardown ------------------------------------------------------

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            handles = list(self._seats.values())
            graveyard = list(self._graveyard)
            for h in handles + graveyard:
                h.closing = True
            self._cv.notify_all()
        for h in handles:
            try:
                ss.send_msg(h.conn, {"type": "shutdown"},
                            lock=h.send_lock)
            except (ConnectionError, OSError):
                pass
        for h in handles:
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    pass
        for h in graveyard:
            # a heartbeat-dead zombie may STILL be running: reap it now
            if h.proc is not None and h.proc.poll() is None:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    pass
        for h in handles + graveyard:
            try:
                h.conn.close()
            except OSError:
                pass
        self.watchdog.close()
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        self.server.close()
        shutil.rmtree(self._dir, ignore_errors=True)
        deactivate(self)


# ---------------------------------------------------------------------------
# Process-wide active pool (the local runner / service / monitor hook)
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active_pool: Optional[ExecutorPool] = None


def activate(pool: ExecutorPool) -> ExecutorPool:
    global _active_pool
    with _active_lock:
        _active_pool = pool
    return pool


def deactivate(pool: Optional[ExecutorPool] = None) -> None:
    global _active_pool
    with _active_lock:
        if pool is None or _active_pool is pool:
            _active_pool = None


def active() -> Optional[ExecutorPool]:
    with _active_lock:
        return _active_pool


def pool_stats() -> Optional[dict]:
    """Monitor-facing snapshot: None when no pool is active (gauges are
    omitted entirely in that mode — the in-process runtime has no
    executor topology to report)."""
    pool = active()
    if pool is None:
        return None
    stats = pool.stats()
    stats["executors"] = pool.executors()
    return stats


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------


class _Worker:
    """Executor-process main object: control-socket loop + beat thread.
    Task handlers run on their own threads (the driver bounds concurrency
    at conf.executor_slots); heavy engine imports are deferred to the
    first plan task so protocol-only workers stay cheap."""

    def __init__(self) -> None:
        self.token = os.environ[_ENV_TOKEN]
        self.ctl_path = os.environ[_ENV_CTL]
        self.shuffle_path = os.environ.get(_ENV_SHUFFLE, "")
        self.sock: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.stop = threading.Event()
        # hang fault (chaos): beats stop and outbound sends stall until
        # this monotonic instant — the process neither exits nor beats
        self.hang_until = 0.0
        self._client: Optional[ss.ShuffleClient] = None
        self._client_lock = threading.Lock()
        self._rid_refs: Dict[str, int] = {}
        self._rid_lock = threading.Lock()

    # -- plumbing ------------------------------------------------------

    def _send(self, header: dict, blob: bytes = b"") -> None:
        wait = self.hang_until - time.monotonic()
        if wait > 0:
            # a hung executor's results arrive LATE — after the driver
            # declared it dead and fenced its epoch
            time.sleep(wait)
        ss.send_msg(self.sock, header, blob, lock=self.send_lock)

    def _beat_loop(self) -> None:
        period = max(int(conf.executor_heartbeat_ms), 10) / 1000.0
        while not self.stop.wait(period):
            if time.monotonic() < self.hang_until:
                continue  # hung: silence, but stay alive
            try:
                ss.send_msg(self.sock, {"type": "beat"},
                            lock=self.send_lock)
            except (ConnectionError, OSError):
                # driver gone: a leaderless executor must not linger
                self.stop.set()
                os._exit(0)

    def shuffle_client(self) -> ss.ShuffleClient:
        with self._client_lock:
            if self._client is None:
                self._client = ss.ShuffleClient(self.shuffle_path)
            return self._client

    # -- task handlers -------------------------------------------------

    def _acquire_rid(self, rid: str, provider) -> None:
        from blaze_tpu.runtime import resources

        with self._rid_lock:
            n = self._rid_refs.get(rid, 0)
            self._rid_refs[rid] = n + 1
            if n == 0:
                resources.put(rid, provider)

    def _release_rid(self, rid: str) -> None:
        from blaze_tpu.runtime import resources

        with self._rid_lock:
            n = self._rid_refs.get(rid, 1) - 1
            if n <= 0:
                self._rid_refs.pop(rid, None)
                resources.pop(rid)
            else:
                self._rid_refs[rid] = n

    def _run_plan(self, payload: dict, blob: bytes, epoch: int) -> dict:
        from blaze_tpu.ops.base import ExecContext
        from blaze_tpu.plan import plan_pb2 as pb
        from blaze_tpu.runtime import artifacts
        from blaze_tpu.runtime.executor import run_pool_plan

        node = pb.PlanNode()
        node.ParseFromString(blob)
        # the fence stamp: this attempt's artifacts land on epoch-named
        # files, so even a zombie's completed write can't collide with a
        # retried attempt's output
        data_path = artifacts.stamp_epoch(node.shuffle_writer.data_file,
                                          epoch)
        index_path = artifacts.stamp_epoch(node.shuffle_writer.index_file,
                                           epoch)
        node.shuffle_writer.data_file = data_path
        node.shuffle_writer.index_file = index_path
        client = self.shuffle_client()
        rids = list(payload.get("rids") or [])

        def make_provider(rid):
            # exactly one positional param: _call_provider passes the
            # task partition to 1-arg providers (a default-arg closure
            # would be miscounted as 2-arg and handed num_partitions)
            def provider(partition):
                return iter(ss.split_frames(client.fetch(rid, partition)))
            return provider

        for rid in rids:
            self._acquire_rid(rid, make_provider(rid))
        try:
            ctx = ExecContext(partition=int(payload.get("partition", 0)),
                              num_partitions=int(
                                  payload.get("num_partitions", 1)))
            # the in-process resilience ladder runs INSIDE the worker:
            # transient faults retry here before costing the driver a
            # cross-process re-queue (runtime/executor.run_pool_plan)
            op = run_pool_plan(node, ctx,
                               what=payload.get("what", "pool_plan"))
            logical = int(op.metrics.values.get("shuffle_logical_bytes",
                                                0))
            return {"data_path": data_path, "index_path": index_path,
                    "logical_bytes": logical}
        finally:
            for rid in rids:
                self._release_rid(rid)

    def _run_flaky(self, payload: dict) -> dict:
        """Test handler: fail the first `times` attempts (counted in a
        driver-provided file so the count survives this process dying),
        then succeed."""
        from blaze_tpu.runtime import faults

        marker = payload["marker"]
        n = 0
        try:
            with open(marker, "r") as f:
                n = int(f.read().strip() or 0)
        except (OSError, ValueError):
            n = 0
        if n < int(payload.get("times", 1)):
            with open(marker, "w") as f:
                f.write(str(n + 1))
            cls = faults.CATEGORY_CLASSES.get(
                payload.get("category", "retryable"), faults.FatalError)
            raise cls(f"flaky task (attempt {n + 1})")
        return {"attempts_failed": n}

    def _run_task(self, msg: dict, blob: bytes) -> None:
        key, epoch = msg.get("task", ""), int(msg.get("epoch", 0))
        kind = msg.get("kind", "")
        payload = msg.get("payload") or {}
        try:
            if kind == "plan":
                result = self._run_plan(payload, blob, epoch)
            elif kind == "echo":
                result = {"value": payload.get("value")}
            elif kind == "sleep":
                end = time.monotonic() + float(payload.get("ms", 0)) / 1e3
                while time.monotonic() < end and not self.stop.is_set():
                    time.sleep(0.01)
                result = {}
            elif kind == "flaky":
                result = self._run_flaky(payload)
            else:
                raise ValueError(f"unknown task kind: {kind}")
        except BaseException as e:  # noqa: BLE001 — classified + relayed
            from blaze_tpu.runtime import faults

            try:
                self._send({"type": "result", "task": key, "epoch": epoch,
                            "ok": False, "category": faults.classify(e),
                            "error": type(e).__name__,
                            "message": str(e)[:500]})
            except (ConnectionError, OSError):
                pass
            return
        reply = {"type": "result", "task": key, "epoch": epoch,
                 "ok": True}
        reply.update(result)
        try:
            self._send(reply)
        except (ConnectionError, OSError):
            pass

    # -- main loop -----------------------------------------------------

    def run(self) -> int:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(self.ctl_path)
        self.sock = sock
        ss.send_msg(sock, {"type": "hello", "token": self.token,
                           "pid": os.getpid()}, lock=self.send_lock)
        beat = threading.Thread(target=self._beat_loop, name="blz-wk-beat",
                                daemon=True)
        beat.start()
        try:
            while not self.stop.is_set():
                try:
                    msg, blob = ss.recv_msg(sock)
                except (ConnectionError, OSError):
                    break  # driver gone
                mtype = msg.get("type")
                if mtype == "task":
                    threading.Thread(target=self._run_task,
                                     args=(msg, blob),
                                     name="blz-wk-task",
                                     daemon=True).start()
                elif mtype == "ping":
                    self._send({"type": "pong"})
                elif mtype == "hang":
                    self.hang_until = (time.monotonic()
                                       + int(msg.get("ms", 0)) / 1000.0)
                elif mtype == "shutdown":
                    break
        finally:
            self.stop.set()
            with self._client_lock:
                client, self._client = self._client, None
            if client is not None:
                client.close()
            try:
                sock.close()
            except OSError:
                pass
        return 0


def _worker_main() -> int:
    overrides = os.environ.get(_ENV_CONF, "")
    if overrides:
        for name, value in json.loads(overrides).items():
            if name in KNOBS:
                setattr(conf, name, value)
    return _Worker().run()


if __name__ == "__main__":
    if "--worker" in sys.argv:
        sys.exit(_worker_main())
    sys.exit("executor_pool is a library; run with --worker as a pool "
             "child process")
