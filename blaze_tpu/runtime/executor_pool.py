"""Process-isolated executor pool: crash containment for the runtime.

Ref: Spark's executor model (PAPER.md §1 — Spark remains the
distributed runtime; executors die, the driver detects it, lost
partitions are re-executed from persisted shuffle artifacts). This
module is that driver/executor split for the local runtime: N worker
PROCESSES, each owning a virtual device slice, receive TaskSpecs over a
length-prefixed control socket (the serde frame discipline —
runtime/shuffle_server.py holds the shared framing) and read upstream
shuffle input from the driver's ShuffleServer, so one hard fault (OOM
kill, segfault, wedged interpreter) costs ONE process, not the service.

The robustness path, not the transport, is the point:

  heartbeat   every worker pushes beats over the control socket; ANY
              inbound frame refreshes liveness (supervisor.ProcessPeer —
              the thread heartbeat posture generalized to PIDs).

  death       supervisor.ProcessWatchdog declares an executor dead on
              reap/exit (exact exit code / killing signal) or heartbeat
              staleness past conf.executor_death_ms — the latter may be
              a ZOMBIE that is still running.

  fencing     every task attempt carries an epoch (artifacts.EpochFence)
              stamped into its TaskSpec, its shuffle artifact names
              (`shuffle_0_1.e2.data`) and the result accounting: a
              re-queue advances the fence, so a zombie's late result is
              rejected at the driver (never double-counted) and its late
              files land on stale names that get swept — they can never
              overwrite the retried attempt's artifacts.

  lineage     only the LOST partitions re-execute: completed map outputs
              live in driver-committed .data/.index files served by the
              ShuffleServer, so surviving artifacts are re-read, not
              recomputed. Re-queues are bounded with exponential backoff.

  degradation on a death the pool's membership callbacks fire — the
              QueryService recomputes admission capacity as
              live_executors x conf.executor_slots, parks (re-queues)
              displaced arrivals instead of failing them, and restores
              capacity when the replacement process (bounded by
              conf.executor_restart_max, backed off) rejoins.

  telemetry   the cross-process observability plane (ISSUE 14). Each
              worker runs its own bounded TraceLog ring
              (conf.executor_trace_events) and monitor counters, stamps
              records with the driver-issued correlation ids replayed
              from the task payload, and ships batched deltas back as
              "telemetry" frames on the control socket — every
              conf.telemetry_ship_ms AND immediately before each result
              frame, so counters are federated before the driver closes
              the stage span that reads them. Before every ship the
              batch is spilled crash-atomically to a per-worker sidecar
              file (<token>.telemetry); on a death the driver recovers
              the unshipped tail from the sidecar, idempotently (batch
              seq watermark), marking the records truncated=true. A
              clock-offset estimate from the hello echo (bounded by
              conf.clock_skew_bound_ms, refined by the min observed
              transit) rebases worker monotonic timestamps onto the
              driver's, so one merged Chrome trace renders a pid row
              per executor. Frames from a declared-dead (zombie) handle
              are dropped — the sidecar already covered them; accepting
              both would double-count.

Worker processes are spawned as `python -m
blaze_tpu.runtime.executor_pool --worker` with their identity and socket
paths in the environment; the driver-side conf snapshot rides along so
knobs agree across the process boundary.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from blaze_tpu.config import KNOBS, conf
from blaze_tpu.runtime import shuffle_server as ss

_ENV_TOKEN = "BLAZE_EXEC_TOKEN"
_ENV_SEAT = "BLAZE_EXEC_SEAT"
_ENV_CTL = "BLAZE_EXEC_SOCK"
_ENV_SHUFFLE = "BLAZE_EXEC_SHUFFLE_SOCK"
_ENV_CONF = "BLAZE_TPU_WORKER_CONF"

# knobs a worker must NOT inherit verbatim: a worker never spawns its own
# pool, never serves metrics, and never EXPORTS traces/dossiers/history
# (the driver owns exporting; worker-side trace records buffer in the
# local ring and ship back over the control socket — _spawn additionally
# sets trace_enabled/trace_buffer_events dynamically from the driver's
# tracing state)
_WORKER_CONF_OVERRIDES = {
    "executor_count": 0,
    "metrics_port": 0,
    "trace_export_dir": "",
    "history_dir": "",
    "flight_dir": "",
    "progress_enabled": False,
    "fault_injection_spec": {},
    # only the driver journals (one journal per query) or replays them
    "journal_dir": "",
    "recovery_enabled": False,
}


def _clamp_offset(offset_ns: int) -> int:
    """Bound a clock-offset estimate to ±conf.clock_skew_bound_ms: one
    bad echo (a worker descheduled mid-handshake) must not scramble
    merged-trace ordering by seconds."""
    bound = max(int(conf.clock_skew_bound_ms), 0) * 1_000_000
    return max(-bound, min(bound, int(offset_ns)))


class PoolTaskSpec:
    """One schedulable unit for the process pool (the TaskSpec twin for
    the process boundary: everything must be serializable). `key` is the
    fence key — unique per logical task; `payload` is the JSON header the
    worker dispatches on; `blob` carries the plan proto bytes."""

    __slots__ = ("key", "kind", "payload", "blob", "what")

    def __init__(self, key: str, kind: str, payload: Optional[dict] = None,
                 blob: bytes = b"", what: str = "") -> None:
        self.key = key
        self.kind = kind
        self.payload = dict(payload or {})
        self.blob = blob
        self.what = what or key


class _PoolTask:
    """Pool-internal task state: current epoch, retry/death budgets, and
    the terminal outcome."""

    __slots__ = ("spec", "epoch", "state", "result", "error", "tries",
                 "death_requeues", "not_before", "executor")

    def __init__(self, spec: PoolTaskSpec, epoch: int) -> None:
        self.spec = spec
        self.epoch = epoch
        self.state = "queued"  # queued | running | done | error
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.tries = 0
        self.death_requeues = 0
        self.not_before = 0.0
        self.executor: Optional["ExecutorHandle"] = None

    @property
    def finished(self) -> bool:
        return self.state in ("done", "error")


class ExecutorHandle:
    """Driver-side view of one executor process."""

    def __init__(self, seat: int, generation: int, token: str, pid: int,
                 proc: Optional[subprocess.Popen],
                 conn: socket.socket) -> None:
        self.seat = seat
        self.generation = generation
        self.token = token
        self.pid = pid
        self.proc = proc
        self.conn = conn
        self.send_lock = threading.Lock()
        self.inflight: Dict[str, _PoolTask] = {}  # guarded by pool lock
        self.dead = False                         # guarded by pool lock
        self.closing = False
        self.joined_at = time.monotonic()
        self.last_beat = self.joined_at
        # telemetry federation state (guarded by pool lock):
        # clock_offset_ns rebases this worker's monotonic timestamps
        # onto the driver's; tel_seq is the highest batch ingested (the
        # sidecar-recovery dedup watermark)
        self.clock_offset_ns = 0
        self.tel_seq = 0
        self.tel_bytes = 0
        self.tel_records = 0
        self.tel_dropped = 0
        self.tasks_done = 0

    @property
    def exec_id(self) -> str:
        return f"exec{self.seat}"


class PoolUnavailableError(ConnectionError):
    """No live executor can run a queued task and no replacement is
    pending: callers degrade to the in-process runtime."""


class ExecutorPool:
    """Spawns, supervises, feeds and buries executor processes.

    Lifecycle: `start()` spawns conf.executor_count workers and waits
    for their control-socket handshakes; `run_tasks(specs)` executes a
    batch with epoch-fenced re-queue on executor death; `close()` tears
    everything down. `activate(pool)` publishes the pool process-wide so
    the local runner routes eligible stages here and the service derives
    its admission capacity from membership."""

    _READY_TIMEOUT = 90.0
    _HELLO_TIMEOUT = 30.0

    def __init__(self, count: Optional[int] = None,
                 slots: Optional[int] = None) -> None:
        self.count = int(count if count is not None
                         else conf.executor_count)
        self.slots = max(1, int(slots if slots is not None
                                else conf.executor_slots))
        from blaze_tpu.runtime import artifacts, supervisor

        self.fence = artifacts.EpochFence()
        self.watchdog = supervisor.ProcessWatchdog()
        self._dir = tempfile.mkdtemp(prefix="blzex-")
        # pool-unique token prefix: two pools in one process (tests, a
        # service restart) must not collide in the flight recorder's
        # (query_id, trigger) exactly-once dedup or the watchdog registry
        self._pool_id = os.path.basename(self._dir)[len("blzex-"):]
        self._ctl_path = os.path.join(self._dir, "ctl.sock")
        self.server = ss.ShuffleServer(os.path.join(self._dir, "shf.sock"))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._seats: Dict[int, ExecutorHandle] = {}
        # declared-dead handles: a heartbeat-dead ZOMBIE's socket stays
        # open (its late results must arrive to be fenced) and its
        # process may still run — close() reaps whatever is left here
        self._graveyard: List[ExecutorHandle] = []
        self._awaiting: Dict[str, tuple] = {}  # token -> (seat, gen, proc)
        self._queue: List[_PoolTask] = []
        self._running: Dict[str, _PoolTask] = {}
        self._seat_restarts: Dict[int, int] = {}
        self._respawns_pending = 0
        self._membership_cbs: List[Callable[["ExecutorPool"], None]] = []
        self._closed = False
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self.deaths_total = 0
        self.restarts_total = 0
        self.tasks_done = 0
        self.telemetry_bytes_total = 0
        self.telemetry_records_total = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ExecutorPool":
        if self.count <= 0:
            raise ValueError("executor pool needs count >= 1")
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self._ctl_path)
        listener.listen(self.count * 2 + 4)
        self._listener = listener
        self.server.start()
        for name, target in (("blz-pool-accept", self._accept_loop),
                             ("blz-pool-dispatch", self._dispatch_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        for seat in range(self.count):
            self._spawn(seat, 0)
        deadline = time.monotonic() + self._READY_TIMEOUT
        with self._cv:
            while (len([h for h in self._seats.values() if not h.dead])
                   < self.count):
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"executor pool: {len(self._seats)}/{self.count} "
                        f"workers joined within {self._READY_TIMEOUT}s")
                self._cv.wait(min(left, 0.25))
        return self

    def _spawn(self, seat: int, generation: int) -> None:
        token = f"exec{seat}g{generation}.{self._pool_id}"
        env = dict(os.environ)
        env[_ENV_TOKEN] = token
        env[_ENV_SEAT] = str(seat)
        env[_ENV_CTL] = self._ctl_path
        env[_ENV_SHUFFLE] = self.server.sock_path
        snapshot = {name: getattr(conf, name) for name in KNOBS}
        snapshot.update(_WORKER_CONF_OVERRIDES)
        # the worker traces exactly when the driver does — into its own
        # SMALL bounded ring (the driver-sized ring would let a chatty
        # worker hold megabytes of unshipped records)
        snapshot["trace_enabled"] = bool(conf.trace_enabled)
        snapshot["trace_buffer_events"] = int(conf.executor_trace_events)
        env[_ENV_CONF] = json.dumps(snapshot)
        # the worker resolves blaze_tpu by module name regardless of the
        # driver's cwd (pytest may chdir into a tmp dir)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        err_path = os.path.join(self._dir, f"{token}.err")
        with open(err_path, "ab") as err:
            proc = subprocess.Popen(
                [sys.executable, "-m", "blaze_tpu.runtime.executor_pool",
                 "--worker"],
                env=env, stdin=subprocess.DEVNULL,
                stdout=subprocess.DEVNULL, stderr=err)
        with self._cv:
            self._awaiting[token] = (seat, generation, proc)
        from blaze_tpu.runtime import trace

        trace.event("executor_spawn", exec_id=f"exec{seat}",
                    generation=generation, pid=proc.pid)

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._handshake, args=(conn,),
                             name="blz-pool-hello", daemon=True).start()

    def _handshake(self, conn: socket.socket) -> None:
        conn.settimeout(self._HELLO_TIMEOUT)
        try:
            msg, _blob = ss.recv_msg(conn)
        except (ConnectionError, OSError):
            conn.close()
            return
        conn.settimeout(None)
        token = msg.get("token", "")
        with self._cv:
            pending = self._awaiting.pop(token, None)
        if msg.get("type") != "hello" or pending is None:
            conn.close()
            return
        seat, generation, proc = pending
        handle = ExecutorHandle(seat, generation, token,
                                int(msg.get("pid", proc.pid)), proc, conn)
        # clock-offset estimate from the hello echo: the worker stamps
        # its monotonic clock into the hello; (driver_now - worker_then)
        # = true offset + one-way transit, so the estimate is inflated
        # by transit and refined downward by later frames (_on_telemetry
        # keeps the minimum candidate — least transit, closest to truth)
        mono = msg.get("mono_ns")
        if mono is not None:
            handle.clock_offset_ns = _clamp_offset(
                time.monotonic_ns() - int(mono))
        with self._cv:
            if self._closed:
                handle.closing = True
            self._seats[seat] = handle
            self._cv.notify_all()
        if handle.closing:
            conn.close()
            return
        self.watchdog.register(
            token, handle.pid,
            lambda peer, reason, rc, h=handle: self._declare_dead(
                h, reason, rc, emit_event=False),
            poll=proc.poll)
        t = threading.Thread(target=self._reader, args=(handle,),
                             name=f"blz-pool-rd-{seat}", daemon=True)
        t.start()
        self._threads.append(t)
        self._notify_membership()

    # -- socket reader -------------------------------------------------

    def _reader(self, handle: ExecutorHandle) -> None:
        """Per-executor inbound loop. Keeps reading a heartbeat-declared
        zombie's socket so its late results arrive — and get fenced —
        instead of rotting in the kernel buffer."""
        while True:
            try:
                msg, _blob = ss.recv_msg(handle.conn)
            except (ConnectionError, OSError):
                break
            handle.last_beat = time.monotonic()
            self.watchdog.beat(handle.token)
            mtype = msg.get("type")
            if mtype == "result":
                self._on_result(handle, msg)
            elif mtype == "telemetry":
                self._on_telemetry(handle, msg)
        if not handle.closing:
            # EOF before shutdown: the process died (or is dying) — don't
            # wait the heartbeat staleness out
            self._declare_dead(handle, "exit",
                               handle.proc.poll() if handle.proc else None)

    def _on_result(self, handle: ExecutorHandle, msg: dict) -> None:
        from blaze_tpu.runtime import artifacts

        key, epoch = msg.get("task", ""), int(msg.get("epoch", 0))
        if not self.fence.admit(key, epoch):
            # the zombie's late write: reject the result and sweep its
            # stale-named files; the ledger never sees it
            for p in (msg.get("data_path"), msg.get("index_path")):
                if p and artifacts.epoch_of(p) == epoch:
                    artifacts._unlink_quiet(p)
            return
        with self._cv:
            task = self._running.get(key)
            if task is None or task.epoch != epoch:
                return
            del self._running[key]
            handle.inflight.pop(key, None)
            if msg.get("ok"):
                task.state, task.result = "done", msg
                self.tasks_done += 1
                handle.tasks_done += 1
            else:
                self._handle_task_failure_locked(task, msg)
            self._cv.notify_all()

    # -- telemetry federation ------------------------------------------

    def _on_telemetry(self, handle: ExecutorHandle, msg: dict) -> None:
        """Ingest one batched telemetry frame from a live executor.

        Zombie posture mirrors _on_result: frames from a declared-dead
        handle are DROPPED — its unshipped tail was already recovered
        from the sidecar at death, and accepting the late socket copy
        too would double-count it. The batch seq watermark makes the
        sidecar recovery idempotent in the other direction (a sidecar
        whose batch already arrived over the socket is skipped)."""
        with self._cv:
            if handle.dead or self._closed:
                return
            seq = int(msg.get("seq", 0))
            if seq <= handle.tel_seq:
                return  # duplicate / reordered batch
            handle.tel_seq = seq
            # refine the clock offset: every frame carries the worker's
            # send-time monotonic clock; the minimum candidate has the
            # least transit inflation
            mono = msg.get("mono_ns")
            if mono is not None:
                cand = _clamp_offset(time.monotonic_ns() - int(mono))
                if cand < handle.clock_offset_ns:
                    handle.clock_offset_ns = cand
        self._ingest_batch(handle, msg, truncated=False)

    def _ingest_batch(self, handle: ExecutorHandle, msg: dict,
                      truncated: bool) -> None:
        """Federate one telemetry batch (socket frame or recovered
        sidecar) into the driver's observability plane: trace records
        rebased + stamped into the ring, counter deltas merged into the
        per-query roll-ups, histogram deltas folded in."""
        from blaze_tpu.runtime import monitor, trace

        records = msg.get("records") or []
        n = trace.ingest_remote(records, exec_id=handle.exec_id,
                                pid=handle.pid,
                                offset_ns=handle.clock_offset_ns,
                                truncated=truncated)
        monitor.merge_remote(msg.get("counters") or {})
        trace.ingest_histograms(msg.get("histograms") or {})
        nbytes = int(msg.get("nbytes") or 0)
        with self._lock:
            handle.tel_records += len(records)
            handle.tel_bytes += nbytes
            handle.tel_dropped = int(msg.get("dropped") or 0)
            self.telemetry_records_total += len(records)
            self.telemetry_bytes_total += nbytes
        if truncated:
            trace.event("telemetry_recovered", exec_id=handle.exec_id,
                        records=n, seq=int(msg.get("seq", 0)),
                        nbytes=nbytes)
        else:
            trace.event("telemetry_shipped", exec_id=handle.exec_id,
                        records=n, seq=int(msg.get("seq", 0)),
                        nbytes=nbytes)

    def _handle_task_failure_locked(self, task: _PoolTask,
                                    msg: dict) -> None:
        from blaze_tpu.runtime import faults, trace

        category = msg.get("category", "fatal")
        retryable = category in ("retryable", "resource")
        if retryable and task.tries < int(conf.max_task_retries):
            task.tries += 1
            task.epoch = self.fence.advance(task.spec.key)
            task.not_before = (time.monotonic()
                               + conf.retry_backoff_ms
                               * (2 ** (task.tries - 1)) / 1000.0)
            task.state = "queued"
            task.executor = None
            self._queue.append(task)
            trace.event("executor_task_requeued", task=task.spec.key,
                        cause="error", category=category,
                        epoch=task.epoch, tries=task.tries)
            return
        cls = faults.CATEGORY_CLASSES.get(category, faults.FatalError)
        task.state = "error"
        task.error = cls(
            f"{task.spec.what}: executor task failed "
            f"[{msg.get('error', '?')}] {msg.get('message', '')}")

    # -- death & recovery ----------------------------------------------

    def _declare_dead(self, handle: ExecutorHandle, reason: str,
                      rc: Optional[int], emit_event: bool = True) -> None:
        """Idempotent executor-death path: fence + re-queue the in-flight
        tasks, record the dossier, recompute capacity, schedule the
        replacement. Runs from the watchdog, a reader EOF, or a failed
        send — first caller wins."""
        from blaze_tpu.runtime import faults, trace

        now = time.monotonic()
        with self._cv:
            if handle.dead or self._closed:
                return
            handle.dead = True
            displaced = list(handle.inflight.values())
            handle.inflight.clear()
            self.deaths_total += 1
            recovery: Dict[str, str] = {}
            for task in displaced:
                self._running.pop(task.spec.key, None)
                if (task.death_requeues
                        < max(1, int(conf.executor_restart_max))):
                    task.death_requeues += 1
                    task.epoch = self.fence.advance(task.spec.key)
                    task.not_before = (
                        now + conf.retry_backoff_ms
                        * (2 ** (task.death_requeues - 1)) / 1000.0)
                    task.state = "queued"
                    task.executor = None
                    self._queue.append(task)
                    recovery[task.spec.key] = "re-queued"
                else:
                    task.state = "error"
                    task.error = faults.FatalError(
                        f"{task.spec.what}: lost to repeated executor "
                        f"deaths ({task.death_requeues} re-queues)")
                    recovery[task.spec.key] = "shed"
            self._graveyard.append(handle)
            restarts = self._seat_restarts.get(handle.seat, 0)
            will_respawn = restarts < int(conf.executor_restart_max)
            if will_respawn:
                self._seat_restarts[handle.seat] = restarts + 1
                self._respawns_pending += 1
            self._cv.notify_all()
        self.watchdog.unregister(handle.token)
        if emit_event:
            # the watchdog path already emitted its executor_death event
            trace.event("executor_death", exec_id=handle.token,
                        pid=handle.pid, reason=reason, exit_code=rc)
        for task in displaced:
            if recovery.get(task.spec.key) == "re-queued":
                trace.event("executor_task_requeued", task=task.spec.key,
                            cause="executor_death", epoch=task.epoch)
        recovered = self._recover_sidecar(handle)
        self._capture_death_dossier(handle, reason, rc, displaced,
                                    recovery, now, recovered)
        self._notify_membership()
        if will_respawn:
            threading.Thread(
                target=self._respawn, args=(handle.seat, restarts,
                                            handle.generation + 1),
                name="blz-pool-respawn", daemon=True).start()
        else:
            trace.event("degrade", what="executor_retired",
                        exec_id=handle.exec_id, restarts=restarts)

    def _recover_sidecar(self, handle: ExecutorHandle) -> List[dict]:
        """Crash recovery for the telemetry plane: a SIGKILL'd worker's
        unshipped ring tail survives in its crash-atomic sidecar spill
        (written tmp+rename BEFORE every ship). Ingest it exactly once —
        the batch seq watermark skips a sidecar whose batch DID arrive
        over the socket before death — marking every recovered record
        truncated=true (the span stream ended mid-flight). Returns the
        recovered records for the death dossier."""
        path = os.path.join(self._dir, f"{handle.token}.telemetry")
        try:
            nbytes = os.path.getsize(path)
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return []
        if not isinstance(doc, dict):
            return []
        if int(doc.get("seq", 0)) <= handle.tel_seq:
            return []  # tail already shipped over the socket
        handle.tel_seq = int(doc.get("seq", 0))
        doc.setdefault("nbytes", nbytes)
        self._ingest_batch(handle, doc, truncated=True)
        return list(doc.get("records") or [])

    def _capture_death_dossier(self, handle: ExecutorHandle, reason: str,
                               rc: Optional[int], displaced, recovery,
                               now: float,
                               recovered: Optional[List[dict]] = None
                               ) -> None:
        if not conf.flight_dir:
            return
        from blaze_tpu.runtime import flight_recorder

        signal_no = -rc if (rc is not None and rc < 0) else None
        # one dossier per kill: keyed on the executor GENERATION token,
        # so a seat's successive deaths each capture exactly once
        flight_recorder.capture(
            "executor_death", handle.token, detail={
                "exec_id": handle.exec_id,
                "seat": handle.seat,
                "generation": handle.generation,
                "pid": handle.pid,
                "reason": reason,
                "exit_code": rc,
                "signal": signal_no,
                "last_heartbeat_age_ms": round(
                    (now - handle.last_beat) * 1000),
                "tasks_in_flight": [t.spec.what for t in displaced],
                "recovery": recovery,
                "live_executors": self.live_count(),
                "capacity": self.capacity(),
                # the dead worker's own last spans as spilled (raw
                # worker-clock ts; clock_offset_ms above rebases them;
                # the driver ring holds the rebased truncated copies) —
                # bounded: a dossier is a summary, not a trace export
                "clock_offset_ms": round(
                    handle.clock_offset_ns / 1e6, 3),
                "executor_trace": list(recovered or [])[-200:],
            })


    def _respawn(self, seat: int, restarts: int, generation: int) -> None:
        backoff = (conf.executor_restart_backoff_ms
                   * (2 ** restarts) / 1000.0)
        time.sleep(backoff)
        with self._cv:
            self._respawns_pending -= 1
            if self._closed:
                return
        self.restarts_total += 1
        self._spawn(seat, generation)

    # -- membership / capacity -----------------------------------------

    def on_membership(self, cb: Callable[["ExecutorPool"], None]) -> None:
        with self._lock:
            self._membership_cbs.append(cb)

    def _notify_membership(self) -> None:
        with self._lock:
            cbs = list(self._membership_cbs)
        for cb in cbs:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — listeners must not wedge us
                pass

    def live_handles(self) -> List[ExecutorHandle]:
        with self._lock:
            return [h for h in self._seats.values() if not h.dead]

    def live_count(self) -> int:
        return len(self.live_handles())

    def capacity(self) -> int:
        return self.live_count() * self.slots

    def executors(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            return [{"exec_id": h.exec_id, "pid": h.pid,
                     "generation": h.generation, "up": not h.dead,
                     "inflight": len(h.inflight),
                     "heartbeat_age_ms": round(
                         (now - h.last_beat) * 1000),
                     "tasks_done": h.tasks_done,
                     "telemetry_bytes": h.tel_bytes,
                     "telemetry_records": h.tel_records,
                     "telemetry_dropped": h.tel_dropped,
                     "clock_offset_ms": round(h.clock_offset_ns / 1e6, 3)}
                    for h in self._seats.values()]

    def stats(self) -> dict:
        with self._lock:
            live = sum(1 for h in self._seats.values() if not h.dead)
            inflight = sum(len(h.inflight) for h in self._seats.values())
            deaths, restarts = self.deaths_total, self.restarts_total
            done = self.tasks_done
            tel_bytes = self.telemetry_bytes_total
            tel_records = self.telemetry_records_total
        return {"count": self.count, "live": live,
                "capacity": live * self.slots, "slots": self.slots,
                "inflight": inflight, "deaths_total": deaths,
                "restarts_total": restarts,
                "fenced_total": self.fence.fenced_total,
                "tasks_done": done,
                "telemetry_bytes_total": tel_bytes,
                "telemetry_records_total": tel_records}

    # -- dispatch ------------------------------------------------------

    def _pick_locked(self) -> Optional[tuple]:
        now = time.monotonic()
        handles = [h for h in self._seats.values()
                   if not h.dead and len(h.inflight) < self.slots]
        if not handles:
            return None
        for i, task in enumerate(self._queue):
            if task.not_before <= now:
                handle = min(handles, key=lambda h: (len(h.inflight),
                                                     h.seat))
                self._queue.pop(i)
                task.state = "running"
                task.executor = handle
                handle.inflight[task.spec.key] = task
                self._running[task.spec.key] = task
                return task, handle
        return None

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                picked = self._pick_locked()
                while picked is None and not self._closed:
                    timeout = 0.05 if self._queue else None
                    self._cv.wait(timeout)
                    picked = self._pick_locked()
                if picked is None:
                    return  # closed
            task, handle = picked
            header = {"type": "task", "task": task.spec.key,
                      "epoch": task.epoch, "kind": task.spec.kind,
                      "payload": task.spec.payload}
            try:
                ss.send_msg(handle.conn, header, task.spec.blob,
                            lock=handle.send_lock)
            except (ConnectionError, OSError):
                # broken pipe: the executor is gone; death handling
                # re-queues this task (it is in handle.inflight)
                self._declare_dead(handle, "send_error",
                                   handle.proc.poll() if handle.proc
                                   else None)

    # -- public task API -----------------------------------------------

    def run_tasks(self, specs: List[PoolTaskSpec],
                  timeout: Optional[float] = None) -> List[dict]:
        """Run a batch of tasks, returning their result messages in spec
        order. Raises the first task error (classified), or
        PoolUnavailableError when every executor seat is retired —
        callers degrade to the in-process runtime."""
        if not specs:
            return []
        from blaze_tpu.runtime import faults

        tasks = [_PoolTask(spec, self.fence.advance(spec.key))
                 for spec in specs]
        deadline = (time.monotonic() + timeout) if timeout else None
        try:
            with self._cv:
                if self._closed:
                    raise RuntimeError("executor pool is closed")
                self._queue.extend(tasks)
                self._cv.notify_all()
                while True:
                    if all(t.finished for t in tasks):
                        break
                    if self._closed:
                        raise RuntimeError(
                            "executor pool closed mid-stage")
                    alive = any(not h.dead
                                for h in self._seats.values())
                    if (not alive and self._respawns_pending == 0
                            and not self._awaiting):
                        self._abandon_locked(tasks)
                        raise PoolUnavailableError(
                            "no live executors and no replacement "
                            "pending")
                    if (deadline is not None
                            and time.monotonic() > deadline):
                        self._abandon_locked(tasks)
                        raise faults.DeadlineError(
                            "executor pool stage timed out")
                    self._cv.wait(0.1)
            errs = [t for t in tasks if t.state == "error"]
            if errs:
                raise errs[0].error
            return [t.result for t in tasks]
        finally:
            # a straggler result after this point finds no fence entry
            # (missing key == epoch 0) and is rejected like any stale
            # attempt, so forgetting keeps the fence bounded per batch
            for spec in specs:
                self.fence.forget(spec.key)

    def _abandon_locked(self, tasks: List[_PoolTask]) -> None:
        """Drop a failed batch: unqueue its pending tasks and fence its
        running ones so straggler results are rejected."""
        for t in tasks:
            if t.state == "queued":
                try:
                    self._queue.remove(t)
                except ValueError:
                    pass
                t.state = "error"
                if t.error is None:
                    from blaze_tpu.runtime import faults

                    t.error = faults.FaultError("sibling task failed")
            elif t.state == "running":
                self._running.pop(t.spec.key, None)
                if t.executor is not None:
                    t.executor.inflight.pop(t.spec.key, None)
                self.fence.advance(t.spec.key)  # fence the straggler

    # -- chaos / test hooks --------------------------------------------

    def hang_executor(self, seat: int, ms: int) -> bool:
        """Ask a worker to stop heartbeating (and defer sends) for `ms`
        without dying — the hung/zombie fault for the chaos soak."""
        with self._lock:
            handle = self._seats.get(seat)
        if handle is None or handle.dead:
            return False
        try:
            ss.send_msg(handle.conn, {"type": "hang", "ms": int(ms)},
                        lock=handle.send_lock)
            return True
        except (ConnectionError, OSError):
            return False

    def pids(self) -> Dict[int, int]:
        with self._lock:
            return {h.seat: h.pid for h in self._seats.values()
                    if not h.dead}

    def busy_pids(self) -> Dict[int, int]:
        with self._lock:
            return {h.seat: h.pid for h in self._seats.values()
                    if not h.dead and h.inflight}

    # -- teardown ------------------------------------------------------

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            handles = list(self._seats.values())
            graveyard = list(self._graveyard)
            for h in handles + graveyard:
                h.closing = True
            self._cv.notify_all()
        for h in handles:
            try:
                ss.send_msg(h.conn, {"type": "shutdown"},
                            lock=h.send_lock)
            except (ConnectionError, OSError):
                pass
        for h in handles:
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    pass
        for h in graveyard:
            # a heartbeat-dead zombie may STILL be running: reap it now
            if h.proc is not None and h.proc.poll() is None:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    pass
        for h in handles + graveyard:
            try:
                h.conn.close()
            except OSError:
                pass
        self.watchdog.close()
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        self.server.close()
        shutil.rmtree(self._dir, ignore_errors=True)
        deactivate(self)


# ---------------------------------------------------------------------------
# Process-wide active pool (the local runner / service / monitor hook)
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active_pool: Optional[ExecutorPool] = None


def activate(pool: ExecutorPool) -> ExecutorPool:
    global _active_pool
    with _active_lock:
        _active_pool = pool
    return pool


def deactivate(pool: Optional[ExecutorPool] = None) -> None:
    global _active_pool
    with _active_lock:
        if pool is None or _active_pool is pool:
            _active_pool = None


def active() -> Optional[ExecutorPool]:
    with _active_lock:
        return _active_pool


def pool_stats() -> Optional[dict]:
    """Monitor-facing snapshot: None when no pool is active (gauges are
    omitted entirely in that mode — the in-process runtime has no
    executor topology to report)."""
    pool = active()
    if pool is None:
        return None
    stats = pool.stats()
    stats["executors"] = pool.executors()
    return stats


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------


def _merge_counter_deltas(dst: Dict[str, dict],
                          src: Dict[str, dict]) -> None:
    """Fold freshly-drained monitor deltas into the worker's pending
    (unshipped) counters — a ship failure keeps pending populated, so
    successive drains must accumulate, not replace."""
    for qid, d in src.items():
        qd = dst.setdefault(qid, {})
        for sect, vals in d.items():
            s = qd.setdefault(sect, {})
            if sect == "stage_time_ns":
                for sk, cats in vals.items():
                    sc = s.setdefault(sk, {})
                    for cat, n in cats.items():
                        sc[cat] = sc.get(cat, 0) + n
            else:
                for k, n in vals.items():
                    s[k] = s.get(k, 0) + n


def _merge_hist_snaps(dst: Dict[str, dict], src: Dict[str, dict]) -> None:
    """Fold histogram snapshot deltas (bucket-count sums) into pending."""
    for name, s in src.items():
        cur = dst.get(name)
        if cur is None:
            dst[name] = dict(s)
            continue
        counts = list(cur.get("counts") or ())
        for i, n in enumerate(s.get("counts") or ()):
            if i < len(counts):
                counts[i] += n
            else:
                counts.append(n)
        cur["counts"] = counts
        cur["count"] = int(cur.get("count") or 0) + int(s.get("count") or 0)
        cur["total"] = int(cur.get("total") or 0) + int(s.get("total") or 0)
        for key, pick in (("min", min), ("max", max)):
            a, b = cur.get(key), s.get(key)
            cur[key] = b if a is None else (a if b is None else pick(a, b))


class _Worker:
    """Executor-process main object: control-socket loop + beat thread.
    Task handlers run on their own threads (the driver bounds concurrency
    at conf.executor_slots); heavy engine imports are deferred to the
    first plan task so protocol-only workers stay cheap."""

    def __init__(self) -> None:
        self.token = os.environ[_ENV_TOKEN]
        self.ctl_path = os.environ[_ENV_CTL]
        self.shuffle_path = os.environ.get(_ENV_SHUFFLE, "")
        self.sock: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.stop = threading.Event()
        # hang fault (chaos): beats stop and outbound sends stall until
        # this monotonic instant — the process neither exits nor beats
        self.hang_until = 0.0
        self._client: Optional[ss.ShuffleClient] = None
        self._client_lock = threading.Lock()
        self._rid_refs: Dict[str, int] = {}
        self._rid_lock = threading.Lock()
        # telemetry shipping state: pending holds drained-but-unshipped
        # records/counters (a failed send keeps them; the sidecar spill
        # already covers them on disk), seq is the batch watermark the
        # driver dedups sidecar recovery against
        self._tel_lock = threading.Lock()
        self._tel_seq = 0
        self._tel_pending: List[dict] = []
        self._tel_counters: Dict[str, dict] = {}
        self._tel_hists: Dict[str, dict] = {}
        self._sidecar = os.path.join(os.path.dirname(self.ctl_path),
                                     f"{self.token}.telemetry")

    # -- plumbing ------------------------------------------------------

    def _send(self, header: dict, blob: bytes = b"") -> None:
        wait = self.hang_until - time.monotonic()
        if wait > 0:
            # a hung executor's results arrive LATE — after the driver
            # declared it dead and fenced its epoch
            time.sleep(wait)
        ss.send_msg(self.sock, header, blob, lock=self.send_lock)

    def _beat_loop(self) -> None:
        period = max(int(conf.executor_heartbeat_ms), 10) / 1000.0
        while not self.stop.wait(period):
            if time.monotonic() < self.hang_until:
                continue  # hung: silence, but stay alive
            try:
                ss.send_msg(self.sock, {"type": "beat"},
                            lock=self.send_lock)
            except (ConnectionError, OSError):
                # driver gone: a leaderless executor must not linger
                self.stop.set()
                os._exit(0)

    # -- telemetry shipping --------------------------------------------

    def _flush_telemetry(self) -> None:
        """Stage the unshipped ring tail + counter/histogram deltas,
        spill them crash-atomically to the sidecar, then ship ONE
        batched "telemetry" frame. Ordering matters twice: the spill
        lands BEFORE the send (a SIGKILL between the two loses nothing
        the driver can't recover), and _run_task flushes BEFORE each
        result send on the same socket (frames are processed in order,
        so the driver merges this batch's counters before the stage
        span that reads them closes). A failed send keeps the batch
        pending — same seq, retried next tick — so the driver's seq
        watermark stays exactly-once."""
        from blaze_tpu.runtime import monitor, trace

        if not (conf.trace_enabled or conf.monitor_enabled):
            return
        with self._tel_lock:
            self._tel_pending.extend(trace.TRACE.drain())
            _merge_counter_deltas(self._tel_counters,
                                  monitor.drain_remote_deltas())
            _merge_hist_snaps(self._tel_hists,
                              trace.histograms_snapshot(reset=True))
            if not (self._tel_pending or self._tel_counters
                    or self._tel_hists):
                return
            seq = self._tel_seq + 1
            doc = {"type": "telemetry", "seq": seq,
                   "records": self._tel_pending,
                   "counters": self._tel_counters,
                   "histograms": self._tel_hists,
                   "dropped": trace.TRACE.dropped,
                   "mono_ns": time.monotonic_ns()}
            payload = json.dumps(doc, default=str)
            doc["nbytes"] = len(payload)
            tmp = self._sidecar + ".tmp"
            try:
                with open(tmp, "w") as f:
                    f.write(payload)
                os.replace(tmp, self._sidecar)
            except OSError:
                pass  # spill is best-effort; the socket ship still runs
            try:
                self._send(doc)
            except (ConnectionError, OSError):
                return  # keep pending; beat loop notices a dead driver
            self._tel_seq = seq
            self._tel_pending = []
            self._tel_counters = {}
            self._tel_hists = {}

    def _ship_loop(self) -> None:
        period_ms = int(conf.telemetry_ship_ms)
        if period_ms <= 0:
            return  # timer disabled; results still carry their flush
        period = max(period_ms, 10) / 1000.0
        while not self.stop.wait(period):
            if time.monotonic() < self.hang_until:
                continue  # hung: the telemetry plane stalls with beats
            try:
                self._flush_telemetry()
            except Exception:  # noqa: BLE001 — never kill the worker
                pass

    def shuffle_client(self) -> ss.ShuffleClient:
        with self._client_lock:
            if self._client is None:
                self._client = ss.ShuffleClient(self.shuffle_path)
            return self._client

    # -- task handlers -------------------------------------------------

    def _acquire_rid(self, rid: str, provider) -> None:
        from blaze_tpu.runtime import resources

        with self._rid_lock:
            n = self._rid_refs.get(rid, 0)
            self._rid_refs[rid] = n + 1
            if n == 0:
                resources.put(rid, provider)

    def _release_rid(self, rid: str) -> None:
        from blaze_tpu.runtime import resources

        with self._rid_lock:
            n = self._rid_refs.get(rid, 1) - 1
            if n <= 0:
                self._rid_refs.pop(rid, None)
                resources.pop(rid)
            else:
                self._rid_refs[rid] = n

    def _run_plan(self, payload: dict, blob: bytes, epoch: int) -> dict:
        from blaze_tpu.ops.base import ExecContext
        from blaze_tpu.plan import plan_pb2 as pb
        from blaze_tpu.runtime import artifacts
        from blaze_tpu.runtime.executor import run_pool_plan

        node = pb.PlanNode()
        node.ParseFromString(blob)
        # the fence stamp: this attempt's artifacts land on epoch-named
        # files, so even a zombie's completed write can't collide with a
        # retried attempt's output
        data_path = artifacts.stamp_epoch(node.shuffle_writer.data_file,
                                          epoch)
        index_path = artifacts.stamp_epoch(node.shuffle_writer.index_file,
                                           epoch)
        node.shuffle_writer.data_file = data_path
        node.shuffle_writer.index_file = index_path
        client = self.shuffle_client()
        rids = list(payload.get("rids") or [])

        def make_provider(rid):
            # exactly one positional param: _call_provider passes the
            # task partition to 1-arg providers (a default-arg closure
            # would be miscounted as 2-arg and handed num_partitions)
            def provider(partition):
                return iter(ss.split_frames(client.fetch(rid, partition)))
            return provider

        for rid in rids:
            self._acquire_rid(rid, make_provider(rid))
        try:
            ctx = ExecContext(partition=int(payload.get("partition", 0)),
                              num_partitions=int(
                                  payload.get("num_partitions", 1)))
            # the in-process resilience ladder runs INSIDE the worker:
            # transient faults retry here before costing the driver a
            # cross-process re-queue (runtime/executor.run_pool_plan)
            op = run_pool_plan(node, ctx,
                               what=payload.get("what", "pool_plan"))
            logical = int(op.metrics.values.get("shuffle_logical_bytes",
                                                0))
            return {"data_path": data_path, "index_path": index_path,
                    "logical_bytes": logical}
        finally:
            for rid in rids:
                self._release_rid(rid)

    def _run_flaky(self, payload: dict) -> dict:
        """Test handler: fail the first `times` attempts (counted in a
        driver-provided file so the count survives this process dying),
        then succeed."""
        from blaze_tpu.runtime import faults

        marker = payload["marker"]
        n = 0
        try:
            with open(marker, "r") as f:
                n = int(f.read().strip() or 0)
        except (OSError, ValueError):
            n = 0
        if n < int(payload.get("times", 1)):
            with open(marker, "w") as f:
                f.write(str(n + 1))
            cls = faults.CATEGORY_CLASSES.get(
                payload.get("category", "retryable"), faults.FatalError)
            raise cls(f"flaky task (attempt {n + 1})")
        return {"attempts_failed": n}

    def _run_task(self, msg: dict, blob: bytes) -> None:
        from blaze_tpu.runtime import monitor, trace

        key, epoch = msg.get("task", ""), int(msg.get("epoch", 0))
        kind = msg.get("kind", "")
        payload = msg.get("payload") or {}
        # replay the driver-issued correlation ids: every worker-side
        # record (the task_attempt span, nested events, counter
        # attribution) then carries the same query/stage/task ids the
        # driver's records do — the federation join key
        ids = {k: payload.get(k) for k in trace.ID_KEYS
               if payload.get(k) is not None}
        if ids.get("query_id"):
            monitor.ensure_query(ids["query_id"])
        try:
            with trace.context(**ids):
                with trace.span("task_attempt",
                                attempt_id=f"{key}#e{epoch}",
                                pool_kind=kind,
                                what=payload.get("what", key)):
                    if kind == "plan":
                        result = self._run_plan(payload, blob, epoch)
                    elif kind == "echo":
                        result = {"value": payload.get("value")}
                    elif kind == "sleep":
                        end = (time.monotonic()
                               + float(payload.get("ms", 0)) / 1e3)
                        while (time.monotonic() < end
                               and not self.stop.is_set()):
                            time.sleep(0.01)
                        result = {}
                    elif kind == "flaky":
                        result = self._run_flaky(payload)
                    else:
                        raise ValueError(f"unknown task kind: {kind}")
        except BaseException as e:  # noqa: BLE001 — classified + relayed
            from blaze_tpu.runtime import faults

            self._flush_telemetry()
            try:
                self._send({"type": "result", "task": key, "epoch": epoch,
                            "ok": False, "category": faults.classify(e),
                            "error": type(e).__name__,
                            "message": str(e)[:500]})
            except (ConnectionError, OSError):
                pass
            return
        # flush BEFORE the result: same socket, in-order processing, so
        # the driver has this task's spans/counters federated before the
        # stage span that reads them closes
        self._flush_telemetry()
        reply = {"type": "result", "task": key, "epoch": epoch,
                 "ok": True}
        reply.update(result)
        try:
            self._send(reply)
        except (ConnectionError, OSError):
            pass

    # -- main loop -----------------------------------------------------

    def run(self) -> int:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(self.ctl_path)
        self.sock = sock
        ss.send_msg(sock, {"type": "hello", "token": self.token,
                           "pid": os.getpid(),
                           # clock echo: the driver estimates this
                           # worker's monotonic offset from it
                           "mono_ns": time.monotonic_ns()},
                    lock=self.send_lock)
        beat = threading.Thread(target=self._beat_loop, name="blz-wk-beat",
                                daemon=True)
        beat.start()
        ship = threading.Thread(target=self._ship_loop, name="blz-wk-ship",
                                daemon=True)
        ship.start()
        try:
            while not self.stop.is_set():
                try:
                    msg, blob = ss.recv_msg(sock)
                except (ConnectionError, OSError):
                    break  # driver gone
                mtype = msg.get("type")
                if mtype == "task":
                    threading.Thread(target=self._run_task,
                                     args=(msg, blob),
                                     name="blz-wk-task",
                                     daemon=True).start()
                elif mtype == "ping":
                    self._send({"type": "pong"})
                elif mtype == "hang":
                    self.hang_until = (time.monotonic()
                                       + int(msg.get("ms", 0)) / 1000.0)
                elif mtype == "shutdown":
                    break
        finally:
            try:
                # last chance to ship buffered telemetry on a clean
                # shutdown (send errors are swallowed inside)
                self._flush_telemetry()
            except Exception:  # noqa: BLE001 — teardown must proceed
                pass
            self.stop.set()
            with self._client_lock:
                client, self._client = self._client, None
            if client is not None:
                client.close()
            try:
                sock.close()
            except OSError:
                pass
        return 0


def _worker_main() -> int:
    overrides = os.environ.get(_ENV_CONF, "")
    if overrides:
        for name, value in json.loads(overrides).items():
            if name in KNOBS:
                setattr(conf, name, value)
    return _Worker().run()


if __name__ == "__main__":
    if "--worker" in sys.argv:
        sys.exit(_worker_main())
    sys.exit("executor_pool is a library; run with --worker as a pool "
             "child process")
