"""Global jit-compile cache keyed on plan structure.

Ref analog: none in the reference (DataFusion interprets plans); this is the
TPU-specific cost center called out in SURVEY.md §7(f): AQE re-plans every
stage, so per-stage compiled pipelines must be cached across tasks. jax.jit
already caches per (shapes, dtypes) *per function object*; operators are
rebuilt per task, so we key the function object itself on the plan's
structural key — same plan + same shape bucket => zero recompiles.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Hashable

import jax

_lock = threading.Lock()
_cache: Dict[Hashable, Callable] = {}
_stats = {"hits": 0, "misses": 0}
# single observer slot (runtime/compile_service registers its shape
# registry here): called as observer(event, key, ns) with event in
# {"hit", "miss", "compiled"} — outside _lock, exceptions swallowed.
_observer = None


def set_observer(fn) -> None:
    global _observer
    _observer = fn


def _notify(event: str, key: Hashable, ns: int = 0) -> None:
    obs = _observer
    if obs is not None:
        try:
            obs(event, key, ns)
        except Exception:
            pass


def get_or_compile(key: Hashable, make_fn: Callable[[], Callable],
                   jit: bool = True, **jit_kwargs) -> Callable:
    """Return a jitted function for `key`, building it once.

    `jit=False` caches the bare callable instead: used for pipelines with
    host-evaluated expressions (digests/JSON/UDF) — the axon TPU backend has
    no host-callback support, so those run op-at-a-time on concrete arrays
    (hostfns.host_apply) rather than inside one compiled program."""
    with _lock:
        fn = _cache.get(key)
        if fn is not None:
            _stats["hits"] += 1
    if fn is not None:
        _notify("hit", key)
        return fn
    with _lock:
        _stats["misses"] += 1
    _notify("miss", key)
    from blaze_tpu.runtime import faults

    faults.inject("jit.compile")
    built = jax.jit(make_fn(), **jit_kwargs) if jit else make_fn()
    if jit:
        built = _with_stale_exec_retry(key, built, make_fn, jit_kwargs)
        built = _with_first_call_timer(key, built)
    with _lock:
        return _cache.setdefault(key, built)


def _with_first_call_timer(key, fn):
    """Report the first invocation's wall time as this key's compile cost.

    jax compiles lazily at the first jitted call, so the first-call wall
    clock is trace + XLA build (+ the first dispatch enqueue; the result
    is NOT blocked on — blocking here would serialize the engine's async
    dispatch pipelines, and compile time dwarfs enqueue time anyway).
    """
    import functools

    done = []

    @functools.wraps(fn)
    def timed(*args, **kwargs):
        if done:
            return fn(*args, **kwargs)
        done.append(True)
        t0 = time.perf_counter_ns()
        out = fn(*args, **kwargs)
        _notify("compiled", key, time.perf_counter_ns() - t0)
        return out

    return timed


def _with_stale_exec_retry(key, fn, make_fn, jit_kwargs):
    """Self-healing wrapper for a rare XLA dispatch inconsistency.

    Re-executing a cached jitted fn on inputs with identical pytree /
    avals / shardings can fail with `INVALID_ARGUMENT: Execution supplied
    N buffers but compiled program expected M buffers` (observed on the
    forced-multi-device CPU backend with struct-backed columns; the
    executable's captured-constant accounting goes stale). A fresh trace
    of the same program always succeeds, so on that specific error we
    evict, rebuild once, and re-dispatch — correctness is unaffected and
    steady-state cost is zero."""
    import functools

    with _lock:
        holder = _retry.setdefault(key, [fn])

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return holder[0](*args, **kwargs)
        # raised as ValueError on some paths and as XlaRuntimeError (a
        # RuntimeError subclass) on others — match by message
        except (ValueError, RuntimeError) as e:
            if "buffers but compiled program expected" not in str(e):
                raise
            with _lock:
                _stats["stale_exec_rebuilds"] = \
                    _stats.get("stale_exec_rebuilds", 0) + 1
                holder[0] = jax.jit(make_fn(), **jit_kwargs)
            return holder[0](*args, **kwargs)

    return wrapped


_retry: Dict[Hashable, list] = {}


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


def clear() -> None:
    with _lock:
        _cache.clear()
        _retry.clear()
        _stats.update(hits=0, misses=0)
