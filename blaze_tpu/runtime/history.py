"""Query history store: persistent plan-fingerprinted statistics.

The run ledger (runtime/trace.py) is append-only and unqueried — the
engine forgets every observed statistic the moment a query ends, which
is exactly the feedback signal the cost-based fusion optimizer (ROADMAP
item 3) and the cross-run perf tooling need. This module is the durable
layer on top:

  HistoryStore   bounded, sharded JSONL store under conf.history_dir:
                 one record per query run — per-stage wall time / copy
                 traffic / transport (keyed by the stage's plan
                 fingerprint), per-operator output row counts (keyed by
                 the operator fingerprint, with child fingerprints so
                 selectivity is derivable), dense-vs-fallback groupby
                 cardinality from the whole-stage compiler, and the
                 monitor's spill/compile roll-ups. Shards rotate at
                 conf.history_shard_runs records; retention prunes the
                 oldest shards so the store never exceeds
                 conf.history_retention_runs records.

  taps           begin_query()/observe_rows()/observe_groups() — bounded
                 in-memory accumulators fed from ops/base.count_stream
                 (per-batch row counts; the batch boundary that already
                 hosts the trace/heartbeat hooks) and
                 runtime/stage_compiler.py (dense group cardinality vs
                 streaming fallback). record_run() pops the accumulator
                 and appends the run record — called by the local
                 runner at query close, ledger or no ledger.

  StatisticsFeed observed_cardinality(fingerprint) /
                 observed_stage_cost(fingerprint): the aggregation API
                 the fusion cost model consumes — exact percentiles
                 over the retained runs (the store is bounded, so
                 loading it is O(retention)).

  detector       detect_regressions(): the latest run of each stage
                 fingerprint against its own history — flagged when
                 wall time or copy traffic exceeds the historical
                 median by conf.history_regression_pct (plus an
                 absolute noise grace, so CPU jitter on short stages
                 can't false-positive). tools/history_report.py renders
                 it; `make check-history` gates on it.

Everything is gated on `conf.history_dir`: unset, every call site pays
one truthiness check (the conf.trace_enabled posture).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from blaze_tpu.config import conf
from blaze_tpu.plan.fingerprint import (
    fingerprint_operator,
    fingerprint_query,
)
from blaze_tpu.runtime import trace

_SHARD_RE = re.compile(r"^history-(\d{6})\.jsonl$")

# bounds on the per-query accumulators: a pathological plan (or a leak)
# must not grow driver memory without limit — overflow is counted, not
# stored
_MAX_OPS_PER_QUERY = 1024
_MAX_GROUPS_PER_QUERY = 256


# ---------------------------------------------------------------------------
# sharded JSONL store
# ---------------------------------------------------------------------------


class HistoryStore:
    """Bounded sharded-JSONL store: `history-<NNNNNN>.jsonl` files under
    `directory`, appended in order. The active shard rotates at
    `shard_runs` records; after every append, whole oldest shards are
    pruned while the total exceeds `retention` — so the store holds at
    most `retention` records (give or take nothing: the active shard is
    capped at min(shard_runs, retention))."""

    def __init__(self, directory: str, retention: Optional[int] = None,
                 shard_runs: Optional[int] = None) -> None:
        self.dir = directory
        self._retention = retention
        self._shard_runs = shard_runs
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    def _ret(self) -> int:
        r = (self._retention if self._retention is not None
             else conf.history_retention_runs)
        return max(int(r), 1)

    def _shard_cap(self) -> int:
        s = (self._shard_runs if self._shard_runs is not None
             else conf.history_shard_runs)
        return max(1, min(int(s), self._ret()))

    def shards(self) -> List[str]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return [os.path.join(self.dir, n)
                for n in sorted(n for n in names if _SHARD_RE.match(n))]

    @staticmethod
    def _count_lines(path: str) -> int:
        try:
            with open(path, "rb") as f:
                return sum(1 for _ in f)
        except OSError:
            return 0

    def total_records(self) -> int:
        return sum(self._count_lines(p) for p in self.shards())

    def append(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, default=str) + "\n"
        with self._lock:
            shards = self.shards()
            if shards and self._count_lines(shards[-1]) < self._shard_cap():
                active = shards[-1]
            else:
                nxt = 1
                if shards:
                    m = _SHARD_RE.match(os.path.basename(shards[-1]))
                    nxt = int(m.group(1)) + 1
                active = os.path.join(self.dir, f"history-{nxt:06d}.jsonl")
                shards.append(active)
            with open(active, "ab+") as f:
                # heal a torn tail (crash mid-write left no newline) so
                # the new record never concatenates onto garbage
                if f.tell() > 0:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        f.write(b"\n")
                f.write(line.encode())
            # retention: drop whole oldest shards (never the active one)
            counts = {p: self._count_lines(p) for p in shards}
            total = sum(counts.values())
            while total > self._ret() and len(shards) > 1:
                oldest = shards.pop(0)
                total -= counts.pop(oldest, 0)
                try:
                    os.remove(oldest)
                except OSError:
                    pass

    def records(self) -> List[Dict[str, Any]]:
        """Every retained run record, oldest first (bounded by
        retention, so this is an O(retention) load)."""
        out: List[Dict[str, Any]] = []
        for path in self.shards():
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            try:
                                out.append(json.loads(line))
                            except ValueError:
                                continue  # torn line: skip, don't die
            except OSError:
                continue
        return out


_stores_lock = threading.Lock()
_stores: Dict[str, HistoryStore] = {}


def store(directory: Optional[str] = None) -> Optional[HistoryStore]:
    d = directory or conf.history_dir
    if not d:
        return None
    with _stores_lock:
        s = _stores.get(d)
        if s is None:
            s = _stores[d] = HistoryStore(d)
        return s


# ---------------------------------------------------------------------------
# per-query in-memory taps
# ---------------------------------------------------------------------------


class _QueryAcc:
    __slots__ = ("qid", "t0", "ops", "groups", "overflow")

    def __init__(self, qid: str) -> None:
        self.qid = qid
        self.t0 = time.time()
        # fp -> {"op", "inputs", "rows", "batches"}
        self.ops: Dict[str, Dict[str, Any]] = {}
        # list of {"fingerprint", "op", "groups", "dense"}
        self.groups: List[Dict[str, Any]] = []
        self.overflow = 0


_acc_lock = threading.Lock()
_accs: Dict[str, _QueryAcc] = {}
_active_qid: Optional[str] = None


def begin_query(qid: str) -> None:
    """Register the query's accumulator (and the active-query fallback
    for taps running outside any trace context). No-op with
    conf.history_dir unset."""
    global _active_qid
    if not conf.history_dir:
        return
    with _acc_lock:
        _accs[qid] = _QueryAcc(qid)
        _active_qid = qid


def _current_acc() -> Optional[_QueryAcc]:
    qid = trace.current_context().get("query_id")
    with _acc_lock:
        if qid is None:
            qid = _active_qid
        if qid is None:
            return None
        return _accs.get(qid)


def op_fingerprint(op) -> str:
    """Cached operator fingerprint (computed once per operator instance
    — count_stream calls this per batch)."""
    fp = getattr(op, "_history_fp", None)
    if fp is None:
        fp = fingerprint_operator(op)
        try:
            op._history_fp = fp
        except AttributeError:
            pass
    return fp


def observe_rows(op, rows: int) -> None:
    """Per-batch output-row tap (ops/base.count_stream): accumulate
    output rows per operator fingerprint. Child fingerprints ride along
    so the feed can derive selectivity (an operator's input rows are its
    children's output rows)."""
    acc = _current_acc()
    if acc is None:
        return
    fp = op_fingerprint(op)
    with _acc_lock:
        ent = acc.ops.get(fp)
        if ent is None:
            if len(acc.ops) >= _MAX_OPS_PER_QUERY:
                acc.overflow += 1
                return
            ent = acc.ops[fp] = {
                "op": op.name(),
                "inputs": [op_fingerprint(c) for c in op.children],
                "rows": 0, "batches": 0,
            }
        ent["rows"] += int(rows)
        ent["batches"] += 1


def observe_groups(fp: str, op_name: str, groups: Optional[int],
                   dense: bool) -> None:
    """Whole-stage-compiler tap: the dense one-hot groupby path knows
    its exact group cardinality in one number; the streaming fallback
    records dense=False (cardinality then comes from the row taps)."""
    acc = _current_acc()
    if acc is None:
        return
    with _acc_lock:
        if len(acc.groups) >= _MAX_GROUPS_PER_QUERY:
            acc.overflow += 1
            return
        acc.groups.append({"fingerprint": fp, "op": op_name,
                           "groups": groups, "dense": bool(dense)})


def _pop_acc(qid: str) -> Optional[_QueryAcc]:
    global _active_qid
    with _acc_lock:
        acc = _accs.pop(qid, None)
        if _active_qid == qid:
            _active_qid = None
    return acc


# ---------------------------------------------------------------------------
# run ingestion
# ---------------------------------------------------------------------------


def record_run(qid: str, run_info: Optional[dict] = None,
               directory: Optional[str] = None) -> Optional[dict]:
    """Build one run record for `qid` and append it to the store. Called
    by the local runner at query close (after the monitor roll-up merged
    into run_info). With tracing on, stage detail comes from the same
    records the ledger line is built from; tracing off, the record still
    carries the query-level counters and the op/group taps."""
    st = store(directory)
    acc = _pop_acc(qid)
    if st is None:
        return None
    stages: List[Dict[str, Any]] = []
    duration_ms: Optional[float] = None
    critical_path: Optional[Dict[str, Any]] = None
    if conf.trace_enabled:
        base = trace.build_run_record(qid, run_info)
        stages = base.get("stages") or []
        duration_ms = base.get("duration_ms")
        critical_path = base.get("critical_path")
    if duration_ms is None and acc is not None:
        duration_ms = round((time.time() - acc.t0) * 1e3, 3)
    stage_fps = [s.get("fingerprint") or "" for s in stages]
    record: Dict[str, Any] = {
        # readers treat a MISSING schema_version as version 1 (records
        # written before the critical-path change)
        "schema_version": trace.SCHEMA_VERSION,
        "query_id": qid,
        "tenant_id": (run_info or {}).get("tenant_id", ""),
        "ts": round(time.time(), 3),
        "plan_fingerprint": (fingerprint_query(stage_fps)
                             if stages else None),
        "duration_ms": duration_ms,
        "stages": stages,
        "ops": ([dict(v, fingerprint=k)
                 for k, v in sorted(acc.ops.items())] if acc else []),
        "groups": (acc.groups if acc else []),
        "counters": {k: v for k, v in (run_info or {}).items()
                     if isinstance(v, (int, float))
                     and not isinstance(v, bool)},
    }
    ap = (run_info or {}).get("autopilot") or {}
    if ap:
        # like-with-like hygiene: StatisticsFeed baselines skip canary
        # runs, detect_regressions priors must share the overlay
        # generation, and the autopilot keys its settled baseline off
        # the pre-AQE query fingerprint it actuates on
        record["overlay_hash"] = ap.get("overlay_hash")
        record["canary"] = bool(ap.get("canary"))
        record["autopilot_fp"] = ap.get("fingerprint", "")
    if critical_path is not None:
        record["critical_path"] = critical_path
    if acc is not None and acc.overflow:
        record["tap_overflow"] = acc.overflow
    st.append(record)
    return record


def reset() -> None:
    """Clear accumulators + store cache (test/bench isolation). On-disk
    shards are untouched — they are the persistence under test."""
    global _active_qid
    with _acc_lock:
        _accs.clear()
        _active_qid = None
    with _stores_lock:
        _stores.clear()


# ---------------------------------------------------------------------------
# statistics feed (the fusion cost model's input)
# ---------------------------------------------------------------------------


def _percentile(sorted_vals: List[float], p: float) -> float:
    """Exact nearest-rank percentile over a sorted list."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class StatisticsFeed:
    """Aggregated observed statistics per plan fingerprint — the API the
    cost-based fusion optimizer (ROADMAP item 3) consumes. Built from a
    HistoryStore (or a pre-loaded record list); aggregation is exact
    because the store is bounded by retention."""

    def __init__(self, source=None) -> None:
        if source is None:
            source = store()
        if isinstance(source, HistoryStore):
            self._records = source.records()
        else:
            self._records = list(source or [])
        # stage fingerprint -> per-run samples
        self._stage: Dict[str, List[Dict[str, Any]]] = {}
        # op fingerprint -> per-run {"rows", "in_rows"}
        self._ops: Dict[str, List[Dict[str, Any]]] = {}
        self._groups: Dict[str, List[Dict[str, Any]]] = {}
        for rec in self._records:
            if rec.get("canary"):
                # autopilot canary runs never feed baselines — a knob
                # under trial must not shift the costs it is judged by
                continue
            op_rows = {o.get("fingerprint"): o.get("rows", 0)
                       for o in rec.get("ops") or []}
            for s in rec.get("stages") or []:
                fp = s.get("fingerprint")
                if fp:
                    self._stage.setdefault(fp, []).append(s)
            for o in rec.get("ops") or []:
                fp = o.get("fingerprint")
                if not fp:
                    continue
                inputs = o.get("inputs") or []
                in_rows = sum(op_rows.get(i, 0) for i in inputs)
                self._ops.setdefault(fp, []).append(
                    {"rows": o.get("rows", 0), "batches": o.get("batches", 0),
                     "in_rows": in_rows if inputs else None,
                     "op": o.get("op")})
            for g in rec.get("groups") or []:
                fp = g.get("fingerprint")
                if fp:
                    self._groups.setdefault(fp, []).append(g)

    @property
    def records(self) -> List[Dict[str, Any]]:
        return self._records

    def fingerprints(self) -> Dict[str, List[str]]:
        """Known fingerprints by keyspace: "stages" (fingerprint_plan
        over the executed stage subtree) vs "ops" (operator plan_key
        digests from the batch taps / whole-stage compiler). Both are
        opaque keys — consumers pass them back to observed_*()."""
        return {"stages": sorted(self._stage),
                "ops": sorted(set(self._ops) | set(self._groups)),
                "groups": sorted(self._groups)}

    def observed_cardinality(self, fingerprint: str
                             ) -> Optional[Dict[str, Any]]:
        """Observed output cardinality for an operator (or whole-stage
        group count) fingerprint: {"n", "rows_p50", "rows_mean",
        "selectivity_p50"?, "dense_ratio"?, "groups_p50"?} — None when
        the fingerprint was never observed."""
        samples = self._ops.get(fingerprint, [])
        gsamples = self._groups.get(fingerprint, [])
        if not samples and not gsamples:
            return None
        out: Dict[str, Any] = {"n": len(samples) or len(gsamples)}
        if samples:
            rows = sorted(float(s["rows"]) for s in samples)
            out["rows_p50"] = _percentile(rows, 50)
            out["rows_mean"] = round(sum(rows) / len(rows), 3)
            sel = sorted(s["rows"] / s["in_rows"] for s in samples
                         if s.get("in_rows"))
            if sel:
                out["selectivity_p50"] = round(_percentile(sel, 50), 6)
            out["op"] = samples[-1].get("op")
        if gsamples:
            dense = [g for g in gsamples if g.get("dense")]
            out["dense_ratio"] = round(len(dense) / len(gsamples), 3)
            groups = sorted(float(g["groups"]) for g in dense
                            if g.get("groups") is not None)
            if groups:
                out["groups_p50"] = _percentile(groups, 50)
            out.setdefault("op", gsamples[-1].get("op"))
        return out

    def observed_stage_cost(self, fingerprint: str
                            ) -> Optional[Dict[str, Any]]:
        """Observed cost distribution for a stage fingerprint: wall time
        and copy traffic percentiles over the retained runs."""
        samples = self._stage.get(fingerprint, [])
        if not samples:
            return None
        ms = sorted(float(s.get("ms") or 0) for s in samples)
        copied = sorted(float(s.get("copied_bytes") or 0) for s in samples)
        moved = sorted(float(s.get("moved_bytes") or 0) for s in samples)
        return {
            "n": len(samples),
            "ms_p50": _percentile(ms, 50),
            "ms_p95": _percentile(ms, 95),
            "ms_mean": round(sum(ms) / len(ms), 3),
            "copied_p50": _percentile(copied, 50),
            "moved_p50": _percentile(moved, 50),
            "kind": samples[-1].get("kind"),
            "transport": samples[-1].get("transport"),
        }


# ---------------------------------------------------------------------------
# cross-run regression detector
# ---------------------------------------------------------------------------


def detect_regressions(records: Optional[Iterable[dict]] = None,
                       pct: Optional[float] = None,
                       grace_ms: float = 100.0,
                       grace_bytes: int = 64 << 10,
                       min_prior_runs: int = 2) -> List[Dict[str, Any]]:
    """Compare each stage fingerprint's LATEST observation against its
    own history (all earlier runs): flagged when

        latest > median(prior) * (1 + pct/100) + grace

    for wall time (grace_ms absorbs CPU scheduling jitter on short
    stages) or copy traffic (grace_bytes; byte counts are deterministic,
    so the grace is small). Fingerprints with fewer than
    `min_prior_runs` prior observations are skipped — one run is not a
    distribution. Returns findings sorted worst-first."""
    if records is None:
        st = store()
        records = st.records() if st else []
    records = list(records)
    if pct is None:
        pct = conf.history_regression_pct
    # per (record index, fingerprint) aggregate — two same-shaped stages
    # in one run fold into one sample so intra-run repetition doesn't
    # masquerade as history
    series: Dict[str, List[Tuple[int, float, float, dict]]] = {}
    for idx, rec in enumerate(records):
        per_fp: Dict[str, List[dict]] = {}
        for s in rec.get("stages") or []:
            fp = s.get("fingerprint")
            if fp:
                per_fp.setdefault(fp, []).append(s)
        for fp, ss in per_fp.items():
            ms = sum(float(s.get("ms") or 0) for s in ss)
            cp = sum(float(s.get("copied_bytes") or 0) for s in ss)
            series.setdefault(fp, []).append((idx, ms, cp, ss[-1]))
    findings: List[Dict[str, Any]] = []
    factor = 1.0 + float(pct) / 100.0
    for fp, samples in series.items():
        idx, last_ms, last_cp, meta = samples[-1]
        latest_rec = records[idx]
        # like-with-like: canary runs (autopilot explorations) never
        # serve as priors, and priors must share the settled overlay
        # generation the latest run is judged against. Records without
        # the autopilot fields degrade to the legacy all-priors window
        # (canary falsy, overlay_hash None on both sides).
        if latest_rec.get("canary"):
            settled = [s for s in samples[:-1]
                       if not records[s[0]].get("canary")]
            base_hash = (records[settled[-1][0]].get("overlay_hash")
                         if settled else None)
        else:
            base_hash = latest_rec.get("overlay_hash")
        priors = [s for s in samples[:-1]
                  if not records[s[0]].get("canary")
                  and records[s[0]].get("overlay_hash") == base_hash]
        if len(priors) < min_prior_runs:
            continue
        prior_ms = sorted(s[1] for s in priors)
        prior_cp = sorted(s[2] for s in priors)
        qid = latest_rec.get("query_id")
        for metric, latest, prior, grace in (
                ("wall_ms", last_ms, prior_ms, grace_ms),
                ("copied_bytes", last_cp, prior_cp, float(grace_bytes))):
            median = _percentile(prior, 50)
            threshold = median * factor + grace
            if latest > threshold:
                findings.append({
                    "fingerprint": fp,
                    "metric": metric,
                    "latest": round(latest, 3),
                    "median": round(median, 3),
                    "p95": round(_percentile(prior, 95), 3),
                    "threshold": round(threshold, 3),
                    "ratio": round(latest / median, 2) if median else None,
                    "runs": len(priors),
                    "query_id": qid,
                    "stage_kind": meta.get("kind"),
                })
    findings.sort(key=lambda f: (f["latest"] - f["threshold"]),
                  reverse=True)
    return findings
