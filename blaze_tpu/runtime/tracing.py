"""LEGACY report helpers — the instrumentation itself lives in trace.py.

Role split (also recorded on the `profiler_dir` knob in config.py): the
engine has ONE instrumentation pathway, runtime/trace.py — structured
spans/events, exporters, EXPLAIN ANALYZE, and (since the query-doctor
change) the device-side XLA profiler capture as a "profile" span kind
(`trace.profiled_span`). This module keeps two things alive:

  profiled_scope   a thin alias of trace.profiled_span, preserved so
                   embedder code written against the old import path
                   (`from blaze_tpu.runtime.tracing import
                   profiled_scope`) keeps working — including the
                   `profiler_dir` knob semantics (no capture when unset,
                   the scope is then just an engine-trace span).

  metric_report    the textual per-operator metric tree (the analog of
                   the reference's metric push into the Spark UI,
                   blaze/src/metrics.rs:21-50).

For the ENGINE-side timeline — spans/events with query/stage/task/attempt
correlation ids, Chrome/Perfetto export, the EXPLAIN ANALYZE tree
(`trace.explain_analyze`, a superset of `metric_report`) and the per-query
run ledger — see runtime/trace.py. With conf.profiler_dir set the
"profile" span ALSO captures an XLA/TPU trace viewable in TensorBoard/
Perfetto — device kernel timelines next to the runtime's own spans; load
both in Perfetto side by side (README "Observability").
"""

from __future__ import annotations

from typing import List

# Alias, not a wrapper: the single span-kind pathway in trace.py is the
# implementation; this name survives for the legacy import path only.
from blaze_tpu.runtime.trace import profiled_span as profiled_scope  # noqa: F401

__all__ = ["profiled_scope", "metric_report"]


def metric_report(root) -> str:
    """Operator tree with its metrics, one line per op (post-run).

    Counters are read via MetricsSet.snapshot() — supervisor pool
    threads mutate the raw dicts while a report renders, and iterating
    them unlocked raises RuntimeError("dict changed size during
    iteration"). `*_ns` values render as ms, `*_bytes` as KiB/MiB
    (trace.fmt_metric). For the span-correlated superset (stage
    wall-times, throughput, resilience annotations) use
    trace.explain_analyze(root, run_info)."""
    from blaze_tpu.runtime.trace import fmt_metric

    lines: List[str] = []

    def walk(op, depth: int) -> None:
        vals = {k: v for k, v in op.metrics.snapshot().items() if v}
        shown = ", ".join(fmt_metric(k, v) for k, v in sorted(vals.items()))
        lines.append("  " * depth + f"{op.name()}: {shown}")
        for c in op.children:
            walk(c, depth + 1)

    walk(root, 0)
    from blaze_tpu.runtime import compile_service, faults

    # both summaries include their per-category breakdowns (the faults
    # one appends [plan=1 retryable=2 ...] error counts, not only totals)
    for summary in (compile_service.telemetry_summary(),
                    faults.telemetry_summary()):
        if summary:
            lines.append(summary)
    return "\n".join(lines)
