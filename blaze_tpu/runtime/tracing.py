"""DEPRECATED import shim — everything lives in runtime/trace.py.

The legacy device-profiler module was folded into the structured engine
trace: `profiled_scope` became `trace.profiled_span` (a "profile" span
that also captures a jax.profiler/TensorBoard trace when
conf.profiler_dir is set) and `metric_report` moved to
`trace.metric_report` verbatim. These aliases keep old embedder import
paths working; new code should import from blaze_tpu.runtime.trace.
"""

from __future__ import annotations

# Aliases, not wrappers: trace.py is the implementation.
from blaze_tpu.runtime.trace import metric_report  # noqa: F401
from blaze_tpu.runtime.trace import profiled_span as profiled_scope  # noqa: F401

__all__ = ["profiled_scope", "metric_report"]
