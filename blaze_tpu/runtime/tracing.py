"""LEGACY low-level profiler hooks (SURVEY §5.4) — NOT the engine tracer.

Role split (also recorded on the `profiler_dir` knob in config.py): this
module owns the *device-side* XLA profiler capture and the textual
metric-tree report; the *engine-side* structured span/event log, its
exporters and EXPLAIN ANALYZE live in runtime/trace.py. New
instrumentation belongs in trace.py; this module only changes when the
JAX profiler integration does.

The reference's profiling story is per-operator timing metrics surfaced in
the Spark UI plus DebugExecNode batch logging (debug_exec.rs); it has no
dedicated tracer. This engine additionally hooks the JAX profiler: set
`conf.profiler_dir` and every `profiled_scope` (the local runner wraps each
query; the executor can wrap stages) captures an XLA/TPU trace viewable in
TensorBoard/Perfetto — device kernel timelines, the thing a CPU engine
cannot give you.

`metric_report` renders the per-operator metric tree (MetricNode) after a
run — the textual analog of the reference's metric push into the Spark UI
(blaze/src/metrics.rs:21-50).

For the ENGINE-side timeline — spans/events with query/stage/task/attempt
correlation ids, Chrome/Perfetto export, the EXPLAIN ANALYZE tree
(`trace.explain_analyze`, a superset of `metric_report`) and the per-query
run ledger — see runtime/trace.py. The two traces are complementary: the
XLA profiler shows where the DEVICE spent time, trace.py shows why the
RUNTIME scheduled, retried or rerouted the work around it; load both in
Perfetto side by side (README "Observability").
"""

from __future__ import annotations

import contextlib
from typing import List

from blaze_tpu.config import conf


@contextlib.contextmanager
def profiled_scope(name: str = "query"):
    """JAX profiler trace when conf.profiler_dir is set; no-op otherwise."""
    if not conf.profiler_dir:
        yield
        return
    import jax

    with jax.profiler.trace(conf.profiler_dir):
        with jax.profiler.TraceAnnotation(name):
            yield


def metric_report(root) -> str:
    """Operator tree with its metrics, one line per op (post-run).

    Counters are read via MetricsSet.snapshot() — supervisor pool
    threads mutate the raw dicts while a report renders, and iterating
    them unlocked raises RuntimeError("dict changed size during
    iteration"). `*_ns` values render as ms, `*_bytes` as KiB/MiB
    (trace.fmt_metric). For the span-correlated superset (stage
    wall-times, throughput, resilience annotations) use
    trace.explain_analyze(root, run_info)."""
    from blaze_tpu.runtime.trace import fmt_metric

    lines: List[str] = []

    def walk(op, depth: int) -> None:
        vals = {k: v for k, v in op.metrics.snapshot().items() if v}
        shown = ", ".join(fmt_metric(k, v) for k, v in sorted(vals.items()))
        lines.append("  " * depth + f"{op.name()}: {shown}")
        for c in op.children:
            walk(c, depth + 1)

    walk(root, 0)
    from blaze_tpu.runtime import compile_service, faults

    # both summaries include their per-category breakdowns (the faults
    # one appends [plan=1 retryable=2 ...] error counts, not only totals)
    for summary in (compile_service.telemetry_summary(),
                    faults.telemetry_summary()):
        if summary:
            lines.append(summary)
    return "\n".join(lines)
