"""Tracing / profiling hooks (SURVEY §5.4).

The reference's profiling story is per-operator timing metrics surfaced in
the Spark UI plus DebugExecNode batch logging (debug_exec.rs); it has no
dedicated tracer. This engine additionally hooks the JAX profiler: set
`conf.profiler_dir` and every `profiled_scope` (the local runner wraps each
query; the executor can wrap stages) captures an XLA/TPU trace viewable in
TensorBoard/Perfetto — device kernel timelines, the thing a CPU engine
cannot give you.

`metric_report` renders the per-operator metric tree (MetricNode) after a
run — the textual analog of the reference's metric push into the Spark UI
(blaze/src/metrics.rs:21-50).
"""

from __future__ import annotations

import contextlib
from typing import List

from blaze_tpu.config import conf


@contextlib.contextmanager
def profiled_scope(name: str = "query"):
    """JAX profiler trace when conf.profiler_dir is set; no-op otherwise."""
    if not conf.profiler_dir:
        yield
        return
    import jax

    with jax.profiler.trace(conf.profiler_dir):
        with jax.profiler.TraceAnnotation(name):
            yield


def metric_report(root) -> str:
    """Operator tree with its metrics, one line per op (post-run)."""
    lines: List[str] = []

    def walk(op, depth: int) -> None:
        vals = {k: v for k, v in op.metrics.values.items() if v}
        shown = ", ".join(
            f"{k}={v / 1e6:.1f}ms" if k.endswith("_ns") else f"{k}={v}"
            for k, v in sorted(vals.items()))
        lines.append("  " * depth + f"{op.name()}: {shown}")
        for c in op.children:
            walk(c, depth + 1)

    walk(root, 0)
    from blaze_tpu.runtime import compile_service, faults

    for summary in (compile_service.telemetry_summary(),
                    faults.telemetry_summary()):
        if summary:
            lines.append(summary)
    return "\n".join(lines)
