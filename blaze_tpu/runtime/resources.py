"""Task-resource handoff registry.

Ref: JniBridge.resourcesMap (JniBridge.java:26,42-44) — the string-keyed map
the JVM uses to hand native tasks live objects (fs providers, shuffle IPC
iterators, FFI export iterators, broadcast consumers). Identical role: plan
nodes carry a resource id, the embedding layer registers the object before
execution, operators resolve it lazily.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict

_lock = threading.Lock()
_resources: Dict[str, Any] = {}


def put(key: str, value: Any) -> str:
    with _lock:
        _resources[key] = value
    return key


def register(value: Any, prefix: str = "res") -> str:
    return put(f"{prefix}:{uuid.uuid4().hex}", value)


def get(key: str) -> Any:
    with _lock:
        if key not in _resources:
            raise KeyError(f"resource not registered: {key}")
        return _resources[key]


def try_get(key: str) -> Any:
    with _lock:
        return _resources.get(key)


def pop(key: str) -> Any:
    with _lock:
        return _resources.pop(key, None)


def keys() -> list:
    """Snapshot of registered resource ids — leak checks walk this for
    leftover query-namespaced entries after a run finishes."""
    with _lock:
        return sorted(_resources)


def clear() -> None:
    with _lock:
        _resources.clear()
