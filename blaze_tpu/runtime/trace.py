"""Structured query tracing: correlated span/event log + exporters.

The reference Blaze's observability is per-operator counters pushed into
the Spark UI (blaze/src/metrics.rs, MetricNode.scala). After the
resilience/supervisor PRs this engine retries, degrades, speculates,
kills and reroutes tasks — a flat counter dict cannot answer "why was
this query slow" or "which attempt actually produced partition 7". This
module records every such decision as a structured record with
correlation ids, the native-side trace Flare argues Spark loses once
compilation makes its own instrumentation blind (arxiv 1703.08219):

  TraceLog    process-global, lock-protected, BOUNDED ring of records
              (conf.trace_buffer_events; overflow drops the oldest and
              counts it in `dropped`). Monotonic + wall timestamps come
              from injectable clocks so tests assert exact durations.

  spans       `with span(kind, **attrs):` records one "span" with
              begin/duration; id kwargs (query_id/stage_id/task_id/
              attempt_id) also become thread-local CONTEXT inherited by
              every record opened inside — a grep on one task_id
              reconstructs the task's whole life across threads (the
              supervisor copies the driver's context into pool/
              speculation threads).

  events      `event(kind, **attrs)` records a point: retries, ladder
              rungs, heartbeat misses, deadline kills, speculation
              launch/win/loss, breaker trips, fault injections, spills,
              compile cache traffic.

  exporters   export_chrome_trace() — Chrome/Perfetto trace-event JSON,
              one row per task, spans nested under stages; view next to
              the XLA traces conf.profiler_dir captures (tracing.py).
              explain_analyze() — EXPLAIN ANALYZE-style operator tree
              merging per-op counters with span wall-times, throughput
              and resilience annotations.
              export_run_ledger() — one JSONL summary line per query
              (ids, durations, per-stage timings, telemetry deltas,
              histogram percentiles) for trend tooling
              (tools/trace_report.py).

  histograms  named process-global `metrics.Histogram`s (log2 buckets):
              batch_rows, task_latency_us, shuffle_write_bytes —
              surfaced in the ledger and explain_analyze.

Everything is gated on `conf.trace_enabled`: disabled, span() returns a
shared no-op context manager and event() returns after one truthiness
check — the posture faults.inject established for disabled points.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional

from blaze_tpu.config import conf
from blaze_tpu.runtime.metrics import Histogram

# correlation-id keys: hoisted out of attrs onto the record top level and
# inherited by nested records through the thread-local context stack
ID_KEYS = ("query_id", "tenant_id", "stage_id", "task_id", "attempt_id")

_ctx = threading.local()
_qid_seq = itertools.count(1)


def new_query_id() -> str:
    """Process-unique query correlation id (pid-tagged so ledger lines
    from different drivers sharing a trace dir never collide)."""
    return f"q{os.getpid()}-{next(_qid_seq)}"


def _ctx_stack() -> List[Dict[str, Any]]:
    s = getattr(_ctx, "stack", None)
    if s is None:
        s = _ctx.stack = []
    return s


def current_context() -> Dict[str, Any]:
    """Merged correlation ids active on THIS thread (innermost wins).
    The supervisor snapshots this on the driver thread and replays it
    inside pool/speculative threads (trace.context(**snap))."""
    merged: Dict[str, Any] = {}
    for d in _ctx_stack():
        merged.update(d)
    return merged


# thread ident -> merged correlation ids, mirrored by context() while
# conf.profile_enabled: the sampling profiler's daemon thread cannot
# read another thread's threading.local stack, so the push/pop sites
# publish the merged ids here for it to join against
# sys._current_frames(). Empty (and never written) while profiling is
# off — the mirror costs one truthiness check per push/pop.
_live_ctx: Dict[int, Dict[str, Any]] = {}


@contextlib.contextmanager
def context(**ids):
    """Push correlation ids for records opened inside the block."""
    stack = _ctx_stack()
    stack.append({k: v for k, v in ids.items() if v is not None})
    if conf.profile_enabled:
        _live_ctx[threading.get_ident()] = current_context()
    try:
        yield
    finally:
        stack.pop()
        if conf.profile_enabled:
            ident = threading.get_ident()
            if stack:
                _live_ctx[ident] = current_context()
            else:
                _live_ctx.pop(ident, None)


class TraceLog:
    """Bounded, lock-protected span/event log.

    `clock` returns monotonic nanoseconds (ordering + durations), `wall`
    epoch nanoseconds (cross-process correlation); both injectable so
    tests pin exact timings. Capacity is re-read from
    conf.trace_buffer_events per append unless fixed at construction."""

    def __init__(self, capacity: Optional[int] = None,
                 clock: Optional[Callable[[], int]] = None,
                 wall: Optional[Callable[[], int]] = None) -> None:
        self._lock = threading.Lock()
        self._buf: deque = deque()
        self._capacity = capacity
        self.clock = clock or time.monotonic_ns
        self.wall = wall or time.time_ns
        self.dropped = 0

    def _cap(self) -> int:
        if self._capacity is not None:
            return max(int(self._capacity), 1)
        return max(int(conf.trace_buffer_events), 1)

    def append(self, rec: Dict[str, Any]) -> None:
        cap = self._cap()
        with self._lock:
            while len(self._buf) >= cap:
                self._buf.popleft()
                self.dropped += 1
            self._buf.append(rec)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Records oldest-first (copies of the list, records shared)."""
        with self._lock:
            return list(self._buf)

    def drain(self) -> List[Dict[str, Any]]:
        """Pop and return every buffered record (oldest-first). The
        executor-side telemetry shipper uses this so records buffer in
        the bounded ring between ships and leave exactly once; the
        `dropped` counter is cumulative and survives the drain."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
            return out

    def reset(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


TRACE = TraceLog()

# -- declared record-kind registries -----------------------------------------
# Every event/span kind emitted anywhere in the engine, declared up front:
# exporters and trend tooling key on these strings, so an ad-hoc kind is a
# silent contract break. tools/blazelint's registry-sync checker verifies
# every `trace.event(...)`/`trace.span(...)` literal (and the static prefix
# of dynamic names like f"compile_{event}") resolves here, and flags
# registered-but-never-emitted kinds as stale. Add the kind HERE in the
# same change that introduces the call site.

EVENT_KINDS = (
    "admission_admitted",   # service: query granted a run slot
    "admission_parked",     # service: query queued behind a full pool
    "admission_rejected",   # service: load shed (queue full / deadline)
    "artifact_commit",      # runtime/artifacts.py: first-commit-wins publish
    "artifact_corrupt",     # artifacts: read-path checksum mismatch
    "artifact_quarantined", # artifacts: corrupt file renamed .quarantine
    "autopilot_apply",      # local_runner: stored overlay applied to a
                            # fingerprinted query at admission
    "autopilot_explore",    # autopilot: canary proposed / canary win
    "autopilot_promote",    # autopilot: canary graduated to settled
    "autopilot_rollback",   # autopilot: canary reverted + quarantined
                            # (regression verdict or inconclusive)
    "batch",                # ops/base.count_stream batch boundary
    "breaker_trip",         # supervisor: per-operator circuit breaker
    "compile_compiled",     # compile_service: fresh XLA compilation
    "compile_hit",          # compile_service: persistent-cache hit
    "compile_miss",         # compile_service: persistent-cache miss
    "capacity_changed",     # service: admission capacity recomputed on
                            # executor-pool membership change
    "control_reconnect",    # executor_pool: worker resumed its control
                            # session after a transport blip (no death)
    "deadline_exceeded",    # executor: task/query budget exhausted
    "deadline_kill",        # supervisor: budget exhausted mid-attempt
    "degrade",              # executor: resilience-ladder rung taken
    "dict_decode",          # serde: dictionary string column expanded
                            # at the result-merge edge
    "dict_encode",          # serde: string column shipped as
                            # (dictionary, codes) instead of raw bytes
    "driver_failover",      # standby: warm standby fenced the dead
                            # primary's lease and took over the fleet
    "driver_recovery",      # journal: recovery scan replayed a journal
    "epoch_fenced",         # artifacts.EpochFence: stale attempt rejected
    "executor_adopted",     # executor_pool: rebound listener adopted a
                            # surviving worker via its resume handshake
    "executor_death",       # supervisor/pool: executor process declared dead
    "executor_drain",       # executor_pool: seat gracefully decommissioned
                            # (drain completed; not a death)
    "executor_spawn",       # executor_pool: worker process launched
    "executor_task_requeued",  # executor_pool: displaced/failed task re-queued
    "fault_injected",       # faults.inject: armed point fired
    "flight_capture",       # flight_recorder: incident dossier written
    "hang_detected",        # supervisor watchdog: heartbeat stale
    "hang_relaunch",        # supervisor: killed attempt relaunched
    "journal_replay",       # local_runner: committed stage reused from
                            # a recovered write-ahead journal
    "ladder_rung",          # executor: degradation ladder transition
    "lease_expired",        # executor_pool worker: driver unreachable past
                            # executor_death_ms; self-fenced (exit 17)
    "lease_fenced",         # standby: a stale primary saw a higher lease
                            # epoch on renew and stood down
    "mem_release",          # memory: reservation released by sweep
    "orphan_sweep",         # artifacts: stale attempt files removed
    "partition_suspected",  # executor_pool: control conn broken but the
                            # process looks alive — reconnect window open
    "pipeline_stats",       # pipeline: per-stream close statistics
    "profile_export",       # profiler: per-query collapsed-stack +
                            # speedscope files committed
    "profile_merge",        # profiler: executor folded-stack deltas
                            # federated into the driver table
    "progress_snapshot",    # monitor endpoints: live progress scraped
    "queue_depth",          # pipeline: sampler queue-depth reading
    "resource_leak",        # monitor: leaked reservation/stream detected
    "retry",                # executor: retryable failure retried
    "scale_down",           # autoscaler: idlest seat drained out
                            # (evidence: utilization, idle ticks)
    "scale_up",             # autoscaler: seat spawned (evidence: parked
                            # arrivals / SLO burn / utilization)
    "shuffle_conn_dropped", # shuffle_server: client connection dropped
                            # mid-request (reset/torn frame/CRC mismatch)
    "shuffle_mmap_fetch",   # shuffle_server client: partition served as
                            # zero-copy mmap views (no socket stream)
    "slo_burn",             # service: tenant SLO budget burning hot
    "speculation_launch",   # supervisor: straggler twin launched
    "speculation_loss",     # supervisor: attempt lost the commit race
    "speculation_win",      # supervisor: speculative twin won
    "spill",                # memory: spill file written
    "spill_pages_flush",    # memory: spill page pool flushed
    "stream_batch",         # streaming: micro-batch merged into the
                            # stream's aggregation state
    "stream_checkpoint",    # streaming: offsets+state+epoch made durable
                            # in one crash-atomic journal record
    "stream_resume",        # streaming: state restored from the last
                            # committed checkpoint after a crash/takeover
    "task_abandoned",       # supervisor: attempt abandoned post-kill
    "task_error",           # supervisor: classified attempt failure
    "telemetry_recovered",  # executor_pool: dead worker's sidecar-spilled
                            # ring tail ingested (records marked truncated)
    "telemetry_shipped",    # executor_pool: batched executor telemetry
                            # frame federated into the driver ring
    "tenant_over_quota",    # memory: tenant ceiling hit, self-spilling
    "whole_stage_attempt",  # stage_compiler: fused single-dispatch try
    "whole_stage_fallback", # stage_compiler: fused path bailed out
    "whole_stage_groups",   # stage_compiler: dense-agg group stats
)

SPAN_KINDS = (
    "profile",       # trace.profiled_span: device profiler capture
    "query",         # local_runner: one per query
    "stage",         # executor: shuffle-map/broadcast/result stage
    "task_attempt",  # supervisor: one per (task, attempt)
)

# run-record wire format (ledger lines + history records). Bump on
# shape changes; readers treat a MISSING field as version 1 (PR-9-era
# lines predate the stamp) and must keep loading old lines.
SCHEMA_VERSION = 2

# -- named histogram registry ------------------------------------------------

_hist_lock = threading.Lock()
_HISTS: Dict[str, Histogram] = {}


def histogram(name: str) -> Histogram:
    h = _HISTS.get(name)
    if h is None:
        with _hist_lock:
            h = _HISTS.setdefault(name, Histogram(name))
    return h


def record_value(name: str, value: int) -> None:
    """Record into a named histogram when tracing is enabled."""
    if conf.trace_enabled:
        histogram(name).record(value)


def histograms_snapshot(reset: bool = False) -> Dict[str, dict]:
    with _hist_lock:
        hists = dict(_HISTS)
        if reset:
            _HISTS.clear()
    return {k: h.snapshot() for k, h in hists.items() if h.count}


def reset_histograms() -> None:
    with _hist_lock:
        _HISTS.clear()


def reset() -> None:
    """Clear the global log + histograms (test/bench isolation)."""
    TRACE.reset()
    reset_histograms()


# -- recording ---------------------------------------------------------------


def _base_record(rtype: str, kind: str, attrs: Dict[str, Any]
                 ) -> Dict[str, Any]:
    rec: Dict[str, Any] = {"type": rtype, "kind": kind}
    rec.update(current_context())
    for k in ID_KEYS:
        if k in attrs:
            v = attrs.pop(k)
            if v is not None:
                rec[k] = v
    rec["thread"] = threading.current_thread().name
    if attrs:
        rec["attrs"] = attrs
    return rec


def event(kind: str, **attrs) -> None:
    """Record a point event (no-op unless conf.trace_enabled).

    Correlation ids come from the thread context; explicit id kwargs
    (query_id=..., task_id=...) override it — watchdog-thread callers
    pass them directly since they run outside any task context."""
    if not conf.trace_enabled:
        return
    log = TRACE
    rec = _base_record("event", kind, attrs)
    rec["ts"] = log.clock()
    rec["wall"] = log.wall()
    log.append(rec)


class _Span:
    """Live span handle: `attrs` may be mutated (or set()) before exit —
    the stage spans learn their transport only after the mesh attempt."""

    __slots__ = ("kind", "attrs", "ids", "t0", "wall0", "_cm", "error")

    def __init__(self, kind: str, ids: Dict[str, Any],
                 attrs: Dict[str, Any]) -> None:
        self.kind = kind
        self.ids = ids
        self.attrs = attrs
        self.error: Optional[str] = None
        self.t0 = 0
        self.wall0 = 0
        self._cm = None

    def set(self, **kw) -> "_Span":
        self.attrs.update(kw)
        return self


class _NullSpan:
    """Shared disabled-path span: enter/exit/set are no-ops."""

    __slots__ = ()
    attrs: Dict[str, Any] = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kw):
        return self


_NULL_SPAN = _NullSpan()


class _SpanCM:
    __slots__ = ("span",)

    def __init__(self, span: _Span) -> None:
        self.span = span

    def __enter__(self) -> _Span:
        sp = self.span
        sp.t0 = TRACE.clock()
        sp.wall0 = TRACE.wall()
        cm = context(**sp.ids)
        cm.__enter__()
        sp._cm = cm
        return sp

    def __exit__(self, etype, exc, tb) -> bool:
        sp = self.span
        log = TRACE
        dur = log.clock() - sp.t0
        sp._cm.__exit__(etype, exc, tb)
        rec = _base_record("span", sp.kind, dict(sp.attrs))
        rec.update({k: v for k, v in sp.ids.items() if v is not None})
        rec["ts"] = sp.t0
        rec["wall"] = sp.wall0
        rec["dur"] = dur
        if exc is not None:
            rec["error"] = f"{type(exc).__name__}: {exc}"[:200]
        elif sp.error:
            rec["error"] = sp.error
        log.append(rec)
        return False


def span(kind: str, **attrs):
    """Context manager recording a span (one record at exit, with begin
    timestamp + duration). Id kwargs double as context for the block:

        with span("stage", stage_id=3, stage_kind="shuffle_map") as sp:
            ...                       # children inherit stage_id=3
            sp.set(transport="mesh")  # attrs may be refined before exit
    """
    if not conf.trace_enabled:
        return _NULL_SPAN
    ids = {k: attrs.pop(k) for k in ID_KEYS if k in attrs}
    return _SpanCM(_Span(kind, ids, attrs))


@contextlib.contextmanager
def profiled_span(name: str = "query"):
    """Device-profiler capture as a trace span — the ONE instrumentation
    pathway for `conf.profiler_dir` (folds the legacy
    runtime/tracing.profiled_scope in): records a "profile" span in the
    ring, and when profiler_dir is set additionally wraps the block in a
    jax.profiler trace + TraceAnnotation so the XLA device timeline
    lands next to the engine spans. The capture honors profiler_dir even
    with tracing disabled (span() degrades to the shared no-op)."""
    with span("profile", scope=name) as sp:
        if not conf.profiler_dir:
            yield sp
            return
        import jax

        sp.set(profiler_dir=conf.profiler_dir)
        with jax.profiler.trace(conf.profiler_dir):
            with jax.profiler.TraceAnnotation(name):
                yield sp


def on_batch(op, rows: int) -> None:
    """Batch-boundary hook (ops/base.count_stream — the same place the
    heartbeat/kill check lives, so the hot path gains no new check
    points): batch-size histogram + one trace event per batch."""
    histogram("batch_rows").record(rows)
    event("batch", op=op.name(), rows=rows)


def query_records(query_id: str,
                  records: Optional[Iterable[dict]] = None) -> List[dict]:
    """Records correlated to one query (plus globals recorded with no
    query id inside its window — compile/spill events from helper
    threads keep their ids when context was present, so uncorrelated
    records are rare and excluded)."""
    recs = TRACE.snapshot() if records is None else list(records)
    return [r for r in recs if r.get("query_id") == query_id]


# -- cross-process federation (executor telemetry -> driver ring) ------------


def ingest_remote(records: Iterable[dict], *, exec_id: str,
                  pid: Optional[int] = None, offset_ns: int = 0,
                  truncated: bool = False) -> int:
    """Federate executor-side trace records into the driver's ring.

    Each record's monotonic `ts` is rebased by the executor's estimated
    clock offset (handshake echo, runtime/executor_pool.py) so merged
    exports order driver and executor spans on one timeline, and the
    record is stamped with the shipping executor ("exec", "exec_pid").
    `truncated=True` marks records recovered from a dead worker's
    sidecar spill — the span stream ended mid-flight. Returns the count
    ingested; malformed entries are skipped, never fatal."""
    if not conf.trace_enabled:
        return 0
    n = 0
    off = int(offset_ns)
    for rec in records:
        if not isinstance(rec, dict) or "kind" not in rec:
            continue
        r = dict(rec)
        try:
            r["ts"] = int(r.get("ts", 0)) + off
        except (TypeError, ValueError):
            continue
        r["exec"] = exec_id
        if pid is not None:
            r["exec_pid"] = pid
        if truncated:
            r["truncated"] = True
        TRACE.append(r)
        n += 1
    return n


def ingest_histograms(snaps: Dict[str, dict]) -> None:
    """Merge executor-shipped histogram snapshots (bucket-count deltas)
    into the driver's named histograms — task_latency_us etc. then cover
    pooled and in-process work in one distribution."""
    if not conf.trace_enabled or not snaps:
        return
    for name, s in snaps.items():
        if not isinstance(s, dict):
            continue
        tmp = Histogram(str(name))
        counts = list(s.get("counts") or ())[:Histogram.N_BUCKETS]
        counts += [0] * (Histogram.N_BUCKETS - len(counts))
        tmp.counts = [int(c) for c in counts]
        tmp.count = int(s.get("count") or 0)
        tmp.total = int(s.get("total") or 0)
        tmp.vmin = s.get("min")
        tmp.vmax = s.get("max")
        if tmp.count:
            histogram(str(name)).merge(tmp)


# -- exporter 1: Chrome/Perfetto trace-event JSON ----------------------------


def export_chrome_trace(path: str,
                        records: Optional[Iterable[dict]] = None) -> dict:
    """Write records as Chrome trace-event JSON (load in Perfetto /
    chrome://tracing, next to the XLA profiler traces from
    conf.profiler_dir).

    Row model: one process per query — plus, for federated runs, one
    process per (query, executor): executor-shipped records carry an
    "exec" stamp (ingest_remote) and render on their own pid row named
    "blaze_tpu <qid> [execN]", timestamps already rebased onto the
    driver clock so the merged timeline is one trace. Within a process,
    one row (tid) per task — spans nest by time on their row, so
    task-attempt spans sit under their stage's span on the driver row
    timeline. "X" complete events carry spans; instant events ("i")
    carry points; metadata events name the rows. Returns
    {"events": n, "path": path}."""
    recs = TRACE.snapshot() if records is None else list(records)
    pids: Dict[tuple, int] = {}
    tids: Dict[tuple, int] = {}
    events: List[dict] = []

    def pid_of(rec) -> int:
        q = str(rec.get("query_id", "-"))
        ex = rec.get("exec")
        key = (q, ex)
        if key not in pids:
            pids[key] = len(pids) + 1
            name = f"blaze_tpu {q}" if ex is None else \
                f"blaze_tpu {q} [{ex}]"
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[key], "tid": 0,
                           "args": {"name": name}})
        return pids[key]

    def tid_of(rec, pid: int) -> int:
        row = rec.get("task_id")
        label = str(row) if row is not None else "driver"
        key = (pid, label)
        if key not in tids:
            tids[key] = 1 if row is None else len(tids) + 2
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tids[key],
                           "args": {"name": label}})
        return tids[key]

    for rec in recs:
        pid = pid_of(rec)
        tid = tid_of(rec, pid)
        args = {k: rec[k] for k in ID_KEYS if k in rec}
        args.update(rec.get("attrs") or {})
        if rec.get("error"):
            args["error"] = rec["error"]
        if rec.get("exec"):
            args["exec"] = rec["exec"]
            if rec.get("exec_pid") is not None:
                args["exec_pid"] = rec["exec_pid"]
        if rec.get("truncated"):
            args["truncated"] = True
        ev = {"name": rec["kind"], "cat": rec["type"],
              "ts": rec["ts"] / 1000.0, "pid": pid, "tid": tid,
              "args": args}
        if rec["type"] == "span":
            ev["ph"] = "X"
            ev["dur"] = max(rec.get("dur", 0), 1) / 1000.0
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)

    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"dropped_events": TRACE.dropped}}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return {"events": len(events), "path": path}


# -- exporter 2: EXPLAIN ANALYZE ---------------------------------------------


def human_bytes(n: int) -> str:
    """1536 -> '1.5KiB' (the *_bytes analog of *_ns -> ms rendering)."""
    n = int(n)
    for unit, shift in (("GiB", 30), ("MiB", 20), ("KiB", 10)):
        if abs(n) >= (1 << shift):
            return f"{n / (1 << shift):.1f}{unit}"
    return f"{n}B"


def fmt_metric(k: str, v) -> str:
    if k.endswith("_ns"):
        return f"{k[:-3]}={v / 1e6:.1f}ms"
    if k.endswith("_bytes"):
        return f"{k}={human_bytes(v)}"
    return f"{k}={v}"


def metric_report(root) -> str:
    """Operator tree with its metrics, one line per op (post-run) — the
    analog of the reference's metric push into the Spark UI
    (blaze/src/metrics.rs:21-50), absorbed from the retired
    runtime/tracing.py shim.

    Counters are read via MetricsSet.snapshot() — supervisor pool
    threads mutate the raw dicts while a report renders, and iterating
    them unlocked raises RuntimeError("dict changed size during
    iteration"). `*_ns` values render as ms, `*_bytes` as KiB/MiB
    (fmt_metric). For the span-correlated superset (stage wall-times,
    throughput, resilience annotations) use explain_analyze(root,
    run_info)."""
    lines: List[str] = []

    def walk(op, depth: int) -> None:
        vals = {k: v for k, v in op.metrics.snapshot().items() if v}
        shown = ", ".join(fmt_metric(k, v)
                          for k, v in sorted(vals.items()))
        lines.append("  " * depth + f"{op.name()}: {shown}")
        for c in op.children:
            walk(c, depth + 1)

    walk(root, 0)
    from blaze_tpu.runtime import compile_service, faults

    # both summaries include their per-category breakdowns (the faults
    # one appends [plan=1 retryable=2 ...] error counts, not only totals)
    for summary in (compile_service.telemetry_summary(),
                    faults.telemetry_summary()):
        if summary:
            lines.append(summary)
    return "\n".join(lines)


_RESILIENCE_EVENT_KINDS = (
    "retry", "ladder_rung", "hang_detected", "hang_relaunch",
    "deadline_kill", "deadline_exceeded", "speculation_launch",
    "speculation_win", "speculation_loss", "breaker_trip",
    "fault_injected", "task_error", "degrade", "executor_death",
    "executor_task_requeued", "epoch_fenced",
    # partition-tolerant control plane: wire blips and their outcomes
    # (run records count them so doctor's network_flaky rule can rank)
    "control_reconnect", "partition_suspected", "shuffle_conn_dropped",
    "lease_expired", "executor_drain",
)


def _stage_annotations(stage_events: List[dict]) -> str:
    """'2 retries, rung=halve_batch, speculated: won' from one stage's
    resilience events."""
    notes: List[str] = []
    retries = sum(1 for e in stage_events if e["kind"] == "retry")
    if retries:
        notes.append(f"{retries} retr{'y' if retries == 1 else 'ies'}")
    rungs = [e.get("attrs", {}).get("action") for e in stage_events
             if e["kind"] == "ladder_rung"]
    if rungs:
        notes.append(f"rung={rungs[-1]}")
    hangs = sum(1 for e in stage_events if e["kind"] == "hang_detected")
    if hangs:
        notes.append(f"{hangs} hang kill(s)")
    if any(e["kind"] == "speculation_launch" for e in stage_events):
        won = any(e["kind"] == "speculation_win" for e in stage_events)
        notes.append("speculated: " + ("won" if won else "lost"))
    trips = [e.get("attrs", {}).get("op_kind") for e in stage_events
             if e["kind"] == "breaker_trip"]
    if trips:
        notes.append(f"breaker tripped: {','.join(map(str, trips))}")
    faults_fired = sum(1 for e in stage_events
                       if e["kind"] == "fault_injected")
    if faults_fired:
        notes.append(f"{faults_fired} fault(s) injected")
    return ", ".join(notes)


def _stage_overlap(pipeline_events: List[dict]) -> Optional[int]:
    """Producer-time-weighted overlap % across a stage's pipelined
    streams (runtime/pipeline.py "pipeline_stats" events): the share of
    pool-side production hidden behind the consumer's compute. None when
    the stage ran no pipelines (serial mode or no pipelined sources)."""
    busy = wait = 0.0
    for e in pipeline_events:
        a = e.get("attrs", {})
        busy += a.get("producer_busy_ms", 0.0)
        wait += a.get("consumer_wait_ms", 0.0)
    if busy <= 0:
        return None
    return int(round(100.0 * max(0.0, 1.0 - wait / busy)))


def explain_analyze(root, run_info: Optional[dict] = None,
                    records: Optional[Iterable[dict]] = None) -> str:
    """EXPLAIN ANALYZE-style report: the operator tree with per-operator
    counters (bytes humanized, times in ms, row throughput), then
    per-stage span wall-times with resilience annotations, histogram
    percentiles and the process telemetry summaries.

    `root` is an executed Operator tree (its MetricsSet snapshots are
    read under their locks); `records` defaults to the global TraceLog —
    pass query_records(qid) to scope a multi-query log."""
    lines: List[str] = ["== EXPLAIN ANALYZE =="]

    def walk(op, depth: int) -> None:
        vals = {k: v for k, v in op.metrics.snapshot().items() if v}
        parts = [fmt_metric(k, v) for k, v in sorted(vals.items())]
        ns = vals.get("elapsed_compute_ns", 0)
        rows = vals.get("output_rows", 0)
        if ns and rows:
            parts.append(f"throughput={rows / (ns / 1e9):,.0f} rows/s")
        lines.append("  " * depth + f"{op.name()}: " + ", ".join(parts))
        for c in op.children:
            walk(c, depth + 1)

    walk(root, 0)

    recs = TRACE.snapshot() if records is None else list(records)
    stage_spans = [r for r in recs
                   if r["type"] == "span" and r["kind"] == "stage"]
    # expected-vs-observed column: with a history store configured, each
    # stage's wall time is shown against the fingerprint's historical
    # median (runtime/history.StatisticsFeed)
    feed = None
    if conf.history_dir and stage_spans:
        try:
            from blaze_tpu.runtime.history import StatisticsFeed

            feed = StatisticsFeed()
        except Exception:  # noqa: BLE001 — reporting, never fatal
            feed = None
    if stage_spans:
        lines.append("-- stages --")
        for sp in stage_spans:
            a = sp.get("attrs", {})
            sid = sp.get("stage_id")
            head = (f"stage {sid} {a.get('stage_kind', '?')}"
                    f"[{a.get('transport', '-')}] "
                    f"{sp.get('dur', 0) / 1e6:.1f}ms tasks={a.get('tasks', 1)}")
            if feed is not None and a.get("fingerprint"):
                exp = feed.observed_stage_cost(a["fingerprint"])
                if exp:
                    head += (f" expect~{exp['ms_p50']:.1f}ms "
                             f"(n={exp['n']})")
            if a.get("bytes"):
                head += f" bytes={human_bytes(a['bytes'])}"
            mv, cp = a.get("moved_bytes", 0), a.get("copied_bytes", 0)
            if mv or cp:
                # copy ratio per stage: the zero-copy roadmap's target
                pct = round(100.0 * cp / mv) if mv else 0
                head += (f" moved {human_bytes(mv)}, copied "
                         f"{human_bytes(cp)} ({pct}%)")
            notes = _stage_annotations(
                [r for r in recs if r["type"] == "event"
                 and r.get("stage_id") == sid
                 and r["kind"] in _RESILIENCE_EVENT_KINDS])
            ov = _stage_overlap(
                [r for r in recs if r["type"] == "event"
                 and r.get("stage_id") == sid
                 and r["kind"] == "pipeline_stats"])
            if ov is not None:
                notes = (notes + ", " if notes else "") + f"overlap={ov}%"
            if sp.get("error"):
                notes = (notes + ", " if notes else "") + \
                    f"error={sp['error']}"
            lines.append("  " + head + (f"  [{notes}]" if notes else ""))
    qspans = [r for r in recs
              if r["type"] == "span" and r["kind"] == "query"]
    for q in qspans:
        lines.append(f"query {q.get('query_id')}: "
                     f"{q.get('dur', 0) / 1e6:.1f}ms")

    # doctor section: additive wall-time breakdown + ranked findings for
    # the (last) query span in scope (runtime/doctor.py — pure function
    # of the records, so the rendering is deterministic per run record)
    if conf.doctor_enabled and qspans:
        from blaze_tpu.runtime import doctor

        qid = qspans[-1].get("query_id")
        drec = build_run_record(qid, run_info, recs)
        cp = drec.get("critical_path") or {}
        if cp.get("total_ms"):
            lines.append("-- critical path --")
            lines.extend(doctor.render_critical_path(cp))
        findings = doctor.diagnose(drec, records=query_records(qid, recs),
                                   feed=feed)
        if findings:
            lines.append("-- findings --")
            lines.extend(doctor.render_findings(findings))

    hists = histograms_snapshot()
    if hists:
        lines.append("-- distributions --")
        for name in sorted(hists):
            lines.append("  " + histogram(name).summary())

    # continuous-profiler section: top self-time frames for the (last)
    # query span in scope — the "which code, not just which stage"
    # answer, fleet-merged (executor samples federate driver-ward)
    if conf.profile_enabled:
        from blaze_tpu.runtime import profiler

        hot = profiler.hot_frames(
            qspans[-1].get("query_id") if qspans else None, top=5)
        if hot:
            lines.append("-- hot frames --")
            for h in hot:
                lines.append(f"  {h['frame']:<48} {h['samples']:>6} "
                             f"samples  {h['pct']:>5.1f}%")

    from blaze_tpu.runtime import compile_service, faults

    for summary in (compile_service.telemetry_summary(),
                    faults.telemetry_summary()):
        if summary:
            lines.append(summary)
    if run_info:
        shown = ", ".join(f"{k}={v}" for k, v in sorted(run_info.items())
                          if not isinstance(v, (dict, list)))
        lines.append(f"run_info: {shown}")
    return "\n".join(lines)


# -- exporter 3: run ledger (JSONL, one line per query) ----------------------


def build_run_record(query_id: str, run_info: Optional[dict] = None,
                     records: Optional[Iterable[dict]] = None) -> dict:
    """One query's ledger line: ids, durations, per-stage timings,
    run_info counters, histogram snapshots, drop accounting."""
    recs = query_records(query_id, records)
    qspan = next((r for r in recs if r["type"] == "span"
                  and r["kind"] == "query"), None)
    stages = []
    for sp in recs:
        if sp["type"] != "span" or sp["kind"] != "stage":
            continue
        a = sp.get("attrs", {})
        stages.append({"stage_id": sp.get("stage_id"),
                       "fingerprint": a.get("fingerprint"),
                       "kind": a.get("stage_kind"),
                       "transport": a.get("transport"),
                       "ms": round(sp.get("dur", 0) / 1e6, 3),
                       "tasks": a.get("tasks", 1),
                       "bytes": a.get("bytes", 0),
                       "moved_bytes": a.get("moved_bytes", 0),
                       "copied_bytes": a.get("copied_bytes", 0)})
    event_counts: Dict[str, int] = {}
    for r in recs:
        if r["type"] == "event" and r["kind"] in _RESILIENCE_EVENT_KINDS:
            event_counts[r["kind"]] = event_counts.get(r["kind"], 0) + 1
    info = run_info or {}
    rec = {
        "schema_version": SCHEMA_VERSION,
        "query_id": query_id,
        # billing/SLO attribution: every ledger line names its tenant and
        # how admission handled the query (admitted/parked/rejected +
        # wait); the service also writes lines for queries SHED at
        # admission, which never reach a query span
        "tenant_id": info.get("tenant_id", ""),
        "admission_outcome": info.get("admission_outcome", "admitted"),
        "admission_wait_ms": info.get("admission_wait_ms", 0),
        "wall_ns": qspan.get("wall") if qspan else None,
        "duration_ms": (round(qspan.get("dur", 0) / 1e6, 3)
                        if qspan else None),
        "stages": stages,
        "events": len(recs),
        "resilience_events": event_counts,
        "counters": {k: v for k, v in (run_info or {}).items()
                     if not isinstance(v, (dict, list))},
        "histograms": {
            name: {"count": s["count"], "total": s["total"],
                   "min": s["min"], "max": s["max"],
                   "p50": histogram(name).percentile(50),
                   "p95": histogram(name).percentile(95),
                   "p99": histogram(name).percentile(99)}
            for name, s in histograms_snapshot().items()},
        "dropped_events": TRACE.dropped,
    }
    # elastic-fleet evidence (runtime/autoscaler.py): while the policy
    # loop is active, every ledger line carries the fleet posture at
    # query end so doctor's fleet_under/overprovisioned rules can rank
    # offline, from the record alone
    from blaze_tpu.runtime import autoscaler

    fleet = autoscaler.fleet_snapshot()
    if fleet:
        rec["fleet"] = fleet
    # streaming evidence (runtime/streaming.py): a micro-batch ledger
    # line carries its stream's lag posture so doctor's stream_lag rule
    # can rank offline, from the record alone
    if isinstance(info.get("stream"), dict):
        rec["stream"] = dict(info["stream"])
    # conf-overlay provenance (runtime/autopilot.py): the resolved
    # overlay, which layer set each value, and the canary posture — the
    # 3am "why did my query's conf change" answer, in the ledger line
    if isinstance(info.get("autopilot"), dict):
        rec["autopilot"] = dict(info["autopilot"])
    # sampling-profiler evidence (runtime/profiler.py): top self-time
    # frames so doctor's host_cpu_bound rule ranks offline, from the
    # record alone (diagnose() stays a pure function of its inputs)
    if conf.profile_enabled:
        from blaze_tpu.runtime import profiler

        prof = profiler.profile_summary(query_id)
        if prof:
            rec["profile"] = prof
    if conf.doctor_enabled:
        from blaze_tpu.runtime import doctor

        rec["critical_path"] = doctor.compute_critical_path(rec, recs)
    return rec


def export_run_ledger(path: str, record: dict) -> None:
    """Append one JSONL line (atomic enough for trend tooling: a single
    write() of one line; concurrent drivers interleave whole lines). A
    crash-torn tail (a prior driver died mid-write, leaving a line with
    no newline) is healed before appending, the history-store posture —
    the new record must never concatenate onto garbage."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "ab+") as f:
        if f.tell() > 0:
            f.seek(-1, os.SEEK_END)
            if f.read(1) != b"\n":
                f.write(b"\n")
        f.write((json.dumps(record, default=str) + "\n").encode())


def rotate_export_dir(export_dir: Optional[str] = None,
                      keep: Optional[int] = None) -> Dict[str, int]:
    """Bound the trace export dir: trim ledger.jsonl to its last `keep`
    lines and delete the oldest trace_<qid>.json files beyond `keep`
    (default conf.history_retention_runs). The local runner applies
    this on driver start alongside the orphan sweep — before it, the
    ledger grew one line per query forever. Returns
    {"ledger_trimmed", "traces_pruned"} (zeros when under the bound)."""
    d = export_dir or conf.trace_export_dir
    out = {"ledger_trimmed": 0, "traces_pruned": 0}
    if not d or not os.path.isdir(d):
        return out
    if keep is None:
        keep = conf.history_retention_runs
    keep = max(int(keep), 1)
    ledger = os.path.join(d, "ledger.jsonl")
    if os.path.exists(ledger):
        try:
            with open(ledger) as f:
                lines = f.readlines()
            if len(lines) > keep:
                tmp = ledger + ".tmp"
                with open(tmp, "w") as f:
                    f.writelines(lines[-keep:])
                os.replace(tmp, ledger)  # crash-atomic, like the spills
                out["ledger_trimmed"] = len(lines) - keep
        except OSError:
            pass
    try:
        traces = [os.path.join(d, n) for n in os.listdir(d)
                  if n.startswith("trace_") and n.endswith(".json")]
    except OSError:
        return out
    if len(traces) > keep:
        traces.sort(key=lambda p: (os.path.getmtime(p), p))
        for p in traces[:len(traces) - keep]:
            try:
                os.remove(p)
                out["traces_pruned"] += 1
            except OSError:
                pass
    return out


def export_query(query_id: str, run_info: Optional[dict] = None,
                 export_dir: Optional[str] = None) -> Optional[dict]:
    """Per-query auto-export (the local runner calls this at query-span
    close when conf.trace_export_dir is set): writes
    <dir>/trace_<query_id>.json and appends <dir>/ledger.jsonl."""
    d = export_dir or conf.trace_export_dir
    if not d:
        return None
    recs = query_records(query_id)
    export_chrome_trace(os.path.join(d, f"trace_{query_id}.json"), recs)
    rec = build_run_record(query_id, run_info, recs)
    export_run_ledger(os.path.join(d, "ledger.jsonl"), rec)
    return rec
