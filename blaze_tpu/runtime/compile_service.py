"""Compile service: shape canonicalization, persistent manifest, pre-warm.

The engine's cold wall-clock is dominated by first-ever-shape XLA compiles
(PROFILE_r05: 43-325s/cell cold vs <30s warm on the chip): every
(operator, key-count, dtype-mix, capacity) combination is its own jit
program, and before this module nothing pre-warmed, bounded, or even
recorded the shape population.  This subsystem owns that population
end-to-end (the step from ad-hoc `jit_cache.get_or_compile` calls to a
managed compile service; cf. Flare's compile-amortization argument and
SystemML's dedicated fusion-plan layer in PAPERS.md):

* **Canonicalization policy** — program shapes are already bucketed to
  power-of-two capacities (`batch.bucket_capacity`); above
  `conf.canonical_pow2_limit` the service collapses buckets further onto
  power-of-FOUR rungs, halving the size axis of the shape space for the
  large capacities where compiles are the most expensive.  Sort kernels,
  join build sides, agg collapse inputs and whole-stage batch *counts*
  route through it (`canonical_batch` / `canonical_batch_count`).  Rows
  between the natural bucket and the canonical rung are padding
  (masked everywhere by `row_mask`); the overhead is counted in
  `canonicalization_waste_rows`.

* **Shape registry + manifest** — every jit-cache event (hit / miss /
  compile + wall time) is recorded per cache key, together with enough
  host-side metadata to *replay* sort-kernel shapes from scratch.  The
  registry persists as JSON next to the persistent XLA cache dir,
  versioned by an engine/config fingerprint: a manifest written by one
  process warms another.

* **Pre-warm driver** — ``python -m blaze_tpu.runtime.compile_service
  --warm`` (or ``make warm``) replays (1) the manifest's recorded sort
  shapes and (2) the TPC-DS catalogue's enumerated (query, join-mode)
  cells into the persistent XLA cache ahead of traffic, with progress
  logging and a ``--budget-seconds`` cap.

* **Telemetry** — a process-global `MetricsSet` with
  compile_count / compile_ns / cache_hits / cache_misses /
  canonicalization_waste_rows / stage_attempts / stage_compiled and the
  derived whole_stage_coverage_pct, exported as an extra `MetricNode`
  child by `executor.metric_tree` and as a summary line by
  `tracing.metric_report`.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from blaze_tpu.config import conf
from blaze_tpu.runtime import jit_cache, trace
from blaze_tpu.runtime.metrics import MetricNode, MetricsSet

# --------------------------------------------------------------------------
# telemetry
# --------------------------------------------------------------------------

TELEMETRY = MetricsSet()
# MetricsSet seeds operator-centric counters; the service's set is its own
# namespace, so start clean (reset() clears under the set's lock).
TELEMETRY.reset()

_COUNTERS = (
    "compile_count", "compile_ns", "cache_hits", "cache_misses",
    "canonicalization_waste_rows", "stage_attempts", "stage_compiled",
)
for _c in _COUNTERS:
    TELEMETRY.values[_c] = 0
TELEMETRY.values["whole_stage_coverage_pct"] = 0


def telemetry_node() -> MetricNode:
    """The service metrics as a MetricNode (appended by metric_tree).

    handler stays None: embedding layers that set a handler on the *root*
    only (the common pattern) see an inert extra child; layers that walk
    the tree and install handlers everywhere get the compile counters.
    """
    return MetricNode(TELEMETRY, [])


def _coverage_update() -> None:
    # read-modify-write of two counters: hold the set's lock for the
    # whole derivation so a concurrent add() can't interleave
    with TELEMETRY._lock:
        att = TELEMETRY.values.get("stage_attempts", 0)
        if att:
            TELEMETRY.values["whole_stage_coverage_pct"] = round(
                100 * TELEMETRY.values.get("stage_compiled", 0) / att)


def note_stage_attempt() -> None:
    TELEMETRY.add("stage_attempts", 1)
    _coverage_update()


def note_stage_compiled() -> None:
    TELEMETRY.add("stage_compiled", 1)
    _coverage_update()


def telemetry_summary() -> str:
    """One-line counter summary for metric_report ('' when idle)."""
    v = TELEMETRY.snapshot()  # pool threads add() concurrently
    if not (v.get("compile_count") or v.get("cache_hits")
            or v.get("cache_misses")):
        return ""
    return ("compile_service: compiles={compile_count} "
            "compile_ms={ms:.1f} hits={cache_hits} misses={cache_misses} "
            "waste_rows={canonicalization_waste_rows} "
            "stage_coverage={whole_stage_coverage_pct}%".format(
                ms=v.get("compile_ns", 0) / 1e6,
                **{c: v.get(c, 0) for c in
                   _COUNTERS + ("whole_stage_coverage_pct",)}))


@contextlib.contextmanager
def task_scope(metrics: MetricsSet):
    """Attribute service-counter deltas inside the scope to `metrics`.

    Per-task accounting: operators (or the local runner) wrap a task body
    and receive compile_count / compile_ns / cache_hits /
    canonicalization_waste_rows deltas under the same names.
    """
    before = TELEMETRY.snapshot()
    try:
        yield metrics
    finally:
        after = TELEMETRY.snapshot()
        for k in _COUNTERS:
            d = after.get(k, 0) - before.get(k, 0)
            if d:
                metrics.add(k, d)


# --------------------------------------------------------------------------
# canonicalization policy
# --------------------------------------------------------------------------

def canonical_capacity(n: int) -> int:
    """Canonical capacity bucket for `n` rows.

    Up to conf.canonical_pow2_limit this is the plain power-of-two bucket
    (identical shapes to an unbucketed engine run, so small/test workloads
    are byte-for-byte unchanged).  Above the limit, buckets collapse onto
    power-of-four rungs anchored at the limit: 2^14, 2^16, 2^18, ... —
    each rung absorbs two pow2 buckets, halving the large end of the
    shape space where compiles are slowest.
    """
    from blaze_tpu.columnar.batch import bucket_capacity

    cap = bucket_capacity(n)
    limit = int(conf.canonical_pow2_limit)
    if not conf.enable_compile_canonicalization or cap <= limit or limit <= 0:
        return cap
    base_exp = limit.bit_length() - 1
    exp = cap.bit_length() - 1
    if (exp - base_exp) % 2:
        exp += 1
    return 1 << exp


def canonical_batch_count(n: int) -> int:
    """Canonical rung for a whole-stage batch *count* (the scan length
    axis of stage program shapes): exact up to 2, power-of-two above."""
    if not conf.enable_compile_canonicalization or n <= 2:
        return n
    r = 4
    while r < n:
        r <<= 1
    return r


def canonical_batch(batch, kind: str, raw_rows: Optional[int] = None):
    """Repad `batch` to its canonical capacity rung (no-op when already
    canonical, disabled, or the schema is nested — list element storage
    is compacted per batch and cannot be index-repadded safely).

    The repad itself is one tiny cached gather program; rows added are
    engine padding (masked by row_mask) and are charged to
    canonicalization_waste_rows.
    """
    import jax.numpy as jnp

    cap = int(batch.capacity)
    new_cap = canonical_capacity(cap)
    if new_cap == cap:
        _REGISTRY.note_canonical(kind, cap, cap, raw_rows)
        return batch
    if any(f.dtype.is_nested or f.dtype.wide_decimal for f in batch.schema):
        return batch

    def make():
        def pad(b):
            idx = jnp.minimum(jnp.arange(new_cap, dtype=jnp.int32),
                              b.capacity - 1)
            return b.take(idx, b.num_rows)
        return pad

    fn = jit_cache.get_or_compile(
        ("canon_pad", new_cap, batch.shape_key()), make)
    out = fn(batch)
    TELEMETRY.add("canonicalization_waste_rows", new_cap - cap)
    _REGISTRY.note_canonical(kind, cap, new_cap, raw_rows)
    return out


def pad_batch_list(batches: tuple, kind: str = "stage") -> tuple:
    """Pad a uniform-shape batch tuple to its canonical count rung with
    zero-row copies of batches[0] (identical shape_key; every mask path
    sees num_rows=0, so probe/accumulate/compact treat them as empty)."""
    n = len(batches)
    rung = canonical_batch_count(n)
    if rung == n:
        return batches
    pad = batches[0].with_num_rows(0)
    TELEMETRY.add("canonicalization_waste_rows",
                  (rung - n) * int(batches[0].capacity))
    _REGISTRY.note_canonical(kind + "_count", n, rung, None)
    return batches + (pad,) * (rung - n)


# --------------------------------------------------------------------------
# shape registry + manifest
# --------------------------------------------------------------------------

_REPLAYABLE_KINDS = frozenset((
    "BOOLEAN", "INT8", "INT16", "INT32", "INT64", "FLOAT32", "FLOAT64",
    "STRING", "BINARY", "DATE", "TIMESTAMP", "DECIMAL",
))

MANIFEST_VERSION = 1
_RAW_SHAPE_CAP = 4096  # bound per-kind raw-shape sets in the manifest


def fingerprint() -> str:
    """Engine/config fingerprint versioning the manifest: entries recorded
    under one engine version / platform / shape-relevant config must not
    warm a differently-shaped engine."""
    import hashlib

    import jax

    import blaze_tpu

    payload = {
        "engine": blaze_tpu.__version__,
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "x64": bool(jax.config.jax_enable_x64),
        "min_capacity": conf.min_capacity,
        "min_string_width": conf.min_string_width,
        "batch_size": conf.batch_size,
        "dense_agg_range": conf.dense_agg_range,
        "float_sum_digit_planes": conf.float_sum_digit_planes,
        "canonicalization": conf.enable_compile_canonicalization,
        "canonical_pow2_limit": conf.canonical_pow2_limit,
    }
    blob = json.dumps(payload, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def default_manifest_path() -> Optional[str]:
    """Manifest lives next to the persistent XLA cache, per platform.

    Resolution order: BLAZE_TPU_COMPILE_MANIFEST env ("off" disables),
    else `<configured platform cache dir>/compile_manifest.json`, else
    (cache not configured) the would-be default platform dir so `--warm`
    runs have a stable home even on the CPU gate.
    """
    env = os.environ.get("BLAZE_TPU_COMPILE_MANIFEST", "")
    if env == "off":
        return None
    if env:
        return env
    import blaze_tpu

    d = getattr(blaze_tpu, "_XLA_CACHE_DIR", None)
    if d is None:
        base = os.environ.get("BLAZE_TPU_XLA_CACHE", "")
        if base == "off":
            return None
        import jax

        d = os.path.join(
            base or os.path.expanduser("~/.cache/blaze_tpu_xla_dev"),
            jax.default_backend())
    return os.path.join(d, "compile_manifest.json")


class ShapeRegistry:
    """In-process record of every jit-cache key seen: kind, hit/miss
    counts, first-call compile time, source, and (for sort kernels) a
    host-reconstructible replay payload.  Thread-safe; serializes to the
    manifest JSON."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.entries: Dict[str, Dict[str, Any]] = {}
        # kind -> {"raw": set(caps), "canonical": set(caps), "raw_rows": set}
        self.canonical: Dict[str, Dict[str, set]] = {}
        self.dirty = False

    # -- jit_cache observer protocol -----------------------------------
    def observe(self, event: str, key, ns: int) -> None:
        kind = key[0] if (isinstance(key, tuple) and key
                          and isinstance(key[0], str)) else "other"
        kid = repr(key)
        with self._lock:
            e = self.entries.get(kid)
            if e is None:
                e = self.entries[kid] = {
                    "kind": kind, "source": kind, "hits": 0, "misses": 0,
                    "compile_ns": 0, "replay": None,
                }
            if event == "hit":
                e["hits"] += 1
                TELEMETRY.add("cache_hits", 1)
            elif event == "miss":
                e["misses"] += 1
                TELEMETRY.add("cache_misses", 1)
            elif event == "compiled":
                e["compile_ns"] += int(ns)
                TELEMETRY.add("compile_count", 1)
                TELEMETRY.add("compile_ns", int(ns))
            self.dirty = True
        # after the registry lock: the trace log has its own lock and
        # events inherit the calling thread's query/stage/task context
        if event == "compiled":
            trace.event("compile_compiled", op_kind=kind,
                        compile_ns=int(ns))
        elif event in ("hit", "miss"):
            trace.event(f"compile_{event}", op_kind=kind)

    # -- canonicalization accounting -----------------------------------
    def note_canonical(self, kind: str, raw_cap: int, canon_cap: int,
                       raw_rows: Optional[int]) -> None:
        with self._lock:
            c = self.canonical.setdefault(
                kind, {"raw": set(), "canonical": set(), "raw_rows": set()})
            if len(c["raw"]) < _RAW_SHAPE_CAP:
                c["raw"].add(int(raw_cap))
            c["canonical"].add(int(canon_cap))
            if raw_rows is not None and len(c["raw_rows"]) < _RAW_SHAPE_CAP:
                c["raw_rows"].add(int(raw_rows))
            self.dirty = True

    def attach_replay(self, key, payload: Dict[str, Any],
                      source: str) -> None:
        kind = key[0] if (isinstance(key, tuple) and key
                          and isinstance(key[0], str)) else "other"
        kid = repr(key)
        with self._lock:
            e = self.entries.setdefault(kid, {
                "kind": kind, "source": source,
                "hits": 0, "misses": 0, "compile_ns": 0, "replay": None,
            })
            e["source"] = source
            if e["replay"] is None:
                e["replay"] = payload
            self.dirty = True

    # -- stats ----------------------------------------------------------
    def shape_reduction(self) -> Dict[str, Dict[str, int]]:
        """Per-kind distinct raw vs canonical shape counts (the ≥4x
        acceptance metric reads raw row-count space vs canonical caps)."""
        out = {}
        with self._lock:
            for kind, c in self.canonical.items():
                out[kind] = {
                    "raw_capacities": len(c["raw"]),
                    "raw_rowcounts": len(c["raw_rows"]),
                    "canonical_capacities": len(c["canonical"]),
                }
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            per_kind: Dict[str, Dict[str, int]] = {}
            for e in self.entries.values():
                k = per_kind.setdefault(
                    e["kind"], {"programs": 0, "compile_ns": 0,
                                "hits": 0, "misses": 0})
                k["programs"] += 1
                k["compile_ns"] += e["compile_ns"]
                k["hits"] += e["hits"]
                k["misses"] += e["misses"]
        return {"programs": sum(v["programs"] for v in per_kind.values()),
                "per_kind": per_kind,
                "shape_reduction": self.shape_reduction()}

    # -- persistence -----------------------------------------------------
    def to_manifest(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "version": MANIFEST_VERSION,
                "fingerprint": fingerprint(),
                "entries": {k: dict(v) for k, v in self.entries.items()},
                "canonical": {
                    kind: {ax: sorted(vals) for ax, vals in c.items()}
                    for kind, c in self.canonical.items()},
            }

    def merge_manifest(self, doc: Dict[str, Any]) -> int:
        """Merge a loaded manifest; returns entries merged (0 on version
        or fingerprint mismatch — a differently-configured engine's
        shapes must not be replayed here)."""
        if not isinstance(doc, dict) or doc.get("version") != MANIFEST_VERSION:
            return 0
        if doc.get("fingerprint") != fingerprint():
            return 0
        n = 0
        with self._lock:
            for kid, e in (doc.get("entries") or {}).items():
                cur = self.entries.get(kid)
                if cur is None:
                    self.entries[kid] = dict(e)
                else:
                    cur["hits"] += e.get("hits", 0)
                    cur["misses"] += e.get("misses", 0)
                    cur["compile_ns"] = max(cur["compile_ns"],
                                            e.get("compile_ns", 0))
                    if cur["replay"] is None:
                        cur["replay"] = e.get("replay")
                n += 1
            for kind, c in (doc.get("canonical") or {}).items():
                mine = self.canonical.setdefault(
                    kind,
                    {"raw": set(), "canonical": set(), "raw_rows": set()})
                for ax in ("raw", "canonical", "raw_rows"):
                    mine[ax].update(c.get(ax, ()))
        return n

    def load(self, path: Optional[str] = None) -> int:
        path = path or default_manifest_path()
        if not path or not os.path.exists(path):
            return 0
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return 0
        return self.merge_manifest(doc)

    def persist(self, path: Optional[str] = None) -> Optional[str]:
        path = path or default_manifest_path()
        if not path:
            return None
        doc = self.to_manifest()
        if not doc["entries"] and not doc["canonical"]:
            return None
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp.%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            return None
        with self._lock:
            self.dirty = False
        return path


_REGISTRY = ShapeRegistry()


def registry() -> ShapeRegistry:
    return _REGISTRY


def _observer(event: str, key, ns: int) -> None:
    try:
        _REGISTRY.observe(event, key, ns)
    except Exception:
        pass  # telemetry must never break the compile hot path


jit_cache.set_observer(_observer)


# --------------------------------------------------------------------------
# sort-shape recording + replay
# --------------------------------------------------------------------------

def record_sort_shape(key, batch, specs) -> None:
    """Record a host-reconstructible payload for a sort-kernel key.

    `sorted_batch_jit` keys are deliberately plan-independent
    (specs + shape_key), so a manifest entry is enough to rebuild an
    equivalent batch from scratch in a fresh process and replay the
    compile into the persistent XLA cache.
    """
    try:
        cols = []
        for f, c in zip(batch.schema, batch.columns):
            k = f.dtype.kind.name
            if k not in _REPLAYABLE_KINDS or f.dtype.wide_decimal:
                return  # host-fallback / nested shapes are not replayable
            col = {"name": f.name, "kind": k, "nullable": bool(f.nullable),
                   "valid": c.validity is not None}
            if f.dtype.kind.name == "DECIMAL":
                col["precision"] = f.dtype.precision
                col["scale"] = f.dtype.scale
            if k in ("STRING", "BINARY"):
                col["width"] = int(c.data.width)
            cols.append(col)
        payload = {
            "type": "sort", "capacity": int(batch.capacity),
            "specs": [[int(s.col), bool(s.asc), bool(s.nulls_first)]
                      for s in specs],
            "cols": cols,
        }
        _REGISTRY.attach_replay(key, payload, "ops/sort.sorted_batch_jit")
    except Exception:
        pass


def _rebuild_sort_batch(payload: Dict[str, Any]):
    import numpy as np

    from blaze_tpu.columnar import types as T
    from blaze_tpu.columnar.batch import ColumnBatch

    cap = int(payload["capacity"])
    fields, data, validity = [], {}, {}
    for i, col in enumerate(payload["cols"]):
        kind = T.TypeKind[col["kind"]]
        if kind == T.TypeKind.DECIMAL:
            dt = T.decimal(col.get("precision", 18), col.get("scale", 0))
        else:
            dt = T.DataType(kind)
        name = col.get("name") or f"c{i}"
        fields.append(T.Field(name, dt, col.get("nullable", True)))
        if kind in (T.TypeKind.STRING, T.TypeKind.BINARY):
            w = int(col.get("width", conf.min_string_width))
            # one max-width value pins the width bucket; vary the rest so
            # the sort is not degenerate
            data[name] = ["x" * w] + ["k%04d" % (j % 97)
                                     for j in range(1, cap)]
        elif kind == T.TypeKind.BOOLEAN:
            data[name] = (np.arange(cap) % 2).astype(bool)
        else:
            data[name] = (np.arange(cap) % 251).astype(dt.np_dtype())
        if col.get("valid"):
            validity[name] = (np.arange(cap) % 5 != 0)
    schema = T.Schema(fields)
    return ColumnBatch.from_numpy(data, schema, capacity=cap,
                                  validity=validity or None)


def replay_entry(entry: Dict[str, Any]) -> bool:
    """Re-trigger the compile recorded in a manifest entry (sort kernels
    only for now).  Returns True when a replay ran."""
    payload = entry.get("replay")
    if not payload or payload.get("type") != "sort":
        return False
    from blaze_tpu.ops.sort import SortSpec, sorted_batch_jit

    batch = _rebuild_sort_batch(payload)
    specs = [SortSpec(c, a, nf) for c, a, nf in payload["specs"]]
    out = sorted_batch_jit(batch, specs)
    # touch the result so the dispatch (and with it the XLA compile into
    # the persistent cache) actually completes before the next item
    out.column(0)
    return True


# --------------------------------------------------------------------------
# pre-warm driver
# --------------------------------------------------------------------------

class _Budget:
    def __init__(self, seconds: Optional[float]) -> None:
        self.t0 = time.monotonic()
        self.seconds = seconds

    def spent(self) -> float:
        return time.monotonic() - self.t0

    def exhausted(self) -> bool:
        return self.seconds is not None and self.spent() >= self.seconds


def warm(manifest_path: Optional[str] = None,
         queries: Optional[List[str]] = None,
         rows: int = 20_000,
         modes: Tuple[str, ...] = ("bhj", "smj"),
         budget_seconds: Optional[float] = None,
         skip_catalogue: bool = False,
         num_partitions: int = 4,
         progress=print) -> Dict[str, Any]:
    """Replay manifest shapes + the TPC-DS catalogue into the caches.

    Phase 1 rebuilds every replayable manifest entry (sort kernels) and
    re-runs its compile; phase 2 executes the catalogue's enumerated
    (query, mode) cells end-to-end, populating the persistent XLA cache
    with every stage/join/agg program those plans touch.  Honors
    `budget_seconds` between items.
    """
    import tempfile

    budget = _Budget(budget_seconds)
    stats = {"replayed_shapes": 0, "skipped_shapes": 0, "cells_run": 0,
             "cells_failed": 0, "stopped_early": False, "seconds": 0.0}

    manifest_path = manifest_path or default_manifest_path()
    merged = _REGISTRY.load(manifest_path)
    progress(f"[warm] manifest: {manifest_path or '(disabled)'} "
             f"({merged} entries)")

    for kid, entry in sorted(_REGISTRY.entries.items()):
        if budget.exhausted():
            stats["stopped_early"] = True
            break
        try:
            if replay_entry(entry):
                stats["replayed_shapes"] += 1
                progress(f"[warm] shape {entry['kind']} "
                         f"cap={entry['replay']['capacity']} "
                         f"({budget.spent():.1f}s)")
            else:
                stats["skipped_shapes"] += 1
        except Exception as e:  # a stale shape must not kill the warm run
            stats["skipped_shapes"] += 1
            progress(f"[warm] shape replay failed ({e!r})")

    if not skip_catalogue and not stats["stopped_early"]:
        from blaze_tpu.spark import tpcds
        from blaze_tpu.spark.local_runner import run_plan

        with tempfile.TemporaryDirectory(prefix="blaze_warm_") as td:
            paths, frames = tpcds.generate_tables(td, rows=rows)
            for name, mode in tpcds.warm_cells(queries, modes):
                if budget.exhausted():
                    stats["stopped_early"] = True
                    break
                t0 = time.monotonic()
                try:
                    plan, _oracle = tpcds.QUERIES[name](paths, frames, mode)
                    run_plan(plan, num_partitions=num_partitions)
                    stats["cells_run"] += 1
                    progress(f"[warm] {name}/{mode} rows={rows} "
                             f"{time.monotonic() - t0:.1f}s "
                             f"(total {budget.spent():.1f}s)")
                except Exception as e:
                    stats["cells_failed"] += 1
                    progress(f"[warm] {name}/{mode} FAILED: {e!r}")

    saved = _REGISTRY.persist(manifest_path)
    stats["seconds"] = round(budget.spent(), 2)
    stats["manifest"] = saved or manifest_path
    stats["telemetry"] = TELEMETRY.snapshot()
    stats["shape_reduction"] = _REGISTRY.shape_reduction()
    progress(f"[warm] done: {stats['replayed_shapes']} shapes, "
             f"{stats['cells_run']} cells in {stats['seconds']}s"
             + (" (budget hit)" if stats["stopped_early"] else ""))
    return stats


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="blaze_tpu.runtime.compile_service",
        description="Pre-warm the persistent compile caches from the "
                    "shape manifest and the TPC-DS catalogue.")
    p.add_argument("--warm", action="store_true",
                   help="run the pre-warm driver (the only verb for now)")
    p.add_argument("--manifest", default=None,
                   help="manifest path (default: next to the XLA cache)")
    p.add_argument("--queries", default=None,
                   help="comma-separated catalogue queries (default: all)")
    p.add_argument("--rows", type=int, default=20_000,
                   help="catalogue scale in rows per table (default 20000)")
    p.add_argument("--modes", default="bhj,smj",
                   help="join modes to enumerate (default bhj,smj)")
    p.add_argument("--budget-seconds", type=float, default=None,
                   help="stop starting new items past this many seconds")
    p.add_argument("--skip-catalogue", action="store_true",
                   help="replay manifest shapes only")
    p.add_argument("--num-partitions", type=int, default=4)
    p.add_argument("--json-out", default=None,
                   help="write the warm stats JSON here")
    args = p.parse_args(argv)

    if not args.warm:
        p.error("nothing to do: pass --warm")
    queries = args.queries.split(",") if args.queries else None
    stats = warm(manifest_path=args.manifest, queries=queries,
                 rows=args.rows,
                 modes=tuple(m for m in args.modes.split(",") if m),
                 budget_seconds=args.budget_seconds,
                 skip_catalogue=args.skip_catalogue,
                 num_partitions=args.num_partitions)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(stats, f, indent=1, sort_keys=True, default=str)
    return 0


if __name__ == "__main__":  # pragma: no cover - thin shim
    import sys

    # re-import under the canonical module name so the registry/observer
    # the engine uses is the same object this CLI reads
    from blaze_tpu.runtime import compile_service as _cs

    sys.exit(_cs.main())
