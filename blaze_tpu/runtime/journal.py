"""Write-ahead query journal + driver-crash recovery.

The commit protocol (runtime/artifacts.py) makes each ARTIFACT durable;
this module makes the QUERY durable. Every query appends a crash-atomic
JSONL journal under `conf.journal_dir` — admission, the plan fingerprint,
each stage commit (artifact paths, epochs, checksums), completion — so a
driver that is SIGKILLed mid-query leaves a replayable record of exactly
which stages finished.

At the next driver start, `ensure_recovery_scan()` (called beside the
orphan sweep in the local runner, and by QueryService at startup) replays
every incomplete journal:

  * each journaled stage commit whose artifacts still VERIFY
    (artifacts.verify_pair: footer parses, every frame crc and the
    whole-file digest match, plus the journaled data_crc cross-check)
    is harvested into an in-memory resume map keyed by the stage's plan
    fingerprint — when the query is re-submitted, the runner reuses the
    committed pair instead of re-executing the map tasks
    (`journal_replay` trace event, `recovered_stages` run_info counter);
  * stages that never committed (or whose artifacts fail verification)
    are simply absent from the map and re-execute normally;
  * the interrupted attempt itself is billed failed — a terminal
    `complete{status: failed, error: driver_restart}` record settles the
    journal, a `driver_restart` flight-recorder dossier preserves the
    forensics, and a `driver_recovery` trace event marks the replay.

Journal appends use the run-ledger durability idiom: heal a crash-torn
tail (no trailing newline) before appending, then flush + fsync — and
every loader skips lines that don't parse, so a torn record can never
poison a replay. Retention prunes the oldest COMPLETE journals beyond
`conf.journal_retention`; incomplete journals are never pruned (they are
the recovery scan's input).

Everything is gated on `conf.journal_dir` truthiness — unset (the
default), each hook site pays one check. Worker processes
(runtime/executor_pool.py) run with the knob cleared: only the driver
journals, exactly once per query.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from blaze_tpu.config import conf
from blaze_tpu.runtime import artifacts, trace

_JOURNAL_RE = re.compile(r"^journal_(.+)\.jsonl$")

_lock = threading.Lock()
# stage_fp -> harvested stage_commit record (consume-once: take_resume
# pops, so two queries with the same plan can't both claim one attempt's
# artifacts)
_resume: Dict[str, Dict[str, Any]] = {}
_scanned_dirs: set = set()          # recovery scan runs once per dir
_stats = {"journals_scanned": 0, "journals_resumable": 0,
          "journals_failed": 0, "stages_recovered": 0,
          "recovered_queries": 0, "streams_adoptable": 0}
_recovered_qids: set = set()        # exactly-once recovered_queries bump
# stream_id -> journal path of a dead-writer streaming journal found by
# the recovery scan: ADOPTED (streaming.resume_stream) rather than billed
_adoptable_streams: Dict[str, str] = {}

# record kinds that mark a journal as a durable STREAM journal
# (runtime/streaming.py): its checkpoints are the resume input for an
# unbounded query, so retention and the recovery scan treat it as live
# until the stream is settled by a graceful stop
STREAM_KINDS = ("stream_open", "stream_checkpoint")


def journal_path(qid: str, directory: Optional[str] = None) -> str:
    d = directory or conf.journal_dir
    # query ids are hex tokens (trace.new_query_id) but journals can be
    # opened for arbitrary callers — keep the filename shell-safe
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", qid)
    return os.path.join(d, f"journal_{safe}.jsonl")


class QueryJournal:
    """One query's append-only journal file.

    Records (one JSON object per line, `kind` discriminated):
      admitted      query_id, tenant_id — written at admission
      plan          fingerprint, num_partitions, stages (per-stage kind
                    + base64 serialized plan proto — the log's forensic
                    record of WHAT was admitted, independent of resubmit)
      stage_commit  stage_id, fingerprint, logical_bytes, outputs
                    (map_id, data_path, index_path, epoch, data_crc)
      complete      status ("ok"|"failed"), error — the terminal record
    """

    def __init__(self, qid: str, directory: Optional[str] = None) -> None:
        self.qid = qid
        self.dir = directory or conf.journal_dir
        self.path = journal_path(qid, self.dir)
        self._lock = threading.Lock()
        os.makedirs(self.dir, exist_ok=True)

    def record(self, kind: str, **fields: Any) -> None:
        """Append one record crash-atomically: heal a torn tail, write
        the full line, flush + fsync — after this returns the record
        survives a SIGKILL."""
        rec = {"kind": kind, "query_id": self.qid, "ts": time.time()}
        rec.update(fields)
        line = (json.dumps(rec, default=str) + "\n").encode()
        with self._lock:
            with open(self.path, "ab+") as f:
                if f.tell() > 0:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        f.write(b"\n")
                f.write(line)
                f.flush()
                os.fsync(f.fileno())

    # -- typed appenders -------------------------------------------------

    def admitted(self, tenant_id: str = "") -> None:
        # the pid is the liveness tag the recovery scan keys on: an
        # incomplete journal whose driver still breathes is a RUNNING
        # query, not a crash (the orphan-sweep idiom)
        self.record("admitted", tenant_id=tenant_id, pid=os.getpid())

    def plan(self, fingerprint: str, num_partitions: int,
             stages: List[Dict[str, Any]]) -> None:
        self.record("plan", fingerprint=fingerprint,
                    num_partitions=num_partitions, stages=stages)

    def stage_commit(self, stage_id: int, fingerprint: str,
                     logical_bytes: int,
                     outputs: List[Dict[str, Any]]) -> None:
        self.record("stage_commit", stage_id=stage_id,
                    fingerprint=fingerprint, logical_bytes=logical_bytes,
                    outputs=outputs)

    def complete(self, status: str, error: str = "") -> None:
        self.record("complete", status=status, error=error)
        prune(self.dir)


def journal_for(qid: str) -> Optional["QueryJournal"]:
    """The query's journal when journaling is on, else None (the one
    truthiness check every hook site pays)."""
    if not conf.journal_dir or not qid:
        return None
    try:
        return QueryJournal(qid)
    except OSError:
        return None


def load_records(path: str) -> List[Dict[str, Any]]:
    """All parseable records of one journal; torn/garbage lines are
    skipped, never fatal (a crash can tear at most the last line)."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # crash-torn line
                if isinstance(rec, dict) and rec.get("kind"):
                    records.append(rec)
    except OSError:
        pass
    return records


def is_complete(records: List[Dict[str, Any]]) -> bool:
    return any(r.get("kind") == "complete" for r in records)


def is_stream(records: List[Dict[str, Any]]) -> bool:
    """True when the journal belongs to a streaming query
    (runtime/streaming.py writes stream_open/stream_checkpoint records)."""
    return any(r.get("kind") in STREAM_KINDS for r in records)


def _stream_settled(records: List[Dict[str, Any]]) -> bool:
    """A stream journal is settled only by a GRACEFUL stop (complete
    status ok) with no stream activity after it — re-opening a stopped
    stream appends fresh stream records and un-settles the journal. A
    complete{failed} record (e.g. billed by a pre-streaming recovery
    scan) never settles it: the checkpoints are still the only resume
    input the stream has."""
    settled = False
    for r in records:
        kind = r.get("kind")
        if kind == "complete" and r.get("status") == "ok":
            settled = True
        elif kind in STREAM_KINDS:
            settled = False
    return settled


def prune(directory: Optional[str] = None) -> int:
    """Drop the oldest COMPLETE journals beyond conf.journal_retention.
    Incomplete journals are never pruned — until the recovery scan
    settles them they are the crash-recovery input."""
    d = directory or conf.journal_dir
    if not d:
        return 0
    try:
        names = [n for n in os.listdir(d) if _JOURNAL_RE.match(n)]
    except OSError:
        return 0
    keep = max(int(conf.journal_retention), 1)
    complete: List[tuple] = []
    for name in names:
        path = os.path.join(d, name)
        records = load_records(path)
        if not is_complete(records):
            continue
        if is_stream(records) and not _stream_settled(records):
            # a long-lived stream's journal is its ONLY resume input:
            # never let retention pressure from a busy batch workload
            # drop it while the stream is live or adoptable, no matter
            # how old the file is or what billed it complete
            continue
        try:
            complete.append((os.path.getmtime(path), path))
        except OSError:
            continue
    complete.sort()
    removed = 0
    for _mtime, path in complete[:max(0, len(complete) - keep)]:
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    return removed


# ---------------------------------------------------------------------------
# driver-crash recovery scan
# ---------------------------------------------------------------------------


def recovery_stats() -> Dict[str, int]:
    """Process-lifetime recovery counters (monitor exports
    blaze_recovered_queries_total from "recovered_queries")."""
    with _lock:
        return dict(_stats)


def reset() -> None:
    """Clear in-memory recovery state (test isolation) — journal files
    are left alone."""
    with _lock:
        _resume.clear()
        _scanned_dirs.clear()
        _recovered_qids.clear()
        _adoptable_streams.clear()
        for k in _stats:
            _stats[k] = 0


def ensure_recovery_scan(force: bool = False) -> Dict[str, int]:
    """Replay incomplete journals under conf.journal_dir (once per
    process per directory; `force` rescans for tests).

    For every incomplete journal: verified stage commits are harvested
    into the resume map (reused when the query is re-submitted), the
    interrupted attempt is billed failed with a terminal journal record,
    and a `driver_restart` flight-recorder dossier preserves the
    forensics. Never raises — recovery must not block a healthy start."""
    summary = {"scanned": 0, "resumable": 0, "billed_failed": 0,
               "stages_recovered": 0, "streams_adoptable": 0}
    d = conf.journal_dir
    if not d or not conf.recovery_enabled:
        return summary
    with _lock:
        if d in _scanned_dirs and not force:
            return summary
        _scanned_dirs.add(d)
    try:
        names = sorted(n for n in os.listdir(d) if _JOURNAL_RE.match(n))
    except OSError:
        return summary
    for name in names:
        path = os.path.join(d, name)
        records = load_records(path)
        if not records or is_complete(records):
            continue
        if _writer_alive(records):
            continue  # a LIVE driver's in-flight query, not a crash
        if is_stream(records):
            # a dead-writer STREAM journal is not billed failed — its
            # checkpoints are the resume input. Register it for adoption
            # (standby takeover / streaming.resume_stream) instead.
            qid = records[0].get("query_id", "")
            if qid and not _stream_settled(records):
                summary["streams_adoptable"] += 1
                with _lock:
                    _adoptable_streams[qid] = path
            continue
        try:
            summary["scanned"] += 1
            _replay_one(path, records, summary)
        except Exception:  # noqa: BLE001 — recovery must never block start
            summary["billed_failed"] += 1
    with _lock:
        _stats["journals_scanned"] += summary["scanned"]
        _stats["journals_resumable"] += summary["resumable"]
        _stats["journals_failed"] += summary["billed_failed"]
        _stats["stages_recovered"] += summary["stages_recovered"]
        _stats["streams_adoptable"] += summary["streams_adoptable"]
    prune(d)
    return summary


def _writer_alive(records: List[Dict[str, Any]]) -> bool:
    """True when the journal's admitted record names a pid that is still
    running (this process included). No admitted record (the crash tore
    the very first line) means no liveness claim — replay it. The LAST
    admitted pid wins: a resumed stream re-stamps its adopter's pid onto
    the same journal, and liveness must track the current writer."""
    pid = next((r.get("pid") for r in reversed(records)
                if r.get("kind") == "admitted" and r.get("pid")), None)
    if not pid:
        return False
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError, ValueError):
        return True  # can't prove it dead: never bill a live query
    return True


def _replay_one(path: str, records: List[Dict[str, Any]],
                summary: Dict[str, int]) -> None:
    qid = records[0].get("query_id", "")
    tenant = next((r.get("tenant_id", "") for r in records
                   if r.get("kind") == "admitted"), "")
    plan_fp = next((r.get("fingerprint", "") for r in records
                    if r.get("kind") == "plan"), "")
    recovered = 0
    discarded = 0
    for rec in records:
        if rec.get("kind") != "stage_commit":
            continue
        fp = rec.get("fingerprint") or ""
        outputs = rec.get("outputs") or []
        if fp and outputs and all(_output_verifies(o) for o in outputs):
            with _lock:
                _resume[fp] = rec
            recovered += 1
        else:
            discarded += 1
    trace.event("driver_recovery", query_id=qid,
                stages_recovered=recovered, stages_discarded=discarded,
                fingerprint=plan_fp)
    if recovered:
        summary["resumable"] += 1
        summary["stages_recovered"] += recovered
    # bill the interrupted attempt failed: the terminal record settles
    # the journal (making it prunable) whether or not anything was
    # salvageable — a RESUMED run writes its own journal under a new qid
    summary["billed_failed"] += 1
    try:
        jnl = QueryJournal(qid or os.path.basename(path),
                           os.path.dirname(path))
        jnl.path = path  # bill the file we scanned, not a re-derived name
        jnl.record("complete", status="failed", error="driver_restart",
                   stages_recovered=recovered, stages_discarded=discarded)
    except OSError:
        pass
    _flight_dossier(qid, tenant, recovered, discarded, plan_fp)


def _output_verifies(out: Dict[str, Any]) -> bool:
    data = out.get("data_path", "")
    index = out.get("index_path", "")
    if not data or not index:
        return False
    if not artifacts.verify_pair(data, index):
        return False
    want_crc = out.get("data_crc")
    if want_crc is None:
        return True
    try:
        _offsets, meta = artifacts.read_index(index)
    except Exception:  # noqa: BLE001 — any read failure means unverifiable
        return False
    return meta is None or int(meta["data_crc"]) == int(want_crc)


def _flight_dossier(qid: str, tenant: str, recovered: int,
                    discarded: int, plan_fp: str) -> None:
    from blaze_tpu.runtime import flight_recorder

    if not flight_recorder.enabled("driver_restart"):
        return
    flight_recorder.capture(
        "driver_restart", qid or "unknown", tenant_id=tenant or None,
        error="driver restarted with this query in flight",
        detail={"stages_recovered": recovered,
                "stages_discarded": discarded,
                "plan_fingerprint": plan_fp})


def adoptable_streams() -> Dict[str, str]:
    """{stream_id: journal path} of dead-writer streaming journals the
    recovery scan registered for adoption (consume via
    streaming.resume_stream, which re-stamps the journal's writer pid)."""
    with _lock:
        return dict(_adoptable_streams)


def claim_adoptable_stream(stream_id: str) -> Optional[str]:
    """Pop one adoptable stream registration (consume-once, so two
    adopters can't both resume the same checkpoint chain)."""
    with _lock:
        return _adoptable_streams.pop(stream_id, None)


# -- resume map ---------------------------------------------------------


def take_resume(stage_fp: str) -> Optional[Dict[str, Any]]:
    """Pop the harvested stage_commit record for a stage fingerprint
    (consume-once); None when nothing was recovered for it."""
    if not stage_fp:
        return None
    with _lock:
        return _resume.pop(stage_fp, None)


def resumable_stages() -> int:
    with _lock:
        return len(_resume)


def note_query_recovered(qid: str) -> None:
    """Count a query that reused >= 1 journaled stage (exactly once per
    qid) — the blaze_recovered_queries_total gauge."""
    with _lock:
        if qid in _recovered_qids:
            return
        _recovered_qids.add(qid)
        _stats["recovered_queries"] += 1


def recovered_queries_total() -> int:
    with _lock:
        return _stats["recovered_queries"]
