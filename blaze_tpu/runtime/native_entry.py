"""Python side of the native callNative contract.

Ref: the reference's callNative decodes a TaskDefinition, builds the plan
and streams Arrow batches back over FFI (blaze/src/exec.rs:86-131,
rt.rs:38-205). Here the C++ layer (native/src/task_runtime.cpp) calls
`run_task_serialized(bytes) -> bytes`: decode the TaskDefinition, execute
the plan on this process's jax engine, and return the concatenated BTB1
result frames (the embedding layer streams them back to the JVM).
"""

from __future__ import annotations

import struct

from blaze_tpu.columnar import serde
from blaze_tpu.runtime.executor import execute_plan
from blaze_tpu.ops.base import ExecContext


def init(mem_budget_bytes: bytes) -> None:
    """bn_init hook: set the engine memory budget (little-endian i64)."""
    from blaze_tpu.runtime import memory

    (budget,) = struct.unpack("<q", mem_budget_bytes)
    if budget > 0:
        memory.init(budget)


def run_task_serialized(task_def: bytes) -> bytes:
    from blaze_tpu.plan import decode_task_definition

    plan, td = decode_task_definition(task_def)
    ctx = ExecContext(partition=td.partition_id)
    out = bytearray()
    for batch in execute_plan(plan, ctx):
        out += serde.serialize_batch(batch)
    return bytes(out)
