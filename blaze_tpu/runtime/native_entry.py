"""Python side of the native callNative contract.

Ref: the reference's callNative decodes a TaskDefinition, builds the plan
and streams Arrow batches back over FFI (blaze/src/exec.rs:86-131,
rt.rs:38-205). Here the C++ layer (native/src/task_runtime.cpp) calls
`run_task_serialized(bytes) -> bytes`: decode the TaskDefinition, execute
the plan on this process's jax engine, and return the concatenated BTB1
result frames (the embedding layer streams them back to the JVM).
"""

from __future__ import annotations

import struct
import threading

from blaze_tpu.columnar import serde
from blaze_tpu.config import conf
from blaze_tpu.runtime import faults
from blaze_tpu.runtime.executor import execute_plan
from blaze_tpu.ops.base import ExecContext

# Host-requested kill flag (bn_request_kill / bn_clear_kill /
# bn_kill_requested). The host embedding has no reference to a running
# task's ExecContext, so the flag is process-global here: every native
# task entry wires `is_running` to it and execution notices at the next
# batch boundary — the JniBridge.isTaskRunning contract, over the C ABI.
_task_killed = threading.Event()


def error_category_code(exc: BaseException) -> int:
    """faults category -> NATIVE_CATEGORY_CODES wire code for `exc`
    (what bn_last_error_category reports after a failed bn_call)."""
    return faults.NATIVE_CATEGORY_CODES.get(faults.classify(exc), 4)


def exception_for_code(code: int, msg: str = "") -> Exception:
    """Inverse mapping: rebuild a taxonomy exception from a wire code
    (hosts that only see the int reconstruct the Python-side class)."""
    cat = faults.NATIVE_CODE_CATEGORIES.get(code, "fatal")
    if cat == "killed":
        from blaze_tpu.ops.base import TaskKilledError

        return TaskKilledError(msg or "task killed")
    cls = faults.CATEGORY_CLASSES.get(cat, faults.FatalError)
    return cls(msg or f"native error category {cat}")


def init(mem_budget_bytes: bytes) -> None:
    """bn_init hook: set the engine memory budget (little-endian i64)."""
    from blaze_tpu.runtime import memory

    (budget,) = struct.unpack("<q", mem_budget_bytes)
    if budget > 0:
        memory.init(budget)


def spill(bytes_needed_le: bytes) -> bytes:
    """bn_spill hook: the HOST (the JVM's memory manager in deployment)
    asks the engine to release memory — operator state spills to disk
    and the freed byte count returns (little-endian i64). Ref:
    OnHeapSpillManager.scala:61-144, where Spark-tracked spill pages
    drop to disk under heap pressure."""
    from blaze_tpu.runtime import memory

    (needed,) = struct.unpack("<q", bytes_needed_le)
    freed = memory.get_manager().release(max(int(needed), 0))
    return struct.pack("<q", freed)


def request_kill(_payload: bytes = b"") -> bytes:
    """bn_request_kill hook: cooperatively cancel the running native
    task(s); checked at every batch boundary."""
    _task_killed.set()
    return b""


def clear_kill(_payload: bytes = b"") -> bytes:
    """bn_clear_kill hook: re-arm after a kill (next task may run)."""
    _task_killed.clear()
    return b""


def kill_requested() -> bool:
    return _task_killed.is_set()


def kill_state(_payload: bytes = b"") -> bytes:
    """bn_kill_requested hook: the flag as one byte (b"\\x01"/b"\\x00")."""
    return b"\x01" if _task_killed.is_set() else b"\x00"


def _native_ctx(partition_id: int) -> ExecContext:
    return ExecContext(partition=partition_id,
                       is_running=lambda: not _task_killed.is_set())


def run_task_serialized(task_def: bytes) -> bytes:
    from blaze_tpu.plan import decode_task_definition

    try:
        plan, td = decode_task_definition(task_def)
        ctx = _native_ctx(td.partition_id)
        out = bytearray()
        for batch in execute_plan(plan, ctx):
            out += serde.serialize_batch(batch)
        if conf.monitor_enabled:
            from blaze_tpu.runtime import monitor

            # result payload crossing the C ABI — the frames inside it
            # were already counted as serde copies when built
            monitor.count_move("ffi", len(out))
        return bytes(out)
    except Exception as e:  # noqa: BLE001 — classified for the C ABI
        # the faults taxonomy must cross the boundary labelled: the C++
        # layer reads `category` off the exception instance to fill
        # bn_last_error_category for the host scheduler
        raise faults.ensure_classified(e) from e


# Arrow C-stream payload type codes (consumed by native/src/arrow_stream.cpp)
_ARROW_CODES = {}


def _arrow_code(dtype):
    from blaze_tpu.columnar.types import TypeKind as K

    if dtype.wide_decimal:
        return 13
    return {
        K.BOOLEAN: 1, K.INT8: 2, K.INT16: 3, K.INT32: 4, K.INT64: 5,
        K.FLOAT32: 6, K.FLOAT64: 7, K.STRING: 8, K.BINARY: 9,
        K.DATE: 10, K.TIMESTAMP: 11, K.DECIMAL: 12,
    }.get(dtype.kind)


def arrow_payload_header(schema) -> bytes:
    """BTAS header: field names + type codes so the C++ stream can build
    the ArrowSchema without parsing the plan protobuf."""
    out = bytearray(b"BTAS")
    out += struct.pack("<H", len(schema.fields))
    for f in schema.fields:
        name = f.name.encode()
        code = _arrow_code(f.dtype)
        if code is None:
            raise ValueError(
                f"arrow stream does not support {f.dtype.kind} columns")
        out += struct.pack("<H", len(name)) + name
        out += struct.pack("<BBii", code, 1 if f.nullable else 0,
                           f.dtype.precision, f.dtype.scale)
    return bytes(out)


def run_task_arrow_payload(task_def: bytes) -> bytes:
    """bn_call_arrow hook: BTAS schema header + the BTB1 result frames.

    The C++ side (native/src/arrow_stream.cpp) turns this payload into a
    standard Arrow C stream (ArrowArrayStream) that ANY Arrow host can
    import zero-copy — the deployment contract of the reference
    (blaze/src/rt.rs:76-80 hands the JVM an FFI_ArrowArrayStream consumed
    by ArrowFFIStreamImportIterator.scala:63-75)."""
    from blaze_tpu.plan import decode_task_definition

    try:
        plan, td = decode_task_definition(task_def)
        ctx = _native_ctx(td.partition_id)
        out = bytearray(arrow_payload_header(plan.schema))
        for batch in execute_plan(plan, ctx):
            out += serde.serialize_batch(batch)
        if conf.monitor_enabled:
            from blaze_tpu.runtime import monitor

            monitor.count_move("ffi", len(out))
        return bytes(out)
    except Exception as e:  # noqa: BLE001 — classified for the C ABI
        raise faults.ensure_classified(e) from e
