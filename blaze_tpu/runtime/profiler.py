"""Always-on wall-clock sampling profiler with fleet-wide attribution.

The observability ladder (trace spans -> monitor counters -> doctor ->
flight dossiers) says *which stage* was slow but never *which code*.
This module closes that gap with a sampling profiler cheap enough to
leave on in production:

  * A single daemon thread wakes every ``conf.profile_sample_ms``,
    snapshots ``sys._current_frames()`` and folds each thread's stack
    (root->leaf, ``module.function`` frames, depth-bounded by
    ``conf.profile_max_frames``) into a bounded aggregated table — the
    flattened form of a folded-stack trie keyed by
    ``(query_id, tenant_id, stage_id, task_id, exec, stack)``.
  * Attribution rides the existing thread-local trace context: a
    ``threading.local`` stack is invisible to other threads, so
    ``trace.context()`` mirrors the merged correlation ids into
    ``trace._live_ctx`` (thread ident -> ids) while profiling is on,
    and the sampler joins that map against the frame snapshot. The
    pipeline pumps, the supervisor's pool threads and the executor-pool
    workers all already replay the driver's context, so their samples
    attribute for free.
  * Pooled executor processes run the same sampler; their workers drain
    folded-stack deltas (``drain_remote`` — counts move, accumulators
    stay, the monitor-counter federation model) onto the existing BCS
    telemetry frames, which are sidecar-spilled before every ship.  The
    driver merges them back (``merge_remote``) stamped with the
    executor id, so one table covers the whole fleet and a SIGKILLed
    worker's last batch still lands via sidecar recovery.

Everything is gated on ONE ``conf.profile_enabled`` truthiness check
(the blazelint hot-path-gating posture): disabled means no sampler
thread, no context mirroring, and every integration hook returns after
a single attribute read.

Exports: ``collapsed()`` (flamegraph.pl collapsed-stack text),
``speedscope()`` (speedscope.app JSON), per-query files via
``export_query`` into ``conf.profile_export_dir`` (render/convert with
``tools/blaze_prof.py``), a hot-frames block in ``explain_analyze``,
``window()`` embeds for hang/deadline flight dossiers, and
``profile_summary()`` attached to run records as evidence for the
doctor's ``host_cpu_bound`` finding.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from blaze_tpu.config import conf

# table key: (query_id, tenant_id, stage_id, task_id, exec, stack).
# exec is "" for samples taken in this process and the executor token
# for federated rows (stamped driver-side at merge).
_Key = Tuple[str, str, str, str, str, str]

_lock = threading.Lock()
_table: Dict[_Key, int] = {}
_qmeta: Dict[str, List[float]] = {}  # qid -> [first_wall, last_wall, n]
_samples = 0            # thread-samples folded locally (accumulator)
_remote_samples = 0     # samples merged from executor telemetry frames
_recovered_samples = 0  # subset of remote that arrived via sidecar recovery
_dropped = 0            # samples folded into nothing: table at capacity
_duty_cost_s = 0.0      # seconds spent inside sampling passes + drains
_duty_wall_s = 0.0      # wall seconds the sampler loop has been alive
_remote_duty_cost_s = 0.0  # federated: sum of executor duty deltas
_remote_duty_wall_s = 0.0

_thread: Optional[threading.Thread] = None
_stop = threading.Event()
_start_lock = threading.Lock()

# capacity bounds — the table is an aggregate (one entry per distinct
# folded stack per attribution), so these are generous: a steady-state
# engine run folds into a few hundred entries
_MAX_ENTRIES = 8192
_MAX_QUERIES = 64          # per-query window metadata (FIFO eviction)
_EMPTY: Dict[str, Any] = {}


# -- sampling ---------------------------------------------------------------

# fold caches: the sampler runs at up to ~100Hz over every thread in
# the process, so per-frame string work (basename/splitext/format) must
# never repeat. Code objects are interned per function for the life of
# the process; an idle thread's whole stack hashes to the same code
# tuple every tick, so the common case is one dict hit per thread.
_fold_lock = threading.Lock()       # guards the two fold caches only
_name_cache: Dict[Any, str] = {}    # code object -> "mod.func"
_fold_cache: Dict[Any, str] = {}    # (code, code, ...) -> folded stack
_FOLD_CACHE_MAX = 32768


def _fold(frame, max_frames: int) -> str:
    """One thread's stack as ``mod.func;mod.func;...`` root->leaf."""
    codes = []
    f = frame
    while f is not None and len(codes) < max_frames:
        codes.append(f.f_code)
        f = f.f_back
    key = tuple(codes)
    with _fold_lock:
        cached = _fold_cache.get(key)
    if cached is not None:
        return cached
    parts: List[str] = []
    for co in codes:
        with _fold_lock:
            name = _name_cache.get(co)
        if name is None:
            mod = os.path.splitext(os.path.basename(co.co_filename))[0]
            name = f"{mod}.{co.co_name}"
            with _fold_lock:
                _name_cache[co] = name
        parts.append(name)
    parts.reverse()
    out = ";".join(parts)
    with _fold_lock:
        if len(_fold_cache) < _FOLD_CACHE_MAX:
            _fold_cache[key] = out
    return out


def _bump_locked(key: _Key, n: int, now: float) -> None:
    global _dropped
    if key in _table:
        _table[key] += n
    elif len(_table) < _MAX_ENTRIES:
        _table[key] = n
    else:
        _dropped += n
        return
    qid = key[0]
    if qid:
        meta = _qmeta.get(qid)
        if meta is None:
            if len(_qmeta) >= _MAX_QUERIES:
                _qmeta.pop(next(iter(_qmeta)))
            _qmeta[qid] = [now, now, n]
        else:
            meta[1] = now
            meta[2] += n


def sample_once(frames: Optional[Dict[int, Any]] = None) -> int:
    """One sampling pass: fold every live thread's stack into the
    table, attributed through ``trace._live_ctx``. Returns the number
    of thread-samples folded. ``frames`` is injectable for tests."""
    global _samples
    from blaze_tpu.runtime import trace

    me = threading.get_ident()
    with _start_lock:
        t = _thread
    sampler = t.ident if t is not None else None
    if frames is None:
        frames = sys._current_frames()
    now = time.time()
    max_frames = max(int(conf.profile_max_frames), 1)
    live = trace._live_ctx
    # prune idents whose thread died while holding a context (the pop
    # side of trace.context() only runs while profiling is on, so a
    # mid-flight toggle can strand an entry)
    for ident in list(live):
        if ident not in frames:
            live.pop(ident, None)
    folded: List[_Key] = []
    for ident, frame in frames.items():
        if ident == me or ident == sampler:
            continue  # never profile the profiler
        stack = _fold(frame, max_frames)
        if not stack:
            continue
        ids = live.get(ident) or _EMPTY
        # str() via None-check, not truthiness: stage 0 is a real stage
        folded.append(tuple(
            "" if v is None else str(v)
            for v in (ids.get("query_id"), ids.get("tenant_id"),
                      ids.get("stage_id"), ids.get("task_id")))
            + ("", stack))
    with _lock:
        for key in folded:
            _bump_locked(key, 1, now)
        _samples += len(folded)
    return len(folded)


def _loop(stop_evt: threading.Event) -> None:
    global _duty_cost_s, _duty_wall_s
    last = time.perf_counter()
    while not stop_evt.is_set():
        cost = 0.0
        if conf.profile_enabled:
            t0 = time.perf_counter()
            try:
                sample_once()
            except Exception:  # noqa: BLE001 — the sampler must never die
                pass
            cost = time.perf_counter() - t0
        # overhead governor: the interval knob is a floor, not a
        # promise — a pass over an unusually wide/deep thread set
        # stretches the next sleep so sampling itself stays around a
        # 1% duty cycle (the always-on contract) no matter the process
        stop_evt.wait(max(max(int(conf.profile_sample_ms), 1) / 1000.0,
                          cost * 100.0))
        now = time.perf_counter()
        with _lock:
            # duty ledger: cost/wall is the profiler's own overhead
            # figure, the one number the <2% always-on contract is
            # gated on (wall-clock A/B on a busy host can't resolve
            # 2%). Booked per full cycle — a pass and the sleep that
            # amortizes it land together, so the ratio is meaningful
            # from the first observable update
            _duty_cost_s += cost
            _duty_wall_s += now - last
        last = now


def ensure_started() -> Optional[threading.Thread]:
    """Start the sampler daemon (idempotent). The one gate: disabled
    profiling returns after a single truthiness check."""
    global _thread
    if not conf.profile_enabled:
        return None
    with _start_lock:
        if _thread is None or not _thread.is_alive():
            _stop.clear()
            _thread = threading.Thread(
                target=_loop, args=(_stop,), name="blaze-profiler",
                daemon=True)
            _thread.start()
        return _thread


def running() -> bool:
    with _start_lock:
        t = _thread
    return t is not None and t.is_alive()


def stop() -> None:
    """Stop the sampler thread (tests / clean teardown)."""
    global _thread
    with _start_lock:
        t = _thread
        _thread = None
        if t is None:
            return
        _stop.set()
    t.join(timeout=2.0)


def reset() -> None:
    """Clear the table and counters (tests / chaos rounds)."""
    global _samples, _remote_samples, _recovered_samples, _dropped
    global _duty_cost_s, _duty_wall_s
    global _remote_duty_cost_s, _remote_duty_wall_s
    with _lock:
        _table.clear()
        _qmeta.clear()
        _samples = 0
        _remote_samples = 0
        _recovered_samples = 0
        _dropped = 0
        _duty_cost_s = 0.0
        _duty_wall_s = 0.0
        _remote_duty_cost_s = 0.0
        _remote_duty_wall_s = 0.0
    with _fold_lock:
        _fold_cache.clear()
        _name_cache.clear()


# -- federation (the monitor-counter delta model) ---------------------------

def drain_remote() -> List[list]:
    """Executor side: pop the folded-stack table as delta rows
    ``[qid, tenant, stage, task, stack, count]`` for the telemetry
    frame. Counts move, accumulators stay — a row handed out here is
    either shipped (possibly recovered from the sidecar spill) or lost
    with the frame, exactly like remote monitor counters."""
    global _duty_cost_s
    t0 = time.perf_counter()
    with _lock:
        rows = [[k[0], k[1], k[2], k[3], k[5], n]
                for k, n in _table.items()]
        _table.clear()
        _qmeta.clear()
        _duty_cost_s += time.perf_counter() - t0
    return rows


def merge_remote(rows: Sequence[Sequence], exec_id: str = "",
                 recovered: bool = False) -> int:
    """Driver side: fold executor delta rows into the fleet table,
    stamped with the executor id. ``recovered`` marks rows replayed
    from a dead worker's sidecar spill."""
    global _remote_samples, _recovered_samples
    if not rows:
        return 0
    from blaze_tpu.runtime import trace

    now = time.time()
    total = 0
    ex = str(exec_id or "")
    with _lock:
        for r in rows:
            try:
                qid, tenant, stage, task, stack = (
                    str(r[0]), str(r[1]), str(r[2]), str(r[3]), str(r[4]))
                n = int(r[5])
            except Exception:  # noqa: BLE001 — a torn row never poisons
                continue       # the rest of the frame
            if n <= 0 or not stack:
                continue
            _bump_locked((qid, tenant, stage, task, ex, stack), n, now)
            total += n
        _remote_samples += total
        if recovered:
            _recovered_samples += total
    trace.event("profile_merge", exec=ex, rows=len(rows),
                samples=total, recovered=bool(recovered))
    return total


def duty_snapshot() -> Tuple[float, float]:
    """Executor ship path: cumulative (cost_s, wall_s) of this
    process's sampler. The worker ships watermarked deltas so the
    driver can sum them without double counting."""
    with _lock:
        return _duty_cost_s, _duty_wall_s


def merge_duty(d: Any) -> None:
    """Driver side: fold one executor's duty delta into the fleet
    ledger. Torn payloads are dropped, never raised."""
    global _remote_duty_cost_s, _remote_duty_wall_s
    try:
        cost = float(d.get("cost_s", 0.0))
        wall = float(d.get("wall_s", 0.0))
    except Exception:  # noqa: BLE001 — a torn frame never poisons ingest
        return
    if cost <= 0.0 and wall <= 0.0:
        return
    with _lock:
        _remote_duty_cost_s += max(cost, 0.0)
        _remote_duty_wall_s += max(wall, 0.0)


def stats() -> Dict[str, Any]:
    """Cheap counter snapshot for the monitor gauges / blaze_top."""
    with _lock:
        duty = (100.0 * _duty_cost_s / _duty_wall_s
                if _duty_wall_s > 0 else 0.0)
        fleet_cost = _duty_cost_s + _remote_duty_cost_s
        fleet_wall = _duty_wall_s + _remote_duty_wall_s
        fleet = 100.0 * fleet_cost / fleet_wall if fleet_wall > 0 else 0.0
        return {"samples": _samples,
                "remote_samples": _remote_samples,
                "recovered_samples": _recovered_samples,
                "dropped": _dropped,
                "stacks": len(_table),
                "duty_pct": round(duty, 3),
                "duty_cost_s": round(_duty_cost_s, 6),
                "duty_wall_s": round(_duty_wall_s, 3),
                "fleet_duty_pct": round(fleet, 3),
                "running": running()}


# -- views ------------------------------------------------------------------

def rows(query_id: Optional[str] = None) -> List[list]:
    """Table snapshot as ``[qid, tenant, stage, task, exec, stack,
    count]`` rows, optionally filtered to one query."""
    with _lock:
        items = sorted(_table.items())
    out = []
    for (qid, tenant, stage, task, ex, stack), n in items:
        if query_id is not None and qid != query_id:
            continue
        out.append([qid, tenant, stage, task, ex, stack, n])
    return out


def collapsed(query_id: Optional[str] = None) -> List[str]:
    """flamegraph.pl-compatible collapsed-stack lines. Attribution is
    encoded as synthetic root frames (``query:<id>;stage:<id>;...``) so
    a flamegraph groups by query then stage then executor."""
    lines = []
    for qid, tenant, stage, task, ex, stack, n in rows(query_id):
        prefix = [f"query:{qid or '-'}"]
        if stage:
            prefix.append(f"stage:{stage}")
        if ex:
            prefix.append(f"exec:{ex}")
        lines.append(";".join(prefix + [stack]) + f" {n}")
    return lines


def stacks_to_speedscope(pairs: Sequence[Tuple[str, int]],
                         name: str = "blaze profile") -> Dict[str, Any]:
    """Pure converter: ``(folded_stack, count)`` pairs -> a speedscope
    'sampled' profile document (also used by tools/blaze_prof.py)."""
    frame_ix: Dict[str, int] = {}
    frames: List[Dict[str, str]] = []
    samples: List[List[int]] = []
    weights: List[int] = []
    total = 0
    for stack, n in pairs:
        ixs = []
        for f in stack.split(";"):
            ix = frame_ix.get(f)
            if ix is None:
                ix = frame_ix[f] = len(frames)
                frames.append({"name": f})
            ixs.append(ix)
        samples.append(ixs)
        weights.append(int(n))
        total += int(n)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "name": name,
        "exporter": "blaze_prof",
        "shared": {"frames": frames},
        "profiles": [{"type": "sampled", "name": name, "unit": "none",
                      "startValue": 0, "endValue": total,
                      "samples": samples, "weights": weights}],
    }


def speedscope(query_id: Optional[str] = None) -> Dict[str, Any]:
    pairs = []
    for qid, tenant, stage, task, ex, stack, n in rows(query_id):
        prefix = [f"query:{qid or '-'}"]
        if stage:
            prefix.append(f"stage:{stage}")
        if ex:
            prefix.append(f"exec:{ex}")
        pairs.append((";".join(prefix + [stack]), n))
    name = f"blaze profile {query_id}" if query_id else "blaze profile"
    return stacks_to_speedscope(pairs, name=name)


def hot_frames(query_id: Optional[str] = None,
               top: int = 8) -> List[Dict[str, Any]]:
    """Leaf self-time ranking: the frame actually on-stack-top when the
    sample fired, aggregated across attributions. The doctor's
    host_cpu_bound evidence and explain_analyze's hot-frames block."""
    agg: Dict[str, int] = {}
    total = 0
    for _qid, _tenant, _stage, _task, _ex, stack, n in rows(query_id):
        leaf = stack.rsplit(";", 1)[-1]
        agg[leaf] = agg.get(leaf, 0) + n
        total += n
    if not total:
        return []
    ranked = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))[:top]
    return [{"frame": f, "samples": n,
             "pct": round(100.0 * n / total, 1)} for f, n in ranked]


def window(query_id: str,
           max_stacks: int = 64) -> Optional[Dict[str, Any]]:
    """The profiled window around an incident, for flight dossiers: the
    query's aggregated folded stacks plus sampling metadata — the
    continuous upgrade of the dossier's single-instant thread_stacks."""
    qrows = rows(query_id)
    if not qrows:
        return None
    with _lock:
        meta = list(_qmeta.get(query_id) or ())
    qrows.sort(key=lambda r: (-r[6], r[5]))
    stacks = [{"stage_id": r[2], "task_id": r[3], "exec": r[4],
               "stack": r[5], "samples": r[6]} for r in qrows[:max_stacks]]
    return {"query_id": query_id,
            "samples": sum(r[6] for r in qrows),
            "first_wall": meta[0] if meta else None,
            "last_wall": meta[1] if meta else None,
            "sample_ms": int(conf.profile_sample_ms),
            "stacks": stacks,
            "hot_frames": hot_frames(query_id, top=5)}


def profile_summary(query_id: str) -> Optional[Dict[str, Any]]:
    """Compact per-query evidence attached to run records (feeds the
    doctor's host_cpu_bound rule through the pure diagnose() path)."""
    hot = hot_frames(query_id, top=5)
    if not hot:
        return None
    with _lock:
        meta = list(_qmeta.get(query_id) or ())
    return {"samples": int(meta[2]) if meta else
            sum(h["samples"] for h in hot),
            "sample_ms": int(conf.profile_sample_ms),
            "hot_frames": hot}


# -- export -----------------------------------------------------------------

def export_query(query_id: str) -> Optional[Dict[str, str]]:
    """Write the query's profile as collapsed-stack text plus
    speedscope JSON into ``conf.profile_export_dir`` (crash-atomic,
    first-commit-wins like every other artifact)."""
    out_dir = conf.profile_export_dir
    if not out_dir:
        return None
    lines = collapsed(query_id)
    if not lines:
        return None
    from blaze_tpu.runtime import artifacts, trace

    os.makedirs(out_dir, exist_ok=True)
    text = "\n".join(lines) + "\n"
    folded_path = os.path.join(out_dir, f"profile_{query_id}.collapsed")
    scope_path = os.path.join(out_dir,
                              f"profile_{query_id}.speedscope.json")
    doc = json.dumps(speedscope(query_id))

    def _write(payload):
        def fn(tmp):
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
        return fn

    artifacts.commit_file(_write(text), folded_path, fsync=False)
    artifacts.commit_file(_write(doc), scope_path, fsync=False)
    trace.event("profile_export", query_id=query_id, stacks=len(lines))
    return {"collapsed": folded_path, "speedscope": scope_path}
