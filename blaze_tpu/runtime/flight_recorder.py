"""Black-box incident capture: one self-contained dossier per incident.

The observability ladder (trace ring -> monitor -> history -> doctor) is
aggregate and postmortem: when a query fails, is shed, blows its
deadline, breaches its tenant SLO, trips a breaker, or leaks resources,
the evidence evaporates with the bounded rings unless an operator was
exporting at that exact moment. This module is the flight recorder: at
the moment an incident fires, it snapshots everything the rings know
about the query and commits it crash-atomically (artifacts.commit_file:
temp + fsync + os.replace) as one JSON *dossier* under conf.flight_dir —
one file answers "what happened to query X at 3am".

  triggers   failure / shed / deadline / hang / slo_breach /
             breaker_trip / resource_leak / executor_death /
             driver_restart / driver_failover — each (query, trigger)
             pair captures at most ONCE (a retry storm must not write
             a dossier per retry; a standby takeover writes exactly one
             driver_failover dossier, keyed on its lease epoch).
             conf.flight_triggers ("all" or a comma list) selects
             which classes arm.

  contents   schema-versioned: the query's trace-ring slice, the
             monitor ring's gauge samples over the query's lifetime,
             the doctor's additive critical-path breakdown + ranked
             findings, the resolved knob overlay, per-stage
             StatisticsFeed expectations (and which stages violated
             them), all thread stacks (sys._current_frames) for
             hang/deadline triggers, an executor-pool snapshot
             (pool_stats) when a pool is live, and the run-ledger
             line. Pooled queries need no special casing: the
             trace-ring slice already contains the federated
             executor-side spans (trace.ingest_remote appends them to
             the driver ring), and executor_death dossiers embed the
             worker's recovered sidecar ring slice under
             detail["executor_trace"] (stamped by executor_pool's
             death path).

  retention  the newest conf.flight_retention dossiers are kept; older
             ones are pruned after each capture.

Everything is gated on `conf.flight_dir` truthiness — unset (the
default), every hook is one check. Capture itself must never mask the
incident it is recording: any internal failure is swallowed into
`last_error()` and the original exception keeps propagating.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

from typing import Any, Dict, List, Optional

from blaze_tpu.config import KNOBS, conf
from blaze_tpu.runtime import artifacts, monitor, trace

# dossier wire format; bump on shape changes. Readers (blaze_inspect)
# treat unknown versions as opaque but still render the common fields.
SCHEMA_VERSION = 1

TRIGGERS = ("failure", "shed", "deadline", "hang", "slo_breach",
            "breaker_trip", "resource_leak", "executor_death",
            "driver_restart", "driver_failover", "stream_stall",
            "autopilot_rollback")

_lock = threading.Lock()
_captured: set = set()            # (query_id, trigger): exactly-once
_stacks: Dict[str, dict] = {}     # qid -> stacks recorded at kill time
# qid -> (final run_info, t0): stashed at query end so POST-run captures
# (the service's slo_breach scoring fires after run_plan returns) still
# build a ledger with the full monitor counter roll-up
_run_infos: Dict[str, tuple] = {}
_counts: Dict[str, int] = {}      # trigger -> dossiers written
_last_error: Optional[str] = None
# dedupe-set bound: far above any real incident rate; clearing risks a
# duplicate dossier only after 4096 *distinct* incidents in one process
_CAPTURED_MAX = 4096
_STACKS_MAX = 32
_RUN_INFOS_MAX = 64


def enabled(trigger: str) -> bool:
    """One-truthiness-check gate all hook sites share."""
    if not conf.flight_dir:
        return False
    spec = (conf.flight_triggers or "all").strip()
    if spec in ("all", "*", ""):
        return True
    return trigger in {t.strip() for t in spec.split(",")}


def counts() -> Dict[str, int]:
    """Dossiers written per trigger (feeds blaze_flight_dossiers_total)."""
    with _lock:
        return dict(_counts)


def last_error() -> Optional[str]:
    """The most recent swallowed capture failure (debugging aid)."""
    with _lock:
        return _last_error


def reset() -> None:
    """Clear in-memory state (test isolation) — files are left alone."""
    global _last_error
    with _lock:
        _captured.clear()
        _stacks.clear()
        _run_infos.clear()
        _counts.clear()
        _last_error = None


# -- thread stacks -----------------------------------------------------------


def thread_stacks() -> List[Dict[str, Any]]:
    """Every live thread's stack via sys._current_frames(), names from
    threading.enumerate() — the "where was everyone" page of the dossier
    for hang/deadline incidents."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append({
            "thread_id": ident,
            "name": names.get(ident, "?"),
            "frames": [ln.rstrip("\n")
                       for ln in traceback.format_stack(frame)],
        })
    return out


def record_stacks(query_id: Optional[str], reason: str) -> None:
    """Stash stacks at the MOMENT of a watchdog kill (supervisor._scan):
    by the time the DeadlineError/HungError propagates out of run_plan
    the hung frames are gone, so the watchdog captures them live and the
    dossier written later prefers this stash over a fresh capture."""
    if not query_id or not conf.flight_dir:
        return
    rec = {"reason": reason, "wall": time.time(), "stacks": thread_stacks()}
    with _lock:
        if len(_stacks) >= _STACKS_MAX:
            _stacks.pop(next(iter(_stacks)))
        _stacks[query_id] = rec


# -- capture -----------------------------------------------------------------


def _knob_overlay() -> Dict[str, Any]:
    """The resolved knob set, JSON-safe (non-scalar values repr'd)."""
    out: Dict[str, Any] = {}
    for name in sorted(KNOBS):
        try:
            v = getattr(conf, name)
        except Exception:  # noqa: BLE001 — capture must never fail
            continue
        if isinstance(v, (bool, int, float, str, type(None))):
            out[name] = v
        else:
            out[name] = repr(v)
    return out


def _expectations(ledger: dict, feed) -> List[Dict[str, Any]]:
    """Per-stage fingerprint vs StatisticsFeed history: what the stage
    cost, what history predicted (p50/p95), and whether it violated the
    p95 expectation — the "was this run anomalous" page."""
    out: List[Dict[str, Any]] = []
    if feed is None:
        return out
    for st in ledger.get("stages", ()):
        fp = st.get("fingerprint")
        if not fp:
            continue
        exp = feed.observed_stage_cost(fp)
        if not exp:
            continue
        ms = st.get("ms") or 0.0
        out.append({
            "stage_id": st.get("stage_id"),
            "fingerprint": fp,
            "ms": ms,
            "expected_ms_p50": exp.get("ms_p50"),
            "expected_ms_p95": exp.get("ms_p95"),
            "n": exp.get("n"),
            "violated": bool(exp.get("ms_p95") is not None
                             and ms > exp["ms_p95"]),
        })
    return out


def capture(trigger: str, query_id: Optional[str], *,
            tenant_id: Optional[str] = None,
            error: Optional[BaseException] = None,
            run_info: Optional[dict] = None,
            detail: Optional[dict] = None,
            include_stacks: bool = False,
            started_at: Optional[float] = None) -> Optional[str]:
    """Write one dossier for `trigger` on `query_id`; returns the path,
    or None when disabled / already captured / capture failed. Never
    raises — this runs inside failure paths."""
    global _last_error
    if not query_id or not enabled(trigger):
        return None
    with _lock:
        key = (query_id, trigger)
        if key in _captured:
            return None
        if len(_captured) >= _CAPTURED_MAX:
            _captured.clear()
        _captured.add(key)
    try:
        return _capture_locked_out(trigger, query_id, tenant_id, error,
                                   run_info, detail, include_stacks,
                                   started_at)
    except Exception as e:  # noqa: BLE001 — must not mask the incident
        with _lock:
            _last_error = f"{type(e).__name__}: {e}"
        return None


def _capture_locked_out(trigger, query_id, tenant_id, error, run_info,
                        detail, include_stacks, started_at) -> str:
    now = time.time()
    recs = trace.query_records(query_id)
    # a capture firing after run_plan returned (the service's SLO
    # scoring) has neither run_info nor the monitor acct — fall back to
    # the roll-up on_query_end stashed
    with _lock:
        stashed_info = _run_infos.get(query_id)
    if run_info is None and stashed_info is not None:
        run_info = stashed_info[0]
    # monitor ring slice over the query's lifetime: prefer the live
    # accumulator's t0 (query still registered), else the caller's
    t0 = started_at
    if t0 is None:
        t0 = monitor.query_t0(query_id)
    if t0 is None and stashed_info is not None:
        t0 = stashed_info[1]
    samples = monitor.ring_slice(t0)

    info = dict(run_info or {})
    if tenant_id and "tenant_id" not in info:
        info["tenant_id"] = tenant_id
    ledger = trace.build_run_record(query_id, info, recs)

    from blaze_tpu.runtime import doctor

    critical_path = ledger.get("critical_path")
    if critical_path is None:
        critical_path = doctor.compute_critical_path(ledger, recs)
    feed = None
    if conf.history_dir:
        try:
            from blaze_tpu.runtime.history import StatisticsFeed

            feed = StatisticsFeed()
        except Exception:  # noqa: BLE001 — history is optional context
            feed = None
    findings = [f.to_dict() for f in
                doctor.diagnose(ledger, records=recs, feed=feed,
                                critical_path=critical_path)]

    with _lock:
        stashed = _stacks.get(query_id)
    stacks_doc = stashed
    if stacks_doc is None and include_stacks:
        stacks_doc = {"reason": trigger, "wall": now,
                      "stacks": thread_stacks()}

    doc: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "captured_at": now,
        "trigger": trigger,
        "query_id": query_id,
        "tenant_id": tenant_id or info.get("tenant_id") or "",
        "error": ({"type": type(error).__name__,
                   "message": str(error)[:2000]}
                  if error is not None else None),
        "detail": detail,
        "knobs": _knob_overlay(),
        # conf-overlay provenance (runtime/autopilot.py): the resolved
        # overlay + which layer (tenant/fingerprint/pin) set each value
        # and the canary posture — "why did my query's conf change"
        "autopilot": (dict(info["autopilot"])
                      if isinstance(info.get("autopilot"), dict)
                      else None),
        "trace_events": recs,
        "trace_dropped": trace.TRACE.dropped,
        "monitor_samples": samples,
        "critical_path": critical_path,
        "findings": findings,
        "expectations": _expectations(ledger, feed),
        "thread_stacks": stacks_doc,
        "ledger": ledger,
    }
    # continuous-profiler upgrade (runtime/profiler.py): the aggregated
    # window the sampler collected around the incident — what the code
    # was doing leading up to the hang/deadline, fleet-merged, instead
    # of only the single thread_stacks instant above. Exactly-once per
    # (query, trigger) rides the existing _captured dedup.
    if conf.profile_enabled:
        from blaze_tpu.runtime import profiler

        doc["profile_window"] = profiler.window(query_id)
    else:
        doc["profile_window"] = None
    try:
        from blaze_tpu.runtime import executor_pool

        doc["executor_pool"] = executor_pool.pool_stats()
    except Exception:  # noqa: BLE001 — pool snapshot is optional context
        doc["executor_pool"] = None

    os.makedirs(conf.flight_dir, exist_ok=True)
    qid_safe = "".join(ch if ch.isalnum() or ch in "-_" else "_"
                       for ch in query_id)
    name = f"dossier_{int(now * 1000):013d}_{trigger}_{qid_safe}.json"
    path = os.path.join(conf.flight_dir, name)
    payload = json.dumps(doc, indent=1, default=str)

    def _write(tmp: str) -> None:
        with open(tmp, "w") as f:
            f.write(payload)

    artifacts.commit_file(_write, path)
    _prune()
    with _lock:
        _counts[trigger] = _counts.get(trigger, 0) + 1
    trace.event("flight_capture", query_id=query_id, trigger=trigger,
                dossier=name)
    return path


def _prune() -> None:
    """Bounded retention: keep the newest conf.flight_retention dossiers
    (filenames embed a millisecond stamp, so name order is time order)."""
    keep = max(int(conf.flight_retention), 1)
    try:
        names = sorted(n for n in os.listdir(conf.flight_dir)
                       if n.startswith("dossier_") and n.endswith(".json"))
    except OSError:
        return
    for n in names[:max(len(names) - keep, 0)]:
        try:
            os.remove(os.path.join(conf.flight_dir, n))
        except OSError:
            pass


# -- query-end hook (spark/local_runner.run_plan finally block) --------------


def on_query_end(query_id: str, run_info: Optional[dict],
                 started_at: Optional[float] = None) -> None:
    """Classify how the query ended and capture accordingly. Called from
    run_plan's finally AFTER the monitor roll-up (so the ledger line in
    the dossier carries the full counters) — inside a finally the
    propagating exception is visible via sys.exc_info()."""
    if not conf.flight_dir:
        return
    from blaze_tpu.runtime import faults

    with _lock:
        if len(_run_infos) >= _RUN_INFOS_MAX:
            _run_infos.pop(next(iter(_run_infos)))
        _run_infos[query_id] = (dict(run_info or {}), started_at)
    exc = sys.exc_info()[1]
    if isinstance(exc, Exception):
        if isinstance(exc, faults.DeadlineError):
            trigger = "deadline"
        elif isinstance(exc, faults.HungError):
            trigger = "hang"
        elif isinstance(exc, faults.AdmissionRejected):
            trigger = "shed"
        else:
            trigger = "failure"
        capture(trigger, query_id, error=exc, run_info=run_info,
                include_stacks=trigger in ("deadline", "hang"),
                started_at=started_at)
    if run_info and run_info.get("resource_leaks"):
        capture("resource_leak", query_id, run_info=run_info,
                detail={"resource_leaks": run_info["resource_leaks"]},
                started_at=started_at)
    with _lock:
        _stacks.pop(query_id, None)


# -- reading (tools/blaze_inspect.py) ----------------------------------------


def list_dossiers(directory: Optional[str] = None) -> List[Dict[str, Any]]:
    """Newest-first summaries of the dossiers in `directory` (default
    conf.flight_dir): path, trigger, query, tenant, error, top finding."""
    d = directory or conf.flight_dir
    if not d or not os.path.isdir(d):
        return []
    out = []
    for n in sorted(os.listdir(d), reverse=True):
        if not (n.startswith("dossier_") and n.endswith(".json")):
            continue
        path = os.path.join(d, n)
        try:
            doc = load(path)
        except (OSError, ValueError):
            continue
        findings = doc.get("findings") or []
        out.append({
            "path": path,
            "schema_version": doc.get("schema_version"),
            "captured_at": doc.get("captured_at"),
            "trigger": doc.get("trigger"),
            "query_id": doc.get("query_id"),
            "tenant_id": doc.get("tenant_id"),
            "error": (doc.get("error") or {}).get("type")
            if doc.get("error") else None,
            "top_finding": findings[0].get("code") if findings else None,
        })
    return out


def load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
