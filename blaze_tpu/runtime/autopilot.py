"""Self-tuning autopilot: guarded per-fingerprint knob adaptation.

Closes the loop between the doctor's typed findings (each suggestion
names a declared Knob — blazelint's doctor-knob-sync rule enforces it)
and the conf overlay system (config.resolve_overlay): after each run of
a fingerprinted query, a bounded explorer moves ONE knob ONE step in the
direction the top finding suggests, runs the new value as a canary, and
lets `history.detect_regressions()` judge it:

  propose   top doctor finding names an actuatable knob (ACTUATORS and
            a declared step/min/max schedule); the next value is one
            clamped step from the current settled value, never a value
            this fingerprint has quarantined, and never while
            `autopilot_max_active_canaries` canaries are already live
  canary    runs of the proposed overlay are stamped canary=true in
            history (StatisticsFeed baselines never mix canary and
            settled runs) and verdicted against the SETTLED baseline
  promote   after `autopilot_canary_runs` CONSECUTIVE canary runs beat
            the settled p50 wall time, the value joins the fingerprint's
            settled overlay (fleet-class knobs also publish to base conf
            so the autoscaler's policy loop routes on them)
  rollback  any regression verdict (wall_ms or copied_bytes, the
            detect_regressions contract) reverts the overlay
            immediately, quarantines the value for this fingerprint
            (never re-proposed — no oscillation), and cuts an
            `autopilot_rollback` trace event + flight dossier; a canary
            that can't build its streak within 3x the budget is
            reverted+quarantined as inconclusive

Decisions persist in a crash-atomic `OverlayStore` JSONL under
`conf.autopilot_dir` (the journal append idiom: heal a torn tail, write,
flush+fsync; loaders skip unparseable lines) — settled overlays and
quarantine lists survive driver restart AND standby failover, because
the standby folds the same file on takeover. Everything is gated on
`conf.autopilot_enabled` + `conf.autopilot_dir` + a history store (the
baseline source); off, the run_plan hook sites pay one truthiness check.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from blaze_tpu.config import KNOBS, conf

# The knobs the explorer may actuate (the ROADMAP's distributed set:
# executor routing via the autoscaler ceiling, telemetry cadence,
# reconnect backoff, macro-batching, pipeline depth, dense-vs-fallback
# groupby). blazelint's doctor-knob-sync rule checks every entry is a
# declared Knob WITH a step/min/max schedule. A doctor suggestion naming
# any other knob is advice for the operator, not the autopilot.
ACTUATORS = (
    "autoscale_max",
    "control_reconnect_backoff_ms",
    "dense_agg_range",
    "dict_encode_strings",
    "prefetch_batches",
    "shuffle_mmap_enabled",
    "target_batch_bytes",
    "telemetry_ship_ms",
)

# Promoted values for fleet-class knobs also publish to the base conf:
# the autoscaler's policy loop reads conf on its own thread, so a
# per-query overlay scope can't route it — promotion (already guarded by
# the canary verdicts) is the publication point.
_PUBLISH_ON_PROMOTE = ("autoscale_max",)

# Suggestion parsing: the verb nearest BEFORE a conf.<knob> mention
# gives the step direction.
_KNOB_RE = re.compile(r"conf\.([a-z0-9_]+)")
_RAISE_RE = re.compile(r"\b(raise|increase|grow)\b")
_LOWER_RE = re.compile(r"\b(lower|reduce|shrink|drop)\b")

# A canary gets 3x its promotion budget in total runs to build the
# consecutive-wins streak; past that it is reverted as inconclusive (and
# quarantined, so the explorer cannot oscillate on a neutral value).
_INCONCLUSIVE_FACTOR = 3


def parse_suggestion(suggestion: str) -> Optional[Tuple[str, int]]:
    """(knob, direction) from a doctor suggestion, or None.

    The knob is the first `conf.<name>` mention that is actuatable
    (ACTUATORS + declared schedule); the direction is the nearest
    raise/lower-class verb before it (+1 raise, -1 lower)."""
    text = suggestion or ""
    for m in _KNOB_RE.finditer(text):
        name = m.group(1)
        knob = KNOBS.get(name)
        if name not in ACTUATORS or knob is None or knob.step is None:
            continue
        head = text[:m.start()]
        raises = [v.end() for v in _RAISE_RE.finditer(head)]
        lowers = [v.end() for v in _LOWER_RE.finditer(head)]
        if not raises and not lowers:
            continue
        direction = 1 if max(raises or [-1]) > max(lowers or [-1]) else -1
        return name, direction
    return None


class _FpState:
    """Folded per-fingerprint autopilot state."""

    __slots__ = ("settled", "canary", "quarantine", "promotions",
                 "rollbacks")

    def __init__(self) -> None:
        self.settled: Dict[str, Any] = {}
        # {"knob", "value", "wins", "runs"} while a canary is live
        self.canary: Optional[Dict[str, Any]] = None
        self.quarantine: Dict[str, List[Any]] = {}
        self.promotions = 0
        self.rollbacks = 0

    def quarantined(self, knob: str, value: Any) -> bool:
        return value in self.quarantine.get(knob, [])


class OverlayStore:
    """Append-only JSONL of autopilot decisions, folded into
    per-fingerprint state on open.

    Record kinds (all carry `fp`, `knob`, `value`, `ts`):
      propose   a new canary overlay value (+ the finding that drove it)
      promote   canary graduated to the settled overlay
      rollback  canary reverted (+ quarantined); `reason` is
                "regression" or "inconclusive"

    Appends use the journal durability idiom (heal torn tail, write one
    line, flush+fsync) and the loader skips unparseable lines, so a
    SIGKILL can tear at most the final record — the fold is what a
    restarted driver (or the standby, at takeover) resumes from. The
    file stays small: one line per DECISION, not per run."""

    def __init__(self, directory: str) -> None:
        self.dir = directory
        self.path = os.path.join(directory, "overlays.jsonl")
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    def append(self, kind: str, fp: str, **fields: Any) -> None:
        rec = {"kind": kind, "fp": fp, "ts": time.time()}
        rec.update(fields)
        line = (json.dumps(rec, default=str) + "\n").encode()
        with self._lock:
            with open(self.path, "ab+") as f:
                if f.tell() > 0:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        f.write(b"\n")
                f.write(line)
                f.flush()
                os.fsync(f.fileno())

    def load_records(self) -> List[Dict[str, Any]]:
        records: List[Dict[str, Any]] = []
        try:
            with open(self.path, "rb") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # crash-torn line
                    if isinstance(rec, dict) and rec.get("kind") \
                            and rec.get("fp"):
                        records.append(rec)
        except OSError:
            pass
        return records

    def fold(self) -> Dict[str, _FpState]:
        state: Dict[str, _FpState] = {}
        for rec in self.load_records():
            st = state.setdefault(rec["fp"], _FpState())
            kind, knob, value = rec["kind"], rec.get("knob"), \
                rec.get("value")
            if kind == "propose" and knob:
                st.canary = {"knob": knob, "value": value,
                             "wins": 0, "runs": 0}
            elif kind == "promote" and knob:
                st.settled[knob] = value
                st.canary = None
                st.promotions += 1
            elif kind == "rollback" and knob:
                st.quarantine.setdefault(knob, []).append(value)
                st.canary = None
                st.rollbacks += 1
        return state


class Autopilot:
    """One folded OverlayStore + the explorer/verdict logic."""

    def __init__(self, directory: str) -> None:
        self.store = OverlayStore(directory)
        self._lock = threading.Lock()
        self._state = self.store.fold()

    # -- admission-side ----------------------------------------------------

    def overlay_for(self, fp: str) -> Tuple[Dict[str, Any], str]:
        """The stored overlay for a fingerprint: settled values plus the
        live canary value (if any). Returns (values, canary_knob) —
        canary_knob is "" on a settled-only overlay."""
        with self._lock:
            st = self._state.get(fp)
            if st is None:
                return {}, ""
            values = dict(st.settled)
            if st.canary is not None:
                values[st.canary["knob"]] = st.canary["value"]
                return values, st.canary["knob"]
            return values, ""

    def state_for(self, fp: str) -> _FpState:
        with self._lock:
            return self._state.setdefault(fp, _FpState())

    def active_canaries(self) -> int:
        with self._lock:
            return sum(1 for st in self._state.values()
                       if st.canary is not None)

    def metrics(self) -> Dict[str, Any]:
        """Gauge inputs for monitor.prometheus_text — derived from the
        folded (restart-persistent) state."""
        with self._lock:
            rollbacks: Dict[str, int] = {}
            promotions = 0
            active = 0
            for st in self._state.values():
                if st.settled or st.canary is not None:
                    active += 1
                promotions += st.promotions
                for knob, values in st.quarantine.items():
                    rollbacks[knob] = rollbacks.get(knob, 0) + len(values)
            return {"overlays_active": active,
                    "promotions_total": promotions,
                    "rollbacks_total": rollbacks}

    # -- run-side ----------------------------------------------------------

    def observe(self, qid: str, run_info: dict,
                record: Optional[dict]) -> None:
        """Post-run hook (run_plan's finally, after history.record_run):
        verdict a canary run against the settled baseline, or propose
        the next exploration from the top doctor finding."""
        ap = (run_info or {}).get("autopilot") or {}
        fp = ap.get("fingerprint") or ""
        if not fp or record is None:
            return
        st = self.state_for(fp)
        if ap.get("canary") and st.canary is not None \
                and st.canary["knob"] == ap.get("canary_knob"):
            self._verdict(qid, fp, st, run_info, record)
        elif st.canary is None:
            self._explore(qid, fp, st, record)

    def _baseline(self, fp: str) -> List[dict]:
        """This fingerprint's settled (non-canary) history records under
        the CURRENT settled overlay hash — the like-with-like baseline."""
        from blaze_tpu.config import overlay_hash
        from blaze_tpu.runtime import history

        st = history.store()
        if st is None:
            return []
        with self._lock:
            settled_hash = overlay_hash(self._state[fp].settled) \
                if fp in self._state else None
        return [r for r in st.records()
                if r.get("autopilot_fp") == fp and not r.get("canary")
                and r.get("overlay_hash") == settled_hash]

    def _verdict(self, qid: str, fp: str, st: _FpState, run_info: dict,
                 record: dict) -> None:
        from blaze_tpu.runtime import history, trace

        canary = st.canary
        assert canary is not None
        baseline = self._baseline(fp)
        with self._lock:
            canary["runs"] += 1
        budget = max(int(conf.autopilot_canary_runs), 1)
        # regression verdict: detect_regressions over the settled
        # baseline + this canary run — same pct/grace contract as the
        # check-history gate, on wall time AND copy traffic
        regressions = history.detect_regressions(
            baseline + [record]) if len(baseline) >= 3 else []
        settled_ms = sorted(
            float(r.get("duration_ms") or 0.0) for r in baseline)
        p50 = settled_ms[len(settled_ms) // 2] if settled_ms else 0.0
        this_ms = float(record.get("duration_ms") or 0.0)
        if regressions:
            worst = regressions[0]
            self._rollback(qid, fp, st, run_info, reason="regression",
                           verdict={"metric": worst["metric"],
                                    "latest": worst["latest"],
                                    "threshold": worst["threshold"],
                                    "ratio": worst["ratio"]})
            return
        if p50 > 0 and this_ms < p50:
            with self._lock:
                canary["wins"] += 1
                wins = canary["wins"]
            trace.event("autopilot_explore", fingerprint=fp,
                        knob=canary["knob"], value=canary["value"],
                        phase="canary_win", wins=wins, budget=budget)
            if wins >= budget:
                self._promote(fp, st)
            return
        with self._lock:
            canary["wins"] = 0
            expired = canary["runs"] >= budget * _INCONCLUSIVE_FACTOR
        if expired:
            self._rollback(qid, fp, st, run_info, reason="inconclusive",
                           verdict={"runs": canary["runs"],
                                    "p50_ms": p50, "latest_ms": this_ms})

    def _promote(self, fp: str, st: _FpState) -> None:
        from blaze_tpu.runtime import trace

        with self._lock:
            canary = st.canary
            if canary is None:
                return
            knob, value = canary["knob"], canary["value"]
            st.settled[knob] = value
            st.canary = None
            st.promotions += 1
        self.store.append("promote", fp, knob=knob, value=value)
        if knob in _PUBLISH_ON_PROMOTE:
            # fleet-class knob: the policy loop reads base conf on its
            # own thread, so the promoted bound publishes globally
            conf.update(**{knob: value})
        trace.event("autopilot_promote", fingerprint=fp, knob=knob,
                    value=value,
                    published=knob in _PUBLISH_ON_PROMOTE)

    def _rollback(self, qid: str, fp: str, st: _FpState, run_info: dict,
                  reason: str, verdict: Dict[str, Any]) -> None:
        from blaze_tpu.runtime import flight_recorder, trace

        with self._lock:
            canary = st.canary
            if canary is None:
                return
            knob, value = canary["knob"], canary["value"]
            st.quarantine.setdefault(knob, []).append(value)
            st.canary = None
            st.rollbacks += 1
        self.store.append("rollback", fp, knob=knob, value=value,
                          reason=reason, verdict=verdict)
        trace.event("autopilot_rollback", fingerprint=fp, knob=knob,
                    value=value, reason=reason, **{
                        k: v for k, v in verdict.items()
                        if isinstance(v, (int, float, str))})
        flight_recorder.capture(
            "autopilot_rollback", qid,
            tenant_id=(run_info or {}).get("tenant_id", ""),
            run_info=run_info,
            detail={"fingerprint": fp, "knob": knob, "value": value,
                    "reason": reason, "verdict": verdict,
                    "quarantine": {k: list(v) for k, v
                                   in st.quarantine.items()}})

    def _explore(self, qid: str, fp: str, st: _FpState,
                 record: dict) -> None:
        from blaze_tpu.runtime import doctor, trace

        baseline = self._baseline(fp)
        # a distribution, not a point: never canary against <2 settled
        # runs, and respect the cross-store canary cap
        if len(baseline) < 3 or \
                self.active_canaries() >= \
                max(int(conf.autopilot_max_active_canaries), 1):
            return
        findings = doctor.diagnose(record)
        for finding in findings:
            parsed = parse_suggestion(finding.suggestion)
            if parsed is None:
                continue
            knob, direction = parsed
            current = st.settled.get(
                knob, object.__getattribute__(conf, knob))
            value = KNOBS[knob].propose_step(current, direction)
            # step OVER quarantined values instead of stopping at them:
            # a neutral plateau (the next step changes nothing
            # observable, goes inconclusive, gets quarantined) must not
            # dead-end the walk toward values that do help — quarantine
            # means "never run this value again", not "never pass it"
            while value is not None and st.quarantined(knob, value):
                value = KNOBS[knob].propose_step(value, direction)
            if value is None:
                continue
            with self._lock:
                st.canary = {"knob": knob, "value": value,
                             "wins": 0, "runs": 0}
            self.store.append("propose", fp, knob=knob, value=value,
                              direction=direction, finding=finding.code,
                              current=current)
            trace.event("autopilot_explore", fingerprint=fp, knob=knob,
                        value=value, phase="propose",
                        direction=direction, finding=finding.code)
            return  # ONE knob, one step, per exploration


# ---------------------------------------------------------------------------
# module singleton (the history.store() caching idiom)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_instances: Dict[str, Autopilot] = {}


def active() -> Optional[Autopilot]:
    """The process's Autopilot when enabled (one per autopilot_dir),
    else None — the single truthiness check every hook site pays."""
    if not conf.autopilot_enabled or not conf.autopilot_dir:
        return None
    d = conf.autopilot_dir
    with _lock:
        ap = _instances.get(d)
        if ap is None:
            try:
                ap = Autopilot(d)
            except OSError:
                return None
            _instances[d] = ap
        return ap


def reset() -> None:
    """Drop cached instances (test/restart isolation) — on-disk
    OverlayStore state is untouched; the next active() refolds it,
    which is exactly what a restarted driver or a standby does."""
    with _lock:
        _instances.clear()


def overlay_for(fp: str) -> Tuple[Dict[str, Any], str]:
    ap = active()
    return ap.overlay_for(fp) if ap is not None and fp else ({}, "")


def observe(qid: str, run_info: dict, record: Optional[dict]) -> None:
    ap = active()
    if ap is not None:
        try:
            ap.observe(qid, run_info, record)
        except Exception:  # noqa: BLE001 — advisory, never fails a query
            pass


def metrics() -> Optional[Dict[str, Any]]:
    ap = active()
    return ap.metrics() if ap is not None else None
