"""blaze-tpu: a TPU-native Spark SQL acceleration framework.

A brand-new framework with the capabilities of the Blaze Spark accelerator
(reference: /root/reference, a Rust/DataFusion CPU engine): it accepts a
serialized physical-plan tree per Spark task partition and executes it on
columnar data — but the engine here is jax/XLA on TPU. Columnar batches are
device arrays with static (bucketed) shapes, operators are fused into
`jax.jit`-compiled pipelines, hash tables are replaced by sort-based
algorithms (grouping, joins), and the shuffle partitioning step can run as
collectives over a TPU ICI mesh.

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):
  - plan/       plan contract (protobuf + in-memory IR) — ref: blaze-serde
  - exprs/      expression compiler pb-expr -> jax        — ref: datafusion-ext-exprs
  - columnar/   device batch model + Arrow interop        — ref: arrow-rs usage
  - ops/        physical operators                        — ref: datafusion-ext-plans
  - parallel/   device-mesh collectives (ICI shuffle)     — (TPU-native, no ref analog)
  - runtime/    per-task executor, memory, metrics, jit   — ref: blaze/src/rt.rs
  - native/     C++ layer: wire serde, JNI bridge         — ref: blaze-jni-bridge
  - spark/      Spark-side planner logic                  — ref: spark-extension
"""

__version__ = "0.1.0"

# Spark semantics need real int64/float64 columns; jax disables 64-bit by
# default. Must run before any jax array is created anywhere in the package.
import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: TPU compiles of the engine's sort/scan
# programs cost 15-75s EACH (measured on v5e; key-count-dependent), and a
# query engine re-runs the same plan shapes across processes — AQE
# re-plans, retried tasks, repeated analyst queries. The disk cache turns
# every shape's compile into a once-ever cost (steady-state dispatch is
# pure execution). Opt out with BLAZE_TPU_XLA_CACHE=off.
import os as _os

# The axon site hook (/root/.axon_site) force-sets jax_platforms=axon,cpu
# at `import jax`, overriding JAX_PLATFORMS; honor an explicit CPU request
# centrally so every entry point (pytest, validate.py, `python -m
# blaze_tpu.runtime.compile_service`) resolves to the platform the user
# asked for, not the hook's attached chip.
if "cpu" == _os.environ.get("JAX_PLATFORMS", "").strip():
    jax.config.update("jax_platforms", "cpu")

_cache_env = _os.environ.get("BLAZE_TPU_XLA_CACHE", "")
# Resolve the backend the process will ACTUALLY use (initializes the
# backend; falls back down the platform list if an attached chip's tunnel
# is out) — an env-string match gets this wrong exactly when the resolved
# platform differs from the requested one.
_XLA_PLATFORM = jax.default_backend()
_XLA_CACHE_DIR = None
if _cache_env != "off" and (_cache_env or _XLA_PLATFORM != "cpu"):
    # Default-on for accelerator platforms only: TPU executables are
    # machine-independent, but XLA:CPU AOT artifacts bake the COMPILING
    # machine's features — and chip-attached sessions route even CPU
    # compiles through the remote axon helper, poisoning a shared dir
    # for local CPU-mesh runs (observed: "+prefer-no-scatter is not
    # supported on the host machine ... could lead to SIGILL"). CPU
    # compiles are cheap; the once-ever win is the 15-75s TPU compiles.
    # An EXPLICIT BLAZE_TPU_XLA_CACHE=<dir> is honored on any platform.
    # The dir is partitioned per resolved platform so cpu- and
    # chip-compiled artifacts never share a namespace.
    _XLA_CACHE_DIR = _os.path.join(
        _cache_env or _os.path.expanduser("~/.cache/blaze_tpu_xla_dev"),
        _XLA_PLATFORM)
    jax.config.update("jax_compilation_cache_dir", _XLA_CACHE_DIR)
    # cache EVERY program: on a remote-attached chip even a "fast" 0.5s
    # compile is 5x a dispatch, and the engine's many small per-shape
    # programs (slices, concats, probes) add up to tens of seconds/query
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

from blaze_tpu.config import BlazeConf, conf

__all__ = ["BlazeConf", "conf", "__version__"]
