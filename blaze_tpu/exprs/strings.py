"""String kernels over fixed-width byte matrices — all pure jax, TPU-friendly.

Ref analogs: the specialized string expressions (datafusion-ext-exprs
string_starts_with.rs / string_ends_with.rs / string_contains.rs) and the
spark string kernels (datafusion-ext-functions spark_strings.rs). Where the
reference walks per-row byte slices, we compute on (capacity, width) uint8
matrices with static widths so everything vectorizes on the VPU.

Conventions: bytes beyond a row's length are zero; lexicographic order over
zero-padded matrices + length tiebreak equals true byte-wise order (zero is
the minimum byte; a content byte equal to zero only matters when all earlier
bytes tie, in which case the length tiebreak resolves consistently).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from blaze_tpu.columnar.batch import StringData

Array = jax.Array


def ensure_width(s: StringData, width: int) -> StringData:
    """Pad (never truncate) the byte matrix to `width` columns."""
    if s.width == width:
        return s
    if s.width > width:
        raise ValueError("ensure_width cannot shrink")
    pad = jnp.zeros((s.capacity, width - s.width), jnp.uint8)
    return StringData(jnp.concatenate([s.bytes, pad], axis=1), s.lengths)


def common_width(a: StringData, b: StringData) -> Tuple[StringData, StringData]:
    w = max(a.width, b.width)
    return ensure_width(a, w), ensure_width(b, w)


def pack_words_be(s: StringData) -> Array:
    """(cap, W) uint8 -> (cap, W//4) uint32 big-endian words.

    Unsigned big-endian word order preserves byte-wise lexicographic order —
    these words are directly usable as sort/join/group keys (the TPU-native
    replacement for the reference's row-encoded sort keys, sort_exec.rs).
    """
    cap, w = s.bytes.shape
    assert w % 4 == 0, "string width must be a multiple of 4"
    b = s.bytes.reshape(cap, w // 4, 4).astype(jnp.uint32)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


def compare(a: StringData, b: StringData) -> Tuple[Array, Array]:
    """Row-wise (lt, eq) byte-wise comparison."""
    a, b = common_width(a, b)
    wa, wb = pack_words_be(a), pack_words_be(b)
    nwords = wa.shape[1]
    lt = a.lengths < b.lengths
    eq = a.lengths == b.lengths
    # fold from last word to first: first differing word decides
    for j in range(nwords - 1, -1, -1):
        wlt = wa[:, j] < wb[:, j]
        weq = wa[:, j] == wb[:, j]
        lt = jnp.where(weq, lt, wlt)
        eq = weq & eq
    return lt, eq


def equals(a: StringData, b: StringData) -> Array:
    a, b = common_width(a, b)
    return jnp.all(a.bytes == b.bytes, axis=1) & (a.lengths == b.lengths)


def _pattern_array(pattern: bytes) -> jnp.ndarray:
    import numpy as np

    return jnp.asarray(np.frombuffer(pattern, np.uint8))


def starts_with(s: StringData, pattern: bytes) -> Array:
    p = len(pattern)
    if p == 0:
        return jnp.ones((s.capacity,), jnp.bool_)
    if p > s.width:
        return jnp.zeros((s.capacity,), jnp.bool_)
    pat = _pattern_array(pattern)
    return jnp.all(s.bytes[:, :p] == pat[None, :], axis=1) & (s.lengths >= p)


def ends_with(s: StringData, pattern: bytes) -> Array:
    p = len(pattern)
    if p == 0:
        return jnp.ones((s.capacity,), jnp.bool_)
    if p > s.width:
        return jnp.zeros((s.capacity,), jnp.bool_)
    pat = _pattern_array(pattern)
    start = jnp.maximum(s.lengths - p, 0)
    acc = s.lengths >= p
    for t in range(p):
        got = jnp.take_along_axis(s.bytes, jnp.clip(start + t, 0, s.width - 1)[:, None],
                                  axis=1)[:, 0]
        acc = acc & (got == pat[t])
    return acc


def match_positions(s: StringData, pattern: bytes) -> Array:
    """(cap, W-P+1) bool: pattern matches at shift j (ignoring length)."""
    p = len(pattern)
    pat = _pattern_array(pattern)
    nshift = s.width - p + 1
    acc = jnp.ones((s.capacity, nshift), jnp.bool_)
    for t in range(p):
        acc = acc & (s.bytes[:, t: t + nshift] == pat[t])
    return acc


def contains(s: StringData, pattern: bytes) -> Array:
    p = len(pattern)
    if p == 0:
        return jnp.ones((s.capacity,), jnp.bool_)
    if p > s.width:
        return jnp.zeros((s.capacity,), jnp.bool_)
    pos = match_positions(s, pattern)
    shifts = jnp.arange(pos.shape[1], dtype=jnp.int32)
    return jnp.any(pos & (shifts[None, :] + p <= s.lengths[:, None]), axis=1)


def like_match(s: StringData, pattern: bytes, escape: bytes = b"\\") -> Array:
    """SQL LIKE via a vectorized NFA over pattern positions.

    Tokens: literal byte, '_' (any one char), '%' (any run). State `reach[j]`
    = "first i chars can match first j tokens". The char loop runs over the
    static width; the token loop is unrolled (patterns are short).
    """
    esc = escape[0] if escape else 0x5C
    tokens = []  # (kind, byte) kind: 0 literal, 1 '_', 2 '%'
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == esc and i + 1 < len(pattern):
            tokens.append((0, pattern[i + 1]))
            i += 2
            continue
        if c == 0x25:  # %
            tokens.append((2, 0))
        elif c == 0x5F:  # _
            tokens.append((1, 0))
        else:
            tokens.append((0, c))
        i += 1
    P = len(tokens)
    cap = s.capacity

    # reach[:, j] for j in 0..P; epsilon closure over leading '%' runs
    def closure(reach):
        out = [reach[:, 0]]
        for j in range(1, P + 1):
            r = reach[:, j]
            if tokens[j - 1][0] == 2:
                r = r | out[j - 1]
            out.append(r)
        return jnp.stack(out, axis=1)

    init = jnp.zeros((cap, P + 1), jnp.bool_).at[:, 0].set(True)
    reach = closure(init)
    lens = s.lengths
    for pos in range(s.width):
        c = s.bytes[:, pos]
        in_range = pos < lens
        nxt = [jnp.zeros((cap,), jnp.bool_)]
        for j in range(1, P + 1):
            kind, tb = tokens[j - 1]
            if kind == 0:
                r = reach[:, j - 1] & (c == tb)
            elif kind == 1:
                r = reach[:, j - 1]
            else:  # '%' consumes this char (stay) — closure handles skipping
                r = reach[:, j]
            nxt.append(r)
        stepped = closure(jnp.stack(nxt, axis=1))
        reach = jnp.where(in_range[:, None], stepped, reach)
    return reach[:, P]


def upper_ascii(s: StringData) -> StringData:
    b = s.bytes
    is_lower = (b >= 0x61) & (b <= 0x7A)
    return StringData(jnp.where(is_lower, b - 32, b), s.lengths)


def lower_ascii(s: StringData) -> StringData:
    b = s.bytes
    is_upper = (b >= 0x41) & (b <= 0x5A)
    return StringData(jnp.where(is_upper, b + 32, b), s.lengths)


def char_length(s: StringData) -> Array:
    """UTF-8 character count = bytes that are not continuation bytes."""
    pos = jnp.arange(s.width, dtype=jnp.int32)
    in_len = pos[None, :] < s.lengths[:, None]
    is_cont = (s.bytes & 0xC0) == 0x80
    return jnp.sum(in_len & ~is_cont, axis=1, dtype=jnp.int32)


def octet_length(s: StringData) -> Array:
    return s.lengths


def substring(s: StringData, start: Array, length: Array) -> StringData:
    """1-based SQL substring over BYTES (caller handles utf-8 if needed).

    start may be negative (from end, SQL semantics). Output width = input
    width (lengths shrink)."""
    slen = s.lengths
    start0 = jnp.where(start > 0, start - 1,
                       jnp.where(start < 0, jnp.maximum(slen + start, 0), 0))
    start0 = jnp.minimum(start0, slen)
    out_len = jnp.clip(jnp.minimum(length, slen - start0), 0, s.width)
    j = jnp.arange(s.width, dtype=jnp.int32)
    src = jnp.clip(start0[:, None] + j[None, :], 0, s.width - 1)
    taken = jnp.take_along_axis(s.bytes, src, axis=1)
    mask = j[None, :] < out_len[:, None]
    return StringData(jnp.where(mask, taken, jnp.uint8(0)), out_len)


def concat(parts: list) -> StringData:
    """Concatenate StringData columns row-wise. Output width = bucketed sum."""
    from blaze_tpu.columnar.batch import bucket_width

    total_w = bucket_width(sum(p.width for p in parts))
    cap = parts[0].capacity
    out_len = sum([p.lengths for p in parts], jnp.zeros((cap,), jnp.int32))
    j = jnp.arange(total_w, dtype=jnp.int32)
    result = jnp.zeros((cap, total_w), jnp.uint8)
    offset = jnp.zeros((cap,), jnp.int32)
    for p in parts:
        # place p at per-row offset: out[i, offset[i] + k] = p[i, k]
        rel = j[None, :] - offset[:, None]
        in_part = (rel >= 0) & (rel < p.lengths[:, None])
        src = jnp.clip(rel, 0, p.width - 1)
        gathered = jnp.take_along_axis(p.bytes, src, axis=1)
        result = jnp.where(in_part, gathered, result)
        offset = offset + p.lengths
    return StringData(result, out_len)


def repeat(s: StringData, n: int) -> StringData:
    return concat([s] * max(n, 1)) if n >= 1 else StringData(
        jnp.zeros_like(s.bytes), jnp.zeros_like(s.lengths))


def trim(s: StringData, left: bool = True, right: bool = True,
         chars: bytes = b" ") -> StringData:
    """Trim leading/trailing characters in `chars` (default space)."""
    j = jnp.arange(s.width, dtype=jnp.int32)
    in_len = j[None, :] < s.lengths[:, None]
    is_trim = jnp.zeros_like(s.bytes, dtype=jnp.bool_)
    for c in list(chars):
        is_trim = is_trim | (s.bytes == c)
    keep = in_len & ~is_trim
    any_keep = jnp.any(keep, axis=1)
    first = jnp.argmax(keep, axis=1).astype(jnp.int32)
    last = (s.width - 1 - jnp.argmax(keep[:, ::-1], axis=1)).astype(jnp.int32)
    start = jnp.where(any_keep, first, s.lengths) if left else jnp.zeros_like(s.lengths)
    end = (jnp.where(any_keep, last + 1, start) if right
           else jnp.maximum(s.lengths, start))
    new_len = jnp.maximum(end - start, 0)
    return substring(s, start + 1, new_len)
