"""String kernels over fixed-width byte matrices — all pure jax, TPU-friendly.

Ref analogs: the specialized string expressions (datafusion-ext-exprs
string_starts_with.rs / string_ends_with.rs / string_contains.rs) and the
spark string kernels (datafusion-ext-functions spark_strings.rs). Where the
reference walks per-row byte slices, we compute on (capacity, width) uint8
matrices with static widths so everything vectorizes on the VPU.

Conventions: bytes beyond a row's length are zero; lexicographic order over
zero-padded matrices + length tiebreak equals true byte-wise order (zero is
the minimum byte; a content byte equal to zero only matters when all earlier
bytes tie, in which case the length tiebreak resolves consistently).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from blaze_tpu.columnar.batch import StringData

Array = jax.Array


def ensure_width(s: StringData, width: int) -> StringData:
    """Pad (never truncate) the byte matrix to `width` columns."""
    if s.width == width:
        return s
    if s.width > width:
        raise ValueError("ensure_width cannot shrink")
    pad = jnp.zeros((s.capacity, width - s.width), jnp.uint8)
    return StringData(jnp.concatenate([s.bytes, pad], axis=1), s.lengths)


def common_width(a: StringData, b: StringData) -> Tuple[StringData, StringData]:
    w = max(a.width, b.width)
    return ensure_width(a, w), ensure_width(b, w)


def pack_words_be(s: StringData) -> Array:
    """(cap, W) uint8 -> (cap, W//4) uint32 big-endian words.

    Unsigned big-endian word order preserves byte-wise lexicographic order —
    these words are directly usable as sort/join/group keys (the TPU-native
    replacement for the reference's row-encoded sort keys, sort_exec.rs).
    """
    cap, w = s.bytes.shape
    assert w % 4 == 0, "string width must be a multiple of 4"
    b = s.bytes.reshape(cap, w // 4, 4).astype(jnp.uint32)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


def compare(a: StringData, b: StringData) -> Tuple[Array, Array]:
    """Row-wise (lt, eq) byte-wise comparison."""
    a, b = common_width(a, b)
    wa, wb = pack_words_be(a), pack_words_be(b)
    nwords = wa.shape[1]
    lt = a.lengths < b.lengths
    eq = a.lengths == b.lengths
    # fold from last word to first: first differing word decides
    for j in range(nwords - 1, -1, -1):
        wlt = wa[:, j] < wb[:, j]
        weq = wa[:, j] == wb[:, j]
        lt = jnp.where(weq, lt, wlt)
        eq = weq & eq
    return lt, eq


def equals(a: StringData, b: StringData) -> Array:
    a, b = common_width(a, b)
    return jnp.all(a.bytes == b.bytes, axis=1) & (a.lengths == b.lengths)


def _pattern_array(pattern: bytes) -> jnp.ndarray:
    import numpy as np

    return jnp.asarray(np.frombuffer(pattern, np.uint8))


def starts_with(s: StringData, pattern: bytes) -> Array:
    p = len(pattern)
    if p == 0:
        return jnp.ones((s.capacity,), jnp.bool_)
    if p > s.width:
        return jnp.zeros((s.capacity,), jnp.bool_)
    pat = _pattern_array(pattern)
    return jnp.all(s.bytes[:, :p] == pat[None, :], axis=1) & (s.lengths >= p)


def ends_with(s: StringData, pattern: bytes) -> Array:
    p = len(pattern)
    if p == 0:
        return jnp.ones((s.capacity,), jnp.bool_)
    if p > s.width:
        return jnp.zeros((s.capacity,), jnp.bool_)
    pat = _pattern_array(pattern)
    start = jnp.maximum(s.lengths - p, 0)
    acc = s.lengths >= p
    for t in range(p):
        got = jnp.take_along_axis(s.bytes, jnp.clip(start + t, 0, s.width - 1)[:, None],
                                  axis=1)[:, 0]
        acc = acc & (got == pat[t])
    return acc


def match_positions(s: StringData, pattern: bytes) -> Array:
    """(cap, W-P+1) bool: pattern matches at shift j (ignoring length)."""
    p = len(pattern)
    pat = _pattern_array(pattern)
    nshift = s.width - p + 1
    acc = jnp.ones((s.capacity, nshift), jnp.bool_)
    for t in range(p):
        acc = acc & (s.bytes[:, t: t + nshift] == pat[t])
    return acc


def contains(s: StringData, pattern: bytes) -> Array:
    p = len(pattern)
    if p == 0:
        return jnp.ones((s.capacity,), jnp.bool_)
    if p > s.width:
        return jnp.zeros((s.capacity,), jnp.bool_)
    pos = match_positions(s, pattern)
    shifts = jnp.arange(pos.shape[1], dtype=jnp.int32)
    return jnp.any(pos & (shifts[None, :] + p <= s.lengths[:, None]), axis=1)


def like_match(s: StringData, pattern: bytes, escape: bytes = b"\\") -> Array:
    """SQL LIKE via a vectorized NFA over pattern positions.

    Tokens: literal byte, '_' (any one char), '%' (any run). State `reach[j]`
    = "first i chars can match first j tokens". The char loop runs over the
    static width; the token loop is unrolled (patterns are short).
    """
    esc = escape[0] if escape else 0x5C
    tokens = []  # (kind, byte) kind: 0 literal, 1 '_', 2 '%'
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == esc and i + 1 < len(pattern):
            tokens.append((0, pattern[i + 1]))
            i += 2
            continue
        if c == 0x25:  # %
            tokens.append((2, 0))
        elif c == 0x5F:  # _
            tokens.append((1, 0))
        else:
            tokens.append((0, c))
        i += 1
    P = len(tokens)
    cap = s.capacity

    # reach[:, j] for j in 0..P; epsilon closure over leading '%' runs
    def closure(reach):
        out = [reach[:, 0]]
        for j in range(1, P + 1):
            r = reach[:, j]
            if tokens[j - 1][0] == 2:
                r = r | out[j - 1]
            out.append(r)
        return jnp.stack(out, axis=1)

    init = jnp.zeros((cap, P + 1), jnp.bool_).at[:, 0].set(True)
    reach = closure(init)
    lens = s.lengths
    for pos in range(s.width):
        c = s.bytes[:, pos]
        in_range = pos < lens
        nxt = [jnp.zeros((cap,), jnp.bool_)]
        for j in range(1, P + 1):
            kind, tb = tokens[j - 1]
            if kind == 0:
                r = reach[:, j - 1] & (c == tb)
            elif kind == 1:
                r = reach[:, j - 1]
            else:  # '%' consumes this char (stay) — closure handles skipping
                r = reach[:, j]
            nxt.append(r)
        stepped = closure(jnp.stack(nxt, axis=1))
        reach = jnp.where(in_range[:, None], stepped, reach)
    return reach[:, P]


def upper_ascii(s: StringData) -> StringData:
    b = s.bytes
    is_lower = (b >= 0x61) & (b <= 0x7A)
    return StringData(jnp.where(is_lower, b - 32, b), s.lengths)


def lower_ascii(s: StringData) -> StringData:
    b = s.bytes
    is_upper = (b >= 0x41) & (b <= 0x5A)
    return StringData(jnp.where(is_upper, b + 32, b), s.lengths)


def char_length(s: StringData) -> Array:
    """UTF-8 character count = bytes that are not continuation bytes."""
    pos = jnp.arange(s.width, dtype=jnp.int32)
    in_len = pos[None, :] < s.lengths[:, None]
    is_cont = (s.bytes & 0xC0) == 0x80
    return jnp.sum(in_len & ~is_cont, axis=1, dtype=jnp.int32)


def octet_length(s: StringData) -> Array:
    return s.lengths


def substring(s: StringData, start: Array, length: Array) -> StringData:
    """1-based SQL substring over BYTES (caller handles utf-8 if needed).

    start may be negative (from end, SQL semantics). Output width = input
    width (lengths shrink)."""
    slen = s.lengths
    start0 = jnp.where(start > 0, start - 1,
                       jnp.where(start < 0, jnp.maximum(slen + start, 0), 0))
    start0 = jnp.minimum(start0, slen)
    out_len = jnp.clip(jnp.minimum(length, slen - start0), 0, s.width)
    j = jnp.arange(s.width, dtype=jnp.int32)
    src = jnp.clip(start0[:, None] + j[None, :], 0, s.width - 1)
    taken = jnp.take_along_axis(s.bytes, src, axis=1)
    mask = j[None, :] < out_len[:, None]
    return StringData(jnp.where(mask, taken, jnp.uint8(0)), out_len)


def concat(parts: list) -> StringData:
    """Concatenate StringData columns row-wise. Output width = bucketed sum."""
    from blaze_tpu.columnar.batch import bucket_width

    total_w = bucket_width(sum(p.width for p in parts))
    cap = parts[0].capacity
    out_len = sum([p.lengths for p in parts], jnp.zeros((cap,), jnp.int32))
    j = jnp.arange(total_w, dtype=jnp.int32)
    result = jnp.zeros((cap, total_w), jnp.uint8)
    offset = jnp.zeros((cap,), jnp.int32)
    for p in parts:
        # place p at per-row offset: out[i, offset[i] + k] = p[i, k]
        rel = j[None, :] - offset[:, None]
        in_part = (rel >= 0) & (rel < p.lengths[:, None])
        src = jnp.clip(rel, 0, p.width - 1)
        gathered = jnp.take_along_axis(p.bytes, src, axis=1)
        result = jnp.where(in_part, gathered, result)
        offset = offset + p.lengths
    return StringData(result, out_len)


def repeat(s: StringData, n: int) -> StringData:
    return concat([s] * max(n, 1)) if n >= 1 else StringData(
        jnp.zeros_like(s.bytes), jnp.zeros_like(s.lengths))


def reverse(s: StringData) -> StringData:
    """Reverse bytes per row (character-exact for ASCII; the engine's string
    kernels are byte-level throughout, same divergence note as the
    reference's caseconvert gate, BlazeConf.java:58)."""
    j = jnp.arange(s.width, dtype=jnp.int32)
    src = jnp.clip(s.lengths[:, None] - 1 - j[None, :], 0, s.width - 1)
    taken = jnp.take_along_axis(s.bytes, src, axis=1)
    mask = j[None, :] < s.lengths[:, None]
    return StringData(jnp.where(mask, taken, jnp.uint8(0)), s.lengths)


def initcap(s: StringData) -> StringData:
    """Uppercase the first letter of each whitespace-delimited word,
    lowercase the rest (ref spark_strings.rs initcap, ASCII subset)."""
    b = s.bytes
    is_ws = (b == 0x20) | ((b >= 0x09) & (b <= 0x0D))
    # word start: position 0, or previous byte is whitespace
    prev_ws = jnp.concatenate(
        [jnp.ones((s.capacity, 1), jnp.bool_), is_ws[:, :-1]], axis=1)
    lo = jnp.where((b >= 0x41) & (b <= 0x5A), b + 32, b)
    up = jnp.where((lo >= 0x61) & (lo <= 0x7A), lo - 32, lo)
    return StringData(jnp.where(prev_ws, up, lo), s.lengths)


def lpad(s: StringData, n: int, pad: bytes) -> StringData:
    """Left-pad (cyclically) with `pad` to byte-length n; truncate if longer.
    n and pad are plan-time literals (static output width)."""
    from blaze_tpu.columnar.batch import bucket_width

    n = max(int(n), 0)
    w_out = bucket_width(max(n, 1))
    j = jnp.arange(w_out, dtype=jnp.int32)
    if not pad:  # spark: nothing to pad with -> str truncated to n
        return substring(s, jnp.ones_like(s.lengths),
                         jnp.full_like(s.lengths, n))
    npad = jnp.maximum(n - s.lengths, 0)
    # byte j: pad[j % P] while j < npad, else input byte j - npad
    body = jnp.take_along_axis(
        s.bytes, jnp.clip(j[None, :] - npad[:, None], 0, s.width - 1), axis=1)
    pat = _pattern_array(pad)
    out = jnp.where(j[None, :] < npad[:, None], pat[j % len(pad)][None, :],
                    body)
    out_len = jnp.full_like(s.lengths, n)  # pad or truncate: always n
    mask = j[None, :] < out_len[:, None]
    return StringData(jnp.where(mask, out, jnp.uint8(0)), out_len)


def rpad(s: StringData, n: int, pad: bytes) -> StringData:
    """Right-pad (cyclically) with `pad` to byte-length n; truncate if
    longer. n and pad are plan-time literals."""
    from blaze_tpu.columnar.batch import bucket_width

    n = max(int(n), 0)
    w_out = bucket_width(max(n, 1))
    j = jnp.arange(w_out, dtype=jnp.int32)
    if not pad:
        return substring(s, jnp.ones_like(s.lengths),
                         jnp.full_like(s.lengths, n))
    # byte j: input byte j while j < strlen, else pad[(j - strlen) % P]
    body = jnp.take_along_axis(
        s.bytes,
        jnp.broadcast_to(jnp.clip(j[None, :], 0, s.width - 1),
                         (s.capacity, w_out)), axis=1)
    pat = _pattern_array(pad)
    rel = jnp.maximum(j[None, :] - s.lengths[:, None], 0)
    out = jnp.where(j[None, :] < s.lengths[:, None], body,
                    pat[rel % len(pad)])
    out_len = jnp.full_like(s.lengths, n)
    mask = j[None, :] < out_len[:, None]
    return StringData(jnp.where(mask, out, jnp.uint8(0)), out_len)


def strpos(s: StringData, pattern: bytes) -> Array:
    """1-based byte position of the first occurrence; 0 if absent
    (spark instr/strpos). Empty pattern -> 1."""
    p = len(pattern)
    if p == 0:
        return jnp.ones((s.capacity,), jnp.int32)
    if p > s.width:
        return jnp.zeros((s.capacity,), jnp.int32)
    pos = match_positions(s, pattern)
    shifts = jnp.arange(pos.shape[1], dtype=jnp.int32)
    ok = pos & (shifts[None, :] + p <= s.lengths[:, None])
    any_ok = jnp.any(ok, axis=1)
    first = jnp.argmax(ok, axis=1).astype(jnp.int32)
    return jnp.where(any_ok, first + 1, 0)


def greedy_matches(s: StringData, pattern: bytes):
    """Left-to-right non-overlapping matches of a literal pattern.

    Returns (emitted (cap, nshift) bool — match chosen at shift j;
    inside (cap, W) bool — byte position lies within a chosen match;
    cum_em (cap, W) int32 — chosen matches with start <= j).
    The greedy pass is a lax.scan over the static width (short loop, small
    per-step work — fine on TPU for bucketed widths)."""
    p = len(pattern)
    cap = s.capacity
    if p == 0 or p > s.width:
        nshift = max(s.width - p + 1, 1)
        z = jnp.zeros((cap, nshift), jnp.bool_)
        return (z, jnp.zeros((cap, s.width), jnp.bool_),
                jnp.zeros((cap, s.width), jnp.int32))
    pos = match_positions(s, pattern)
    nshift = pos.shape[1]
    shifts = jnp.arange(nshift, dtype=jnp.int32)
    ok = pos & (shifts[None, :] + p <= s.lengths[:, None])

    def step(next_ok, x):
        m, j = x
        emit = m & (j >= next_ok)
        return jnp.where(emit, j + p, next_ok), emit

    _, em = jax.lax.scan(step, jnp.zeros((cap,), jnp.int32),
                         (ok.T, shifts))
    emitted = em.T  # (cap, nshift)
    em_w = jnp.zeros((cap, s.width), jnp.bool_).at[:, :nshift].set(emitted)
    inside = jnp.zeros((cap, s.width), jnp.bool_)
    for t in range(p):
        shifted = jnp.roll(em_w, t, axis=1)
        if t:
            shifted = shifted.at[:, :t].set(False)
        inside = inside | shifted
    cum_em = jnp.cumsum(em_w.astype(jnp.int32), axis=1)
    return emitted, inside, cum_em


def replace(s: StringData, search: bytes, rep: bytes) -> StringData:
    """Replace every (greedy, non-overlapping) occurrence. Literal args.
    Output width statically bounds the worst-case expansion — no silent
    truncation."""
    from blaze_tpu.columnar.batch import bucket_width

    p, r = len(search), len(rep)
    if p == 0:  # spark: empty search -> unchanged
        return s
    cap = s.capacity
    emitted, inside, cum_em = greedy_matches(s, search)
    grow = max(r - p, 0)
    w_out = bucket_width(s.width + (s.width // p) * grow)
    j = jnp.arange(s.width, dtype=jnp.int32)
    rows = jnp.arange(cap, dtype=jnp.int32)[:, None]
    out = jnp.zeros((cap, w_out), jnp.uint8)
    # kept bytes: every chosen match with start <= j ended before j
    keep = (j[None, :] < s.lengths[:, None]) & ~inside
    kept_idx = j[None, :] + cum_em * (r - p)
    kept_idx = jnp.where(keep, jnp.clip(kept_idx, 0, w_out - 1), w_out)
    out = out.at[rows, kept_idx].set(s.bytes, mode="drop")
    if r:
        nshift = emitted.shape[1]
        cum_at = cum_em[:, :nshift]
        base = jnp.arange(nshift, dtype=jnp.int32)[None, :] + \
            (cum_at - 1) * (r - p)
        pat = _pattern_array(rep)
        for t in range(r):
            idx = jnp.where(emitted, jnp.clip(base + t, 0, w_out - 1), w_out)
            out = out.at[rows, idx].set(
                jnp.full((cap, nshift), pat[t], jnp.uint8), mode="drop")
    nmatches = jnp.sum(emitted, axis=1, dtype=jnp.int32)
    out_len = jnp.maximum(s.lengths + nmatches * (r - p), 0)
    mask = jnp.arange(w_out, dtype=jnp.int32)[None, :] < out_len[:, None]
    return StringData(jnp.where(mask, out, jnp.uint8(0)), out_len)


def split_part(s: StringData, delim: bytes, n: Array) -> Tuple[StringData, Array]:
    """spark split_part(str, delim, n): n-th (1-based) piece; negative n
    counts from the end; out-of-range -> empty string. Returns
    (result, defined) where defined=False marks n == 0 (spark raises; we
    null the row, converters may reject earlier)."""
    cap = s.capacity
    n = n.astype(jnp.int32)
    if len(delim) == 0 or len(delim) > s.width:
        # no splits: one part = whole string
        whole_ok = (n == 1) | (n == -1)
        empty = StringData(jnp.zeros_like(s.bytes), jnp.zeros_like(s.lengths))
        res = StringData(jnp.where(whole_ok[:, None], s.bytes, empty.bytes),
                         jnp.where(whole_ok, s.lengths, 0))
        return res, n != 0
    _, inside, cum_em = greedy_matches(s, delim)
    j = jnp.arange(s.width, dtype=jnp.int32)
    in_len = j[None, :] < s.lengths[:, None]
    last = cum_em[:, -1]
    nparts = last + 1
    eff = jnp.where(n > 0, n - 1, nparts + n)  # 0-based part index
    keep = in_len & ~inside & (cum_em == eff[:, None])
    count = jnp.sum(keep, axis=1, dtype=jnp.int32)
    start = jnp.argmax(keep, axis=1).astype(jnp.int32)
    res = substring(s, start + 1, count)
    in_range = (eff >= 0) & (eff < nparts)
    res = StringData(jnp.where(in_range[:, None], res.bytes, jnp.uint8(0)),
                     jnp.where(in_range, res.lengths, 0))
    return res, n != 0


def translate(s: StringData, frm: bytes, to: bytes) -> StringData:
    """spark translate: map chars of `frm` to `to` positionally; chars of
    `frm` beyond len(to) are deleted; first occurrence in `frm` wins."""
    import numpy as np

    table = np.arange(256, dtype=np.uint8)
    delete = np.zeros(256, bool)
    seen = set()
    for i, c in enumerate(frm):
        if c in seen:
            continue
        seen.add(c)
        if i < len(to):
            table[c] = to[i]
        else:
            delete[c] = True
    mapped = jnp.asarray(table)[s.bytes]
    dele = jnp.asarray(delete)[s.bytes]
    j = jnp.arange(s.width, dtype=jnp.int32)
    keep = (j[None, :] < s.lengths[:, None]) & ~dele
    # stable-compact kept bytes to the front of each row
    order = jnp.argsort(~keep, axis=1, stable=True)
    packed = jnp.take_along_axis(mapped, order, axis=1)
    new_len = jnp.sum(keep, axis=1, dtype=jnp.int32)
    mask = j[None, :] < new_len[:, None]
    return StringData(jnp.where(mask, packed, jnp.uint8(0)), new_len)


def chr_fn(n: Array, capacity: int) -> StringData:
    """spark chr(bigint): ASCII char of n % 256; negative -> empty."""
    from blaze_tpu.columnar.batch import bucket_width

    w = bucket_width(4)
    v = (n.astype(jnp.int64) % 256).astype(jnp.uint8)
    neg = n.astype(jnp.int64) < 0
    mat = jnp.zeros((capacity, w), jnp.uint8).at[:, 0].set(
        jnp.where(neg, jnp.uint8(0), v))
    return StringData(mat, jnp.where(neg, 0, 1).astype(jnp.int32))


def to_hex(n: Array, capacity: int) -> StringData:
    """spark hex(bigint): uppercase, no leading zeros; negatives print the
    full 16-digit two's complement (java Long.toHexString)."""
    from blaze_tpu.columnar.batch import bucket_width

    w = bucket_width(16)
    x = n.astype(jnp.int64)
    u = x.astype(jnp.uint64)
    nibbles = jnp.stack(
        [((u >> jnp.uint64(4 * (15 - k))) & jnp.uint64(0xF)).astype(jnp.uint8)
         for k in range(16)], axis=1)
    digit = jnp.where(nibbles < 10, nibbles + 0x30, nibbles - 10 + 0x41)
    nz = nibbles != 0
    any_nz = jnp.any(nz, axis=1)
    lead = jnp.where(any_nz, jnp.argmax(nz, axis=1).astype(jnp.int32), 15)
    out_len = (16 - lead).astype(jnp.int32)
    j = jnp.arange(w, dtype=jnp.int32)
    src = jnp.clip(lead[:, None] + j[None, :], 0, 15)
    shifted = jnp.take_along_axis(
        jnp.concatenate([digit, jnp.zeros((capacity, max(w - 16, 0)),
                                          jnp.uint8)], axis=1)
        if w > 16 else digit, src, axis=1)[:, :w]
    mask = j[None, :] < out_len[:, None]
    return StringData(jnp.where(mask, shifted, jnp.uint8(0)), out_len)


def trim(s: StringData, left: bool = True, right: bool = True,
         chars: bytes = b" ") -> StringData:
    """Trim leading/trailing characters in `chars` (default space)."""
    j = jnp.arange(s.width, dtype=jnp.int32)
    in_len = j[None, :] < s.lengths[:, None]
    is_trim = jnp.zeros_like(s.bytes, dtype=jnp.bool_)
    for c in list(chars):
        is_trim = is_trim | (s.bytes == c)
    keep = in_len & ~is_trim
    any_keep = jnp.any(keep, axis=1)
    first = jnp.argmax(keep, axis=1).astype(jnp.int32)
    last = (s.width - 1 - jnp.argmax(keep[:, ::-1], axis=1)).astype(jnp.int32)
    start = jnp.where(any_keep, first, s.lengths) if left else jnp.zeros_like(s.lengths)
    end = (jnp.where(any_keep, last + 1, start) if right
           else jnp.maximum(s.lengths, start))
    new_len = jnp.maximum(end - start, 0)
    return substring(s, start + 1, new_len)
