"""Physical expression IR — the in-memory form of the plan contract's
expression nodes.

Ref: the ~25 expression node kinds of the plan protobuf (blaze.proto:60-115)
and their construction in NativeConverters.scala:392-996. The IR is decoupled
from the wire format (plan/serde.py maps proto <-> IR) so the compiler and
tests can build expressions directly.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, List, Optional, Sequence, Tuple

from blaze_tpu.columnar.types import DataType


class BinOp(enum.Enum):
    ADD = "+"
    SUB = "-"
    MUL = "*"
    DIV = "/"
    MOD = "%"
    EQ = "="
    NEQ = "!="
    LT = "<"
    LE = "<="
    GT = ">"
    GE = ">="
    AND = "and"          # Kleene 3VL
    OR = "or"            # Kleene 3VL
    EQ_NULLSAFE = "<=>"
    BIT_AND = "&"
    BIT_OR = "|"
    BIT_XOR = "^"
    SHIFT_LEFT = "<<"
    SHIFT_RIGHT = ">>"


COMPARISON_OPS = {BinOp.EQ, BinOp.NEQ, BinOp.LT, BinOp.LE, BinOp.GT, BinOp.GE,
                  BinOp.EQ_NULLSAFE}


class Expr:
    """Base class; subclasses are frozen dataclasses."""

    def children(self) -> Sequence["Expr"]:
        return ()

    # structural key for jit-cache hashing
    def key(self) -> tuple:
        return (type(self).__name__,) + tuple(c.key() for c in self.children())


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    dtype: DataType
    value: Any  # None = typed null; strings as bytes/str; decimal as unscaled int

    def key(self):
        return ("lit", repr(self.dtype), repr(self.value))


@dataclasses.dataclass(frozen=True)
class Col(Expr):
    """Column reference by name (bound to an index against a schema at
    compile time — the reference binds by name too, from_proto.rs Column)."""
    name: str

    def key(self):
        return ("col", self.name)


@dataclasses.dataclass(frozen=True)
class BoundRef(Expr):
    index: int
    dtype: Optional[DataType] = None

    def key(self):
        return ("bound", self.index)


@dataclasses.dataclass(frozen=True)
class Binary(Expr):
    op: BinOp
    left: Expr
    right: Expr
    # Optional plan-provided result type (Spark computes decimal result
    # precision/scale at planning time; NativeConverters.scala:599-676).
    result_type: Optional[DataType] = None

    def children(self):
        return (self.left, self.right)

    def key(self):
        return ("bin", self.op.value, self.left.key(), self.right.key(),
                repr(self.result_type))


@dataclasses.dataclass(frozen=True)
class Not(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class IsNull(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class IsNotNull(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Negate(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    """Spark TryCast semantics (invalid -> null), ref datafusion-ext-exprs
    cast.rs + ext-commons cast.rs (float->int saturation etc.)."""
    child: Expr
    dtype: DataType

    def children(self):
        return (self.child,)

    def key(self):
        return ("cast", repr(self.dtype), self.child.key())


@dataclasses.dataclass(frozen=True)
class If(Expr):
    cond: Expr
    then: Expr
    otherwise: Expr

    def children(self):
        return (self.cond, self.then, self.otherwise)


@dataclasses.dataclass(frozen=True)
class CaseWhen(Expr):
    branches: Tuple[Tuple[Expr, Expr], ...]  # (condition, value)
    otherwise: Optional[Expr] = None

    def children(self):
        cs: List[Expr] = []
        for c, v in self.branches:
            cs += [c, v]
        if self.otherwise is not None:
            cs.append(self.otherwise)
        return tuple(cs)


@dataclasses.dataclass(frozen=True)
class InList(Expr):
    child: Expr
    values: Tuple[Expr, ...]  # literals
    negated: bool = False

    def children(self):
        return (self.child,) + self.values

    def key(self):
        return ("inlist", self.negated, self.child.key(),
                tuple(v.key() for v in self.values))


@dataclasses.dataclass(frozen=True)
class StringPredicate(Expr):
    """StartsWith / EndsWith / Contains — dedicated fast-path nodes like the
    reference's StringStartsWithExpr etc. (datafusion-ext-exprs lib.rs:19-27).
    """
    op: str  # "starts_with" | "ends_with" | "contains"
    child: Expr
    pattern: bytes

    def children(self):
        return (self.child,)

    def key(self):
        return ("strpred", self.op, self.pattern, self.child.key())


@dataclasses.dataclass(frozen=True)
class Like(Expr):
    """SQL LIKE with % and _ wildcards (general fallback for patterns that
    are not pure prefix/suffix/infix)."""
    child: Expr
    pattern: bytes
    escape: bytes = b"\\"

    def children(self):
        return (self.child,)

    def key(self):
        return ("like", self.pattern, self.escape, self.child.key())


@dataclasses.dataclass(frozen=True)
class ScalarFn(Expr):
    """Named scalar function from the registry (ref: 64 proto ScalarFunction
    values + SparkExtFunctions escape hatch, blaze.proto:186-252)."""
    name: str
    args: Tuple[Expr, ...]
    result_type: Optional[DataType] = None

    def children(self):
        return self.args

    def key(self):
        return ("fn", self.name, repr(self.result_type),
                tuple(a.key() for a in self.args))


@dataclasses.dataclass(frozen=True)
class GetStructField(Expr):
    child: Expr
    index: int

    def children(self):
        return (self.child,)

    def key(self):
        return ("getfield", self.index, self.child.key())


@dataclasses.dataclass(frozen=True)
class GetIndexedField(Expr):
    """arr[i] over a list column — 0-based, null when out of bounds (spark
    GetArrayItem; ref datafusion-ext-exprs get_indexed_field.rs)."""

    child: Expr
    index: "Literal"

    def children(self):
        return (self.child,)

    def key(self):
        return ("getidx", self.index.key(), self.child.key())


@dataclasses.dataclass(frozen=True)
class GetMapValue(Expr):
    """map[key] with a literal key — null when absent (ref
    get_map_value.rs)."""

    child: Expr
    map_key: "Literal"

    def children(self):
        return (self.child,)

    def key(self):
        return ("getmap", self.map_key.key(), self.child.key())


@dataclasses.dataclass(frozen=True)
class NamedStruct(Expr):
    """struct(name1, v1, ...) constructor (ref named_struct.rs)."""

    names: Tuple[str, ...]
    values: Tuple[Expr, ...]
    result_type: DataType

    def children(self):
        return self.values

    def key(self):
        return ("namedstruct", self.names, repr(self.result_type),
                tuple(v.key() for v in self.values))


@dataclasses.dataclass(frozen=True)
class MakeDecimal(Expr):
    """long unscaled -> decimal (ref proto MakeDecimal / UnscaledValue pair)."""
    child: Expr
    precision: int
    scale: int

    def children(self):
        return (self.child,)

    def key(self):
        return ("make_decimal", self.precision, self.scale, self.child.key())


@dataclasses.dataclass(frozen=True)
class UnscaledValue(Expr):
    child: Expr

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class CheckOverflow(Expr):
    child: Expr
    precision: int
    scale: int

    def children(self):
        return (self.child,)

    def key(self):
        return ("check_overflow", self.precision, self.scale, self.child.key())


@dataclasses.dataclass(frozen=True)
class UdfWrapper(Expr):
    """Serialized engine-external expression evaluated through a registered
    callback (ref SparkUDFWrapperExpr, datafusion-ext-exprs
    spark_udf_wrapper.rs: params computed natively, row batch shipped to the
    JVM over FFI, result array shipped back). Here the callback crosses
    jit via jax.pure_callback."""
    resource_id: str
    return_type: DataType
    nullable: bool
    params: Tuple[Expr, ...]

    def children(self):
        return self.params

    def key(self):
        return ("udf", self.resource_id, repr(self.return_type),
                tuple(p.key() for p in self.params))


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Expr):
    """Lazily-evaluated scalar subquery result fetched from a registered
    provider (ref SparkScalarSubqueryWrapperExpr)."""
    resource_id: str
    return_type: DataType
    nullable: bool = True

    def key(self):
        return ("scalar_subquery", self.resource_id, repr(self.return_type))


def contains_host_fn(expr: Expr) -> bool:
    """True if evaluating the expression crosses to the host (digests, JSON,
    UDF wrapper). Operators containing such expressions must execute
    unjitted — the axon TPU backend has no host-callback support (see
    hostfns.host_apply)."""
    if isinstance(expr, UdfWrapper):
        return True
    if isinstance(expr, ScalarFn):
        from blaze_tpu.exprs.functions import is_host_fn

        if is_host_fn(expr.name):
            return True
    return any(contains_host_fn(c) for c in expr.children())


# -- convenience builders --

def lit(value: Any, dtype: Optional[DataType] = None) -> Literal:
    from blaze_tpu.columnar import types as T

    if dtype is None:
        if isinstance(value, bool):
            dtype = T.BOOLEAN
        elif isinstance(value, int):
            dtype = T.INT64 if not (-(2**31) <= value < 2**31) else T.INT32
        elif isinstance(value, float):
            dtype = T.FLOAT64
        elif isinstance(value, (str, bytes)):
            dtype = T.STRING
        else:
            raise TypeError(f"cannot infer literal type for {value!r}")
    return Literal(dtype, value)


def col(name: str) -> Col:
    return Col(name)
