"""Decimal128 (p > 18) expression kernels over int64 limb-plane columns.

Ref: the reference computes decimals as Decimal128 end-to-end (arrow-rs
i128 arrays; NativeConverters.scala:599-676 supplies the result
precision/scale Spark planned). Narrow decimals (p <= 18) stay on the
engine's compact int64 representation; these kernels cover operations
whose operands or result are wide, storing values as StructData
[hi int64, lo int64-as-unsigned] (columnar/int128.py).

Supported here — and enforced at plan time by the convert strategy's
wide-decimal walk (spark/converters.py) so anything else falls back:
add/sub, mul while p1+p2 <= 38 (the product fits 128 bits), division
via bit-serial 128-bit long division (int128.divmod_full) with HALF_UP
at the planned result scale while the scale-alignment upscale provably
fits 128 bits, all comparisons, negate, casts int/narrow/wide -> wide,
wide -> narrow / float64, and CheckOverflow (null outside 10^p, Spark
non-ANSI). Mod remains plan-time rejected.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from blaze_tpu.columnar import int128 as i128
from blaze_tpu.columnar.batch import Column, StructData
from blaze_tpu.columnar.types import FLOAT64, INT64, DataType, TypeKind
from blaze_tpu.exprs import ir

Array = jax.Array


def is_wide(dtype: DataType) -> bool:
    return dtype.wide_decimal


def planes(col: Column) -> Tuple[Array, Array]:
    """(hi, lo) planes of a decimal column, widening narrow storage."""
    if col.dtype.wide_decimal:
        return col.data.children[0].data, col.data.children[1].data
    return i128.from_i64(col.data.astype(jnp.int64))


def build(dtype: DataType, hi: Array, lo: Array,
          validity: Optional[Array]) -> Column:
    return Column(dtype, StructData(
        [Column(INT64, hi, None), Column(INT64, lo, None)]), validity)


def _rescale_to(col: Column, out_scale: int
                ) -> Tuple[Array, Array, Array]:
    """(hi, lo, ok): ok=False rows wrapped during an upscale (their true
    magnitude exceeds 2^127 post-scale) and must go null/saturate."""
    h, l = planes(col)
    return i128.rescale_checked(h, l, out_scale - col.dtype.scale)


def arith(lc: Column, rc: Column, op: ir.BinOp,
          result_type: DataType, validity: Optional[Array]) -> Column:
    """ADD/SUB/MUL with a wide operand or result (plan-checked bounds).
    Rows whose operands wrap during scale alignment come out null —
    Spark's own result there is the post-CheckOverflow null."""
    out_s = result_type.scale
    if op in (ir.BinOp.ADD, ir.BinOp.SUB):
        lh, ll, lok = _rescale_to(lc, out_s)
        rh, rl, rok = _rescale_to(rc, out_s)
        h, l = (i128.add(lh, ll, rh, rl) if op == ir.BinOp.ADD
                else i128.sub(lh, ll, rh, rl))
        return _shape(result_type, h, l, _and_ok(validity, lok & rok))
    if op == ir.BinOp.MUL:
        ls, rs = lc.dtype.scale, rc.dtype.scale
        h, l = _mul(lc, rc)
        h, l, ok = i128.rescale_checked(h, l, out_s - (ls + rs))
        return _shape(result_type, h, l, _and_ok(validity, ok))
    if op == ir.BinOp.DIV:
        return _div(lc, rc, result_type, validity)
    raise NotImplementedError(f"wide decimal op {op}")


def _div(lc: Column, rc: Column, result_type: DataType,
         validity: Optional[Array]) -> Column:
    """Spark decimal division: HALF_UP at the planner's result scale.

    value = round(a * 10^delta / b) with delta = out_s - a.s + b.s; a
    negative delta instead scales the DIVISOR up (both checked for
    128-bit wrap). Divide-by-zero and out-of-precision quotients go null
    (Spark non-ANSI). Ref: datafusion-ext-commons cast.rs decimal paths /
    Spark Decimal.divide (java BigDecimal HALF_UP)."""
    out_s = result_type.scale
    delta = out_s - lc.dtype.scale + rc.dtype.scale
    ah, al = planes(lc)
    bh, bl = planes(rc)
    ok = jnp.ones(ah.shape, jnp.bool_)
    if delta >= 0:
        ah, al, ok1 = i128.rescale_checked(ah, al, delta, half_up=False)
        ok = ok & ok1
    else:
        bh, bl, ok1 = i128.rescale_checked(bh, bl, -delta, half_up=False)
        ok = ok & ok1
    nonzero = (bh != 0) | (bl != 0)
    sign = i128.is_neg(ah, al) ^ i128.is_neg(bh, bl)
    qh, ql, rh, rl = i128.divmod_full(ah, al, bh, bl)
    # HALF_UP: bump |q| when 2*rem >= |b| (128-bit unsigned compare;
    # rem < |b| < 2^127 so the doubled value's carry bit decides alone
    # when set)
    dbh, dbl = bh, bl
    abh, abl = i128.abs_(dbh, dbl)
    carry = (rh >> 63) & jnp.int64(1)
    r2h = (rh << 1) | ((rl >> 63) & jnp.int64(1))
    r2l = rl << 1
    ge = (carry == 1) | ~(i128._u_lt(r2h, abh)
                          | ((r2h == abh) & i128._u_lt(r2l, abl)))
    qh, ql = i128.add(qh, ql,
                      jnp.zeros_like(qh), ge.astype(jnp.int64))
    nh, nl = i128.neg(qh, ql)
    h = jnp.where(sign, nh, qh)
    l = jnp.where(sign, nl, ql)
    ok = ok & nonzero & i128.in_precision(h, l, result_type.precision)
    return _shape(result_type, h, l, _and_ok(validity, ok))


def _and_ok(validity: Optional[Array], ok: Array) -> Array:
    return ok if validity is None else (validity & ok)


def _mul(lc: Column, rc: Column) -> Tuple[Array, Array]:
    lw, rw = lc.dtype.wide_decimal, rc.dtype.wide_decimal
    if not lw and not rw:
        return i128.mul_i64(lc.data.astype(jnp.int64),
                            rc.data.astype(jnp.int64))
    # one side wide: |product| < 10^38 < 2^127 (plan bound p1+p2 <= 38),
    # so sign-magnitude schoolbook with the low 128 bits is exact
    ah, al = planes(lc)
    bh, bl = planes(rc)
    sign = i128.is_neg(ah, al) ^ i128.is_neg(bh, bl)
    ah, al = i128.abs_(ah, al)
    bh, bl = i128.abs_(bh, bl)
    ph, pl = i128._mul_u64(al, bl)
    ph = ph + al * bh + ah * bl          # low-64 wraps of the cross terms
    nh, nl = i128.neg(ph, pl)
    return (jnp.where(sign, nh, ph), jnp.where(sign, nl, pl))


def _shape(result_type: DataType, h: Array, l: Array,
           validity: Optional[Array]) -> Column:
    """Wide results stay limb-shaped; a narrow result type (possible when
    Spark planned p<=18 for a wide-operand expression) compacts back."""
    if result_type.wide_decimal:
        return build(result_type, h, l, validity)
    v64, fits = i128.to_i64_checked(h, l)
    validity = fits if validity is None else (validity & fits)
    return Column(result_type, v64, validity)


def compare(lc: Column, rc: Column) -> Tuple[Array, Array, Array]:
    """(lt, eq, gt) with scales aligned (Catalyst normally equalizes
    types; unequal scales upscale the smaller side). A side that would
    wrap during the upscale saturates to +/-max128 — its true magnitude
    dominates anything representable, so the order is preserved."""
    s = max(lc.dtype.scale, rc.dtype.scale)
    lh, ll, lok = _rescale_to(lc, s)
    rh, rl, rok = _rescale_to(rc, s)
    lh, ll = _saturate(lh, ll, lok, *planes(lc))
    rh, rl = _saturate(rh, rl, rok, *planes(rc))
    c = i128.cmp(lh, ll, rh, rl)
    return c < 0, c == 0, c > 0


def _saturate(h: Array, l: Array, ok: Array, oh: Array, ol: Array
              ) -> Tuple[Array, Array]:
    neg = i128.is_neg(oh, ol)
    sat_h = jnp.where(neg, np.int64(-0x8000000000000000),
                      np.int64(0x7FFFFFFFFFFFFFFF))
    sat_l = jnp.where(neg, np.int64(0), np.int64(-1))
    return jnp.where(ok, h, sat_h), jnp.where(ok, l, sat_l)


def negate(col: Column) -> Column:
    h, l = planes(col)
    nh, nl = i128.neg(h, l)
    return build(col.dtype, nh, nl, col.validity)


def check_overflow(col: Column, precision: int, scale: int,
                   result_type: DataType) -> Column:
    """Spark CheckOverflow (non-ANSI): rescale then null outside 10^p."""
    h, l, rok = _rescale_to(col, scale)
    ok = rok & i128.in_precision(h, l, precision)
    return _shape(result_type, h, l, _and_ok(col.validity, ok))


def cast_to_wide(col: Column, target: DataType) -> Column:
    """int / narrow decimal / wide decimal -> wide decimal."""
    src = col.dtype
    if src.is_decimal:
        h, l, rok = _rescale_to(col, target.scale)
    elif src.kind in (TypeKind.INT8, TypeKind.INT16, TypeKind.INT32,
                      TypeKind.INT64, TypeKind.BOOLEAN):
        h, l = i128.from_i64(col.data.astype(jnp.int64))
        h, l, rok = i128.rescale_checked(h, l, target.scale)
    else:
        raise NotImplementedError(f"cast {src} -> {target}")
    ok = rok & i128.in_precision(h, l, target.precision)
    return build(target, h, l, _and_ok(col.validity, ok))


# -- segmented aggregation kernels (ops/agg.py wide branches) --------------

_M32 = np.int64(0xFFFFFFFF)
# numpy scalars: module-level jnp constants are concrete device
# arrays that jit LIFTS into scalar-i64 buffer arguments in some
# flows — the axon backend cannot execute those (InvalidArgument);
# np scalars always fold into program literals
_I64_MIN = np.int64(-0x8000000000000000)
# any |sum| past this is already beyond every valid decimal precision
# (10^38 < 1.5e38 < 2^127), so flagging it cannot null a representable
# result; it catches true 128-bit wraps exactly where CheckOverflow's
# in-range test cannot see them
_OVERFLOW_BOUND = 1.5e38


def seg_sum_wide(h: Array, l: Array, valid: Array, layout, seg
                 ) -> Tuple[Array, Array, Array]:
    """Per-group 128-bit sums via four signed 32-bit limb plane sums
    (each limb sum is int64-exact: < 2^21 rows * 2^32). Returns
    (hi, lo, ok) per group slot; ok=False marks magnitude overflow
    (detected on an f64 shadow — sums beyond 2^127 wrap mod 2^128)."""
    neg = h < 0
    nh, nl = i128.neg(h, l)
    ah = jnp.where(neg, nh, h)
    al = jnp.where(neg, nl, l)
    sgn = jnp.where(neg, jnp.int64(-1), jnp.int64(1))
    limbs = [al & _M32, (al >> 32) & _M32, ah & _M32, (ah >> 32) & _M32]
    sums = [seg.seg_sum(limb * sgn, layout, valid) for limb in limbs]
    s0, s1, s2, s3 = sums
    # low 128 bits: s0 + s1*2^32 + (s2 + s3*2^32)*2^64  (mod 2^128)
    h1, l1 = i128.mul_small(*i128.from_i64(s1), 1 << 32)
    acc_h, acc_l = i128.add(*i128.from_i64(s0), h1, l1)
    acc_h = acc_h + s2 + (s3 << 32)
    # f64 shadow for wrap detection (exact magnitude, ~2^-50 relative)
    approx = (s0.astype(jnp.float64)
              + s1.astype(jnp.float64) * (2.0 ** 32)
              + s2.astype(jnp.float64) * (2.0 ** 64)
              + s3.astype(jnp.float64) * (2.0 ** 96))
    ok = jnp.abs(approx) < _OVERFLOW_BOUND
    return acc_h, acc_l, ok


def seg_minmax_wide(h: Array, l: Array, valid: Array, layout, seg,
                    is_min: bool) -> Tuple[Array, Array, Array]:
    """Per-group 128-bit min/max: reduce the signed hi plane, then the
    lo plane among rows at the winning hi (lo compared unsigned via the
    sign-flip trick)."""
    red = seg.seg_min if is_min else seg.seg_max
    mh, has = red(h, layout, valid)
    at_extreme = valid & (h == mh[layout.gid])
    ls = l ^ _I64_MIN
    ml_s, _ = red(ls, layout, at_extreme)
    return mh, ml_s ^ _I64_MIN, has


def div_by_count(h: Array, l: Array, cnt: Array, result: DataType,
                 extra_scale: int) -> Tuple[Array, Array, Array]:
    """(sum * 10^extra_scale) / cnt with HALF_UP — the avg finalize.
    Returns (hi, lo, ok); ok=False where the scale-up wrapped or the
    group count exceeds the limb division's < 2^31 divisor bound (those
    groups go null rather than silently dividing by a clamped count)."""
    rok = jnp.ones(h.shape, jnp.bool_)
    if extra_scale:
        h, l, rok = i128.rescale_checked(h, l, extra_scale)
    sign = h < 0
    cnt_ok = cnt < (1 << 31)
    dd = jnp.clip(jnp.maximum(cnt, 1), 1, (1 << 31) - 1)
    qh, ql, rem = i128.divmod_small(h, l, dd)
    bump = (2 * rem >= dd).astype(jnp.int64)
    qh, ql = i128.add(qh, ql, jnp.zeros_like(qh), bump)
    nh, nl = i128.neg(qh, ql)
    ok = rok & cnt_ok & i128.in_precision(qh, ql, result.precision)
    return jnp.where(sign, nh, qh), jnp.where(sign, nl, ql), ok


def cast_from_wide(col: Column, target: DataType) -> Column:
    """wide decimal -> narrow decimal / integral / float64."""
    h, l = planes(col)
    if target.is_decimal and not target.wide_decimal:
        h, l = i128.rescale(h, l, target.scale - col.dtype.scale)
        v64, fits = i128.to_i64_checked(h, l)
        inp = i128.in_precision(h, l, target.precision)
        ok = fits & inp
        validity = ok if col.validity is None else (col.validity & ok)
        return Column(target, v64, validity)
    if target.kind == TypeKind.FLOAT64:
        # convert the MAGNITUDE (negative values as hi*2^64 + lo would
        # cancel catastrophically: -2^64 + u64(lo) loses the low bits)
        neg = i128.is_neg(h, l)
        ah, al = i128.abs_(h, l)
        lo_u = jnp.where(al < 0, al.astype(jnp.float64)
                         + jnp.float64(2.0**64), al.astype(jnp.float64))
        v = ah.astype(jnp.float64) * jnp.float64(2.0**64) + lo_u
        v = jnp.where(neg, -v, v)
        return Column(FLOAT64, v / jnp.float64(10.0**col.dtype.scale),
                      col.validity)
    if target.kind in (TypeKind.INT32, TypeKind.INT64):
        # truncate the fraction, then narrow with overflow -> null
        h, l = i128.rescale(h, l, -col.dtype.scale, half_up=False)
        v64, fits = i128.to_i64_checked(h, l)
        if target.kind == TypeKind.INT32:
            in32 = (v64 >= jnp.int64(-2**31)) & (v64 < jnp.int64(2**31))
            fits = fits & in32
            out = v64.astype(jnp.int32)
        else:
            out = v64
        validity = fits if col.validity is None else (col.validity & fits)
        return Column(target, out, validity)
    raise NotImplementedError(f"cast {col.dtype} -> {target}")
