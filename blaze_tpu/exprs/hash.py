"""Bit-exact Spark Murmur3 (x86_32) in jax — the partitioning/hash-agg hash.

Ref: datafusion-ext-commons spark_hash.rs:27-90 (itself a port of Spark's
Murmur3_x86_32), and the shuffle partition computation hash(seed=42) then
pmod (datafusion-ext-plans shuffle/mod.rs:94-119). Semantics replicated:

  * int8/16/32/date, and boolean (as 1/0): hashInt(v) — sign-extended
  * int64/timestamp/decimal(p<=18 unscaled): hashLong(v) — two 32-bit halves
  * float32: hashInt(bits(f)), with -0.0 normalized to 0.0; float64 likewise
    via hashLong(bits(d))
  * string/binary: 4-byte little-endian chunks, then per-byte (signed) tail
  * null: leaves the running hash unchanged (multi-column hash chains seeds)

All arithmetic in uint32 with wrapping multiply; vectorized over rows.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from blaze_tpu.columnar import bits64
from blaze_tpu.columnar.batch import Column, StringData
from blaze_tpu.columnar.types import TypeKind

Array = jax.Array

# numpy scalars, NOT jnp: module-level jnp constants are concrete device
# arrays that jit lifts into scalar buffer arguments in some trace
# contexts — the axon backend cannot execute scalar-int buffer args, and
# the varying lifted-const count corrupts cached-executable reuse
# (runtime/jit_cache._with_stale_exec_retry is the backstop)
_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(0xE6546B64)

SPARK_SHUFFLE_SEED = 42


def _rotl(x: Array, r: int) -> Array:
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1: Array) -> Array:
    k1 = (k1 * _C1).astype(jnp.uint32)
    k1 = _rotl(k1, 15)
    return (k1 * _C2).astype(jnp.uint32)


def _mix_h1(h1: Array, k1: Array) -> Array:
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return (h1 * jnp.uint32(5) + _M5).astype(jnp.uint32)


def _fmix(h1: Array, length: Array) -> Array:
    h1 = h1 ^ length.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 16)
    h1 = (h1 * jnp.uint32(0x85EBCA6B)).astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 13)
    h1 = (h1 * jnp.uint32(0xC2B2AE35)).astype(jnp.uint32)
    return h1 ^ (h1 >> 16)


def hash_int32(v: Array, seed: Array) -> Array:
    """Spark hashInt: v int32 (already sign-extended for narrower types)."""
    h1 = _mix_h1(seed.astype(jnp.uint32), _mix_k1(v.astype(jnp.int32).view(jnp.uint32)))
    return _fmix(h1, jnp.uint32(4))


def hash_int64(v: Array, seed: Array) -> Array:
    high, low = bits64.i64_halves(v.astype(jnp.int64))
    return hash_u32_halves(high, low, seed)


def hash_u32_halves(high: Array, low: Array, seed: Array) -> Array:
    """hashLong over pre-split 64-bit words (low mixed first, like Spark)."""
    h1 = _mix_h1(seed.astype(jnp.uint32), _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, jnp.uint32(8))


def hash_bytes(s: StringData, seed: Array) -> Array:
    """Spark hashUnsafeBytes over the fixed-width matrix, masked by length."""
    cap, w = s.bytes.shape
    nwords = w // 4
    b = s.bytes.reshape(cap, nwords, 4).astype(jnp.uint32)
    words = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)  # LE
    lens = s.lengths
    nfull = lens // 4  # number of full 4-byte words

    h = jnp.broadcast_to(seed.astype(jnp.uint32), (cap,))
    # Under shard_map the loop body's output is varying over the manual
    # mesh axes (it reads the sharded batch data) while `h` derives only
    # from the replicated seed — fori_loop then rejects the carry type.
    # XOR-with-zero of batch data promotes h to the same varying type
    # without changing its value (fused away by XLA).
    h = h ^ (lens.astype(jnp.uint32) & jnp.uint32(0))

    def word_step(j, h):
        wj = jax.lax.dynamic_index_in_dim(words, j, axis=1, keepdims=False)
        return jnp.where(j < nfull, _mix_h1(h, _mix_k1(wj)), h)

    h = jax.lax.fori_loop(0, nwords, word_step, h)

    # tail: remaining 0-3 bytes, each as a SIGNED byte, mixed individually
    aligned = nfull * 4
    for t in range(3):
        pos = aligned + t
        byte = jnp.take_along_axis(
            s.bytes, jnp.clip(pos, 0, w - 1)[:, None], axis=1)[:, 0]
        sbyte = byte.astype(jnp.int8).astype(jnp.int32).view(jnp.uint32)
        h = jnp.where(pos < lens, _mix_h1(h, _mix_k1(sbyte)), h)
    return _fmix(h, lens.astype(jnp.uint32))


def _hash_wide_decimal(col: Column, seed: Array) -> Array:
    """Spark hash of a decimal with precision > 18: murmur3 over the
    MINIMAL big-endian two's-complement byte array of the unscaled
    BigInteger (java BigInteger.toByteArray), i.e. leading sign-filler
    bytes are stripped while one sign bit stays. Built as a (cap, 16)
    byte matrix + per-row length and fed to the string hasher."""
    hi = col.data.children[0].data
    lo = col.data.children[1].data
    # big-endian 16-byte representation
    parts = []
    for word in (hi, lo):
        for b in range(7, -1, -1):
            parts.append(((word >> (8 * b)) & jnp.int64(0xFF)
                          ).astype(jnp.uint8))
    be = jnp.stack(parts, axis=1)                      # (cap, 16)
    filler = jnp.where(hi < 0, jnp.uint8(0xFF), jnp.uint8(0))
    # count leading bytes droppable: byte == filler AND the NEXT byte's
    # sign bit matches (so the retained prefix still encodes the sign)
    nxt = jnp.concatenate([be[:, 1:], be[:, -1:]], axis=1)
    next_sign_ok = (nxt >> 7) == (filler[:, None] >> 7)
    droppable = (be == filler[:, None]) & next_sign_ok
    # prefix-run length of droppable (stop at first non-droppable),
    # capped at 15 so at least one byte remains
    run = jnp.cumprod(droppable.astype(jnp.int32), axis=1)
    strip = jnp.minimum(jnp.sum(run, axis=1), 15).astype(jnp.int32)
    length = jnp.int32(16) - strip
    # left-align: shift each row left by `strip` bytes
    idx = (jnp.arange(16, dtype=jnp.int32)[None, :] + strip[:, None])
    aligned = jnp.take_along_axis(be, jnp.minimum(idx, 15), axis=1)
    return hash_bytes(StringData(aligned, length), seed)


def hash_column(col: Column, seed: Array, row_mask: Optional[Array] = None) -> Array:
    """Chainable per-column hash: null (or padding) rows keep `seed`."""
    k = col.dtype.kind
    if col.is_string:
        h = hash_bytes(col.data, seed)
    elif k in (TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.DATE):
        h = hash_int32(col.data.astype(jnp.int32), seed)
    elif k == TypeKind.BOOLEAN:
        h = hash_int32(col.data.astype(jnp.int32), seed)
    elif k == TypeKind.DECIMAL and col.dtype.wide_decimal:
        h = _hash_wide_decimal(col, seed)
    elif k in (TypeKind.INT64, TypeKind.TIMESTAMP, TypeKind.DECIMAL):
        h = hash_int64(col.data, seed)
    elif k == TypeKind.FLOAT32:
        f = col.data
        f = jnp.where(f == 0.0, jnp.float32(0.0), f)  # -0.0 -> 0.0
        h = hash_int32(f.view(jnp.int32), seed)
    elif k == TypeKind.FLOAT64:
        hi32, lo32 = bits64.f64_hash_halves(col.data)
        h = hash_u32_halves(hi32, lo32, seed)
    elif k == TypeKind.NULL:
        h = jnp.broadcast_to(seed.astype(jnp.uint32), (col.capacity,))
    else:
        raise TypeError(f"hash of {col.dtype} not supported on device")
    valid = col.valid_mask()
    if row_mask is not None:
        valid = valid & row_mask
    return jnp.where(valid, h, jnp.broadcast_to(seed.astype(jnp.uint32), h.shape))


def hash_columns(cols: Sequence[Column], seed: int = SPARK_SHUFFLE_SEED,
                 row_mask: Optional[Array] = None) -> Array:
    """Multi-column Spark hash: h = hash_col_n(...hash_col_1(seed))."""
    cap = cols[0].capacity
    h = jnp.full((cap,), jnp.uint32(seed))
    for c in cols:
        h = hash_column(c, h, row_mask)
    return h.view(jnp.int32)


def pmod(hash_i32: Array, num_partitions: int) -> Array:
    """Spark non-negative modulo: partition id in [0, P)."""
    p = jnp.int32(num_partitions)
    r = hash_i32 % p
    return jnp.where(r < 0, r + p, r)
