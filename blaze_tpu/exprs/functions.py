"""Scalar function registry — Spark-compatible kernels on device columns.

Ref: the 64-entry ScalarFunction enum of the plan contract (blaze.proto:
186-252) plus the spark-ext functions (datafusion-ext-functions lib.rs:28-53:
NullIfZero, UnscaledValue, MakeDecimal, CheckOverflow, Murmur3Hash,
StringSpace/Repeat/Split/Concat/ConcatWs/Lower/Upper, MakeArray, json fns).
Math functions map 1:1 to jnp ops; string functions ride the fixed-width
kernels in strings.py. Functions with no device story yet (regex, crypto
digests, json) raise NotImplementedError at compile time so the planner can
keep those subtrees on the JVM/fallback path — same degradation contract as
the reference's tryConvert (BlazeConverters.scala:224-236).
"""

from __future__ import annotations

from typing import Callable, Dict, List

import jax.numpy as jnp

from blaze_tpu.columnar.batch import Column, ColumnBatch, StringData
from blaze_tpu.columnar.types import DataType, FLOAT64, INT32, INT64, STRING
from blaze_tpu.exprs import ir
from blaze_tpu.exprs import strings as S
from blaze_tpu.exprs.cast import _and_valid, civil_from_days

# fn(cols, batch, expr) -> Column
FunctionImpl = Callable[[List[Column], ColumnBatch, ir.ScalarFn], Column]

_REGISTRY: Dict[str, FunctionImpl] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def is_supported(name: str) -> bool:
    """Plan-time check used by the convert strategy's expression walk."""
    return name.lower() in _REGISTRY


def registered_names():
    """All native scalar-fn names (fallback coverage is tested against
    this, tests/test_fallback_fns.py)."""
    return sorted(_REGISTRY)


# functions evaluated on the host (hostfns.py) — their operators run
# unjitted (see ir.contains_host_fn / Operator.jit_safe)
HOST_EVAL_FNS = frozenset({
    "md5", "sha224", "sha256", "sha384", "sha512", "crc32",
    "get_json_object", "get_parsed_json_object", "parse_json",
})


def is_host_fn(name: str) -> bool:
    return name.lower() in HOST_EVAL_FNS


def compile_function(expr: ir.ScalarFn, schema):
    from blaze_tpu.exprs.compiler import compile_expr

    name = expr.name.lower()
    if name not in _REGISTRY:
        raise NotImplementedError(f"scalar function {expr.name} not supported on device")
    impl = _REGISTRY[name]
    arg_fns = [compile_expr(a, schema) for a in expr.args]
    return lambda b: impl([f(b) for f in arg_fns], b, expr)


def _strict(cols: List[Column]):
    v = None
    for c in cols:
        if c.validity is not None:
            v = c.validity if v is None else (v & c.validity)
    return v


def _math1(jnp_fn, domain=None, out_dtype: DataType = FLOAT64):
    def impl(cols, batch, expr):
        (c,) = cols
        x = c.data.astype(jnp.float64)
        valid = _strict(cols)
        if domain is not None:
            ok = domain(x)
            x = jnp.where(ok, x, 1.0)
            valid = _and_valid(valid, ok)
        return Column(out_dtype, jnp_fn(x), valid)

    return impl


for _name, _fn, _dom in [
    ("sqrt", jnp.sqrt, lambda x: x >= 0),
    ("exp", jnp.exp, None),
    ("ln", jnp.log, lambda x: x > 0),
    ("log", jnp.log, lambda x: x > 0),
    ("log10", jnp.log10, lambda x: x > 0),
    ("log2", jnp.log2, lambda x: x > 0),
    ("sin", jnp.sin, None),
    ("cos", jnp.cos, None),
    ("tan", jnp.tan, None),
    ("asin", jnp.arcsin, lambda x: jnp.abs(x) <= 1),
    ("acos", jnp.arccos, lambda x: jnp.abs(x) <= 1),
    ("atan", jnp.arctan, None),
    ("signum", jnp.sign, None),
]:
    _REGISTRY[_name] = _math1(_fn, _dom)


@register("abs")
def _abs(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, jnp.abs(c.data), c.validity)


@register("ceil")
def _ceil(cols, batch, expr):
    (c,) = cols
    if c.dtype.is_integral:
        return Column(INT64, c.data.astype(jnp.int64), c.validity)
    return Column(INT64, jnp.ceil(c.data.astype(jnp.float64)).astype(jnp.int64), c.validity)


@register("floor")
def _floor(cols, batch, expr):
    (c,) = cols
    if c.dtype.is_integral:
        return Column(INT64, c.data.astype(jnp.int64), c.validity)
    return Column(INT64, jnp.floor(c.data.astype(jnp.float64)).astype(jnp.int64), c.validity)


def _static_int_arg(expr, i: int, what: str) -> int:
    """Read a literal int argument from the IR (jit-safe; non-literal args
    make the whole expression fall back at plan time, ref tryConvert)."""
    from blaze_tpu.exprs import ir as _ir

    arg = expr.args[i]
    if not isinstance(arg, _ir.Literal) or arg.value is None:
        raise NotImplementedError(
            f"{expr.name}: {what} must be a non-null literal")
    return int(arg.value)


@register("round")
def _round(cols, batch, expr):
    c = cols[0]
    scale = 0
    if len(cols) > 1:
        scale = _static_int_arg(expr, 1, "scale")
    if c.dtype.is_integral and scale >= 0:
        return c
    x = c.data.astype(jnp.float64) * (10.0 ** scale)
    # spark rounds HALF_UP (away from zero), not banker's
    r = jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5)) / (10.0 ** scale)
    if c.dtype.is_integral:
        return Column(c.dtype, r.astype(c.dtype.jnp_dtype()), c.validity)
    return Column(c.dtype if c.dtype.is_floating else FLOAT64,
                  r.astype(jnp.float64 if not c.dtype.is_floating else c.dtype.jnp_dtype()),
                  c.validity)


@register("trunc")
def _trunc(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, jnp.trunc(c.data.astype(jnp.float64)).astype(c.data.dtype),
                  c.validity)


@register("pow")
@register("power")
def _pow(cols, batch, expr):
    a, b = cols
    x = a.data.astype(jnp.float64)
    y = b.data.astype(jnp.float64)
    return Column(FLOAT64, jnp.power(x, y), _strict(cols))


@register("atan2")
def _atan2(cols, batch, expr):
    a, b = cols
    return Column(FLOAT64, jnp.arctan2(a.data.astype(jnp.float64),
                                       b.data.astype(jnp.float64)), _strict(cols))


@register("nullif")
def _nullif(cols, batch, expr):
    a, b = cols
    if a.is_string:
        eq = S.equals(a.data, b.data)
    else:
        eq = a.data == b.data
    return Column(a.dtype, a.data, _and_valid(a.validity, ~(eq & b.valid_mask())))


@register("nullifzero")
def _nullifzero(cols, batch, expr):
    (a,) = cols
    return Column(a.dtype, a.data, _and_valid(a.validity, a.data != 0))


@register("coalesce")
def _coalesce(cols, batch, expr):
    out_dtype = cols[0].dtype
    if cols[0].is_string:
        w = max(c.data.width for c in cols)
        cols = [Column(c.dtype, S.ensure_width(c.data, w), c.validity) for c in cols]
        acc_b = jnp.zeros_like(cols[0].data.bytes)
        acc_l = jnp.zeros_like(cols[0].data.lengths)
        acc_v = jnp.zeros((batch.capacity,), jnp.bool_)
        for c in cols:
            fire = c.valid_mask() & ~acc_v
            acc_b = jnp.where(fire[:, None], c.data.bytes, acc_b)
            acc_l = jnp.where(fire, c.data.lengths, acc_l)
            acc_v = acc_v | fire
        return Column(out_dtype, StringData(acc_b, acc_l), acc_v)
    acc = jnp.zeros_like(cols[0].data)
    acc_v = jnp.zeros((batch.capacity,), jnp.bool_)
    for c in cols:
        fire = c.valid_mask() & ~acc_v
        acc = jnp.where(fire, c.data.astype(acc.dtype), acc)
        acc_v = acc_v | fire
    return Column(out_dtype, acc, acc_v)


# ---- string functions ----

@register("upper")
def _upper(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, S.upper_ascii(c.data), c.validity)


@register("lower")
def _lower(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, S.lower_ascii(c.data), c.validity)


@register("character_length")
@register("char_length")
@register("length")
def _char_length(cols, batch, expr):
    (c,) = cols
    return Column(INT32, S.char_length(c.data), c.validity)


@register("octet_length")
def _octet_length(cols, batch, expr):
    (c,) = cols
    return Column(INT32, c.data.lengths, c.validity)


@register("bit_length")
def _bit_length(cols, batch, expr):
    (c,) = cols
    return Column(INT32, c.data.lengths * 8, c.validity)


@register("ascii")
def _ascii(cols, batch, expr):
    (c,) = cols
    first = c.data.bytes[:, 0].astype(jnp.int32)
    return Column(INT32, jnp.where(c.data.lengths > 0, first, 0), c.validity)


@register("substr")
@register("substring")
def _substr(cols, batch, expr):
    c = cols[0]
    start = cols[1].data.astype(jnp.int32)
    if len(cols) > 2:
        length = cols[2].data.astype(jnp.int32)
    else:
        length = jnp.full((batch.capacity,), c.data.width, jnp.int32)
    return Column(c.dtype, S.substring(c.data, start, length), _strict(cols))


@register("concat")
def _concat(cols, batch, expr):
    # spark concat: null if any arg null
    return Column(STRING, S.concat([c.data for c in cols]), _strict(cols))


@register("concat_ws")
def _concat_ws(cols, batch, expr):
    """First arg separator; null args are SKIPPED (spark semantics)."""
    sep = cols[0].data
    parts = cols[1:]
    if not parts:
        from blaze_tpu.exprs.cast import _const_string

        return Column(STRING, _const_string(b"", batch.capacity), None)
    # build: for each part, an effective (possibly empty) piece + conditional sep
    pieces = []
    seen_any = jnp.zeros((batch.capacity,), jnp.bool_)
    for c in parts:
        v = c.valid_mask()
        need_sep = seen_any & v
        sep_piece = StringData(sep.bytes, jnp.where(need_sep, sep.lengths, 0))
        body = StringData(c.data.bytes, jnp.where(v, c.data.lengths, 0))
        pieces += [sep_piece, body]
        seen_any = seen_any | v
    return Column(STRING, S.concat(pieces), cols[0].validity)


@register("trim")
@register("btrim")
def _trim(cols, batch, expr):
    (c,) = cols[:1]
    return Column(c.dtype, S.trim(c.data, True, True), c.validity)


@register("ltrim")
def _ltrim(cols, batch, expr):
    (c,) = cols[:1]
    return Column(c.dtype, S.trim(c.data, True, False), c.validity)


@register("rtrim")
def _rtrim(cols, batch, expr):
    (c,) = cols[:1]
    return Column(c.dtype, S.trim(c.data, False, True), c.validity)


@register("repeat")
def _repeat(cols, batch, expr):
    c = cols[0]
    n = _static_int_arg(expr, 1, "repeat count")
    return Column(c.dtype, S.repeat(c.data, n), c.validity)


@register("string_space")
def _string_space(cols, batch, expr):
    (n,) = cols
    from blaze_tpu.columnar.batch import bucket_width

    count = jnp.clip(n.data.astype(jnp.int32), 0, 128)
    w = bucket_width(128)
    j = jnp.arange(w, dtype=jnp.int32)
    mat = jnp.where(j[None, :] < count[:, None], jnp.uint8(0x20), jnp.uint8(0))
    return Column(STRING, StringData(mat, count), n.validity)


# ---- date functions ----

@register("year")
def _year(cols, batch, expr):
    (c,) = cols
    y, _, _ = civil_from_days(c.data)
    return Column(INT32, y, c.validity)


@register("month")
def _month(cols, batch, expr):
    (c,) = cols
    _, m, _ = civil_from_days(c.data)
    return Column(INT32, m, c.validity)


@register("day")
@register("dayofmonth")
def _day(cols, batch, expr):
    (c,) = cols
    _, _, d = civil_from_days(c.data)
    return Column(INT32, d, c.validity)


@register("dayofweek")
def _dayofweek(cols, batch, expr):
    (c,) = cols
    # 1970-01-01 is Thursday; spark dayofweek: 1=Sunday..7=Saturday
    dow = (c.data.astype(jnp.int64) + 4) % 7  # 0=Sunday
    dow = jnp.where(dow < 0, dow + 7, dow)
    return Column(INT32, (dow + 1).astype(jnp.int32), c.validity)


@register("date_add")
def _date_add(cols, batch, expr):
    a, b = cols
    return Column(a.dtype, a.data + b.data.astype(jnp.int32), _strict(cols))


@register("date_sub")
def _date_sub(cols, batch, expr):
    a, b = cols
    return Column(a.dtype, a.data - b.data.astype(jnp.int32), _strict(cols))


@register("datediff")
def _datediff(cols, batch, expr):
    a, b = cols
    return Column(INT32, a.data - b.data, _strict(cols))


# ---- hash ----

@register("murmur3_hash")
@register("hash")
def _murmur3(cols, batch, expr):
    from blaze_tpu.exprs.hash import hash_columns

    return Column(INT32, hash_columns(cols, 42), None)


# ---- string tail (ref spark_strings.rs) ----

def _static_str_arg(expr, i: int, what: str) -> bytes:
    from blaze_tpu.exprs import ir as _ir

    arg = expr.args[i]
    if not isinstance(arg, _ir.Literal) or arg.value is None:
        raise NotImplementedError(
            f"{expr.name}: {what} must be a non-null literal")
    v = arg.value
    return v.encode() if isinstance(v, str) else bytes(v)


@register("reverse")
def _reverse(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, S.reverse(c.data), c.validity)


@register("initcap")
def _initcap(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, S.initcap(c.data), c.validity)


@register("left")
def _left(cols, batch, expr):
    c, n = cols[0], cols[1].data.astype(jnp.int32)
    length = jnp.maximum(n, 0)  # spark: len <= 0 -> empty
    return Column(c.dtype, S.substring(c.data, jnp.ones_like(length), length),
                  _strict(cols))


@register("right")
def _right(cols, batch, expr):
    c, n = cols[0], cols[1].data.astype(jnp.int32)
    length = jnp.maximum(n, 0)
    start = jnp.where(length > 0, -length, 1)
    return Column(c.dtype, S.substring(c.data, start, length), _strict(cols))


@register("lpad")
def _lpad(cols, batch, expr):
    c = cols[0]
    n = _static_int_arg(expr, 1, "length")
    pad = _static_str_arg(expr, 2, "pad") if len(cols) > 2 else b" "
    return Column(c.dtype, S.lpad(c.data, n, pad), c.validity)


@register("rpad")
def _rpad(cols, batch, expr):
    c = cols[0]
    n = _static_int_arg(expr, 1, "length")
    pad = _static_str_arg(expr, 2, "pad") if len(cols) > 2 else b" "
    return Column(c.dtype, S.rpad(c.data, n, pad), c.validity)


@register("strpos")
@register("instr")
@register("position")
def _strpos(cols, batch, expr):
    c = cols[0]
    pat = _static_str_arg(expr, 1, "substring")
    return Column(INT32, S.strpos(c.data, pat), _strict(cols))


@register("replace")
def _replace(cols, batch, expr):
    c = cols[0]
    search = _static_str_arg(expr, 1, "search")
    rep = _static_str_arg(expr, 2, "replacement") if len(cols) > 2 else b""
    return Column(c.dtype, S.replace(c.data, search, rep), _strict(cols[:1]))


@register("translate")
def _translate(cols, batch, expr):
    c = cols[0]
    frm = _static_str_arg(expr, 1, "from")
    to = _static_str_arg(expr, 2, "to")
    return Column(c.dtype, S.translate(c.data, frm, to), c.validity)


@register("split_part")
def _split_part(cols, batch, expr):
    c = cols[0]
    delim = _static_str_arg(expr, 1, "delimiter")
    n = cols[2].data
    res, defined = S.split_part(c.data, delim, n)
    return Column(c.dtype, res, _and_valid(_strict(cols), defined))


@register("chr")
def _chr(cols, batch, expr):
    (n,) = cols
    return Column(STRING, S.chr_fn(n.data, batch.capacity), n.validity)


@register("to_hex")
@register("hex")
def _to_hex(cols, batch, expr):
    (n,) = cols
    return Column(STRING, S.to_hex(n.data.astype(jnp.int64), batch.capacity),
                  n.validity)


# ---- digests / crc (host kernels, see hostfns.py) ----

def _digest_impl(name):
    def impl(cols, batch, expr):
        from blaze_tpu.exprs import hostfns as H

        width, row_fn = H.DIGESTS[name]
        return H.host_bytes_to_string(cols[0], batch,
                                      _hex_width(width), row_fn)

    return impl


def _hex_width(w: int) -> int:
    from blaze_tpu.columnar.batch import bucket_width

    return bucket_width(w)


for _d in ("md5", "sha224", "sha256", "sha384", "sha512"):
    _REGISTRY[_d] = _digest_impl(_d)


@register("crc32")
def _crc32(cols, batch, expr):
    from blaze_tpu.exprs import hostfns as H

    return H.host_bytes_to_int64(cols[0], batch, H.crc32_value)


# ---- json (host kernels; ref spark_get_json_object.rs) ----

@register("get_json_object")
@register("get_parsed_json_object")
def _get_json_object(cols, batch, expr):
    from blaze_tpu.exprs import hostfns as H

    c = cols[0]
    path = _static_str_arg(expr, 1, "json path").decode()
    steps = H.parse_json_path(path)
    if steps is None:
        # malformed path: all-null column of the input's width
        return Column(STRING, StringData(jnp.zeros_like(c.data.bytes),
                                         jnp.zeros_like(c.data.lengths)),
                      jnp.zeros((batch.capacity,), jnp.bool_))
    return H.host_bytes_to_string(
        c, batch, c.data.width,
        lambda raw: H.get_json_object_row(raw, steps))


@register("parse_json")
def _parse_json(cols, batch, expr):
    from blaze_tpu.exprs import hostfns as H

    c = cols[0]
    return H.host_bytes_to_string(c, batch, c.data.width,
                                  H.validate_json_row)


@register("null_if_zero")
def _null_if_zero(cols, batch, expr):
    return _nullifzero(cols, batch, expr)


@register("make_array")
def _make_array(cols, batch, expr):
    """spark array(...): one fixed-size list per row (ref spark_make_array.rs).

    Offsets are uniform (k elements per row); element validity carries each
    argument's nullability."""
    from blaze_tpu.columnar.batch import ListData
    from blaze_tpu.columnar import types as T

    k = len(cols)
    cap = batch.capacity
    if k == 0:
        raise NotImplementedError("make_array() with no args")
    elem_dtype = cols[0].dtype
    offsets = (jnp.arange(cap + 1, dtype=jnp.int32) * k)
    if cols[0].is_string:
        w = max(c.data.width for c in cols)
        datas = [S.ensure_width(c.data, w) for c in cols]
        eb = jnp.stack([d.bytes for d in datas], axis=1).reshape(cap * k, w)
        el = jnp.stack([d.lengths for d in datas], axis=1).reshape(cap * k)
        elems = Column(elem_dtype, StringData(eb, el),
                       _interleave_validity(cols, cap, k))
    else:
        ed = jnp.stack([c.data for c in cols], axis=1).reshape(cap * k)
        elems = Column(elem_dtype, ed, _interleave_validity(cols, cap, k))
    return Column(T.list_of(elem_dtype), ListData(offsets, elems), None)


def _interleave_validity(cols, cap, k):
    if all(c.validity is None for c in cols):
        return None
    vs = [c.valid_mask() for c in cols]
    return jnp.stack(vs, axis=1).reshape(cap * k)
