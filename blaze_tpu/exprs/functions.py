"""Scalar function registry — Spark-compatible kernels on device columns.

Ref: the 64-entry ScalarFunction enum of the plan contract (blaze.proto:
186-252) plus the spark-ext functions (datafusion-ext-functions lib.rs:28-53:
NullIfZero, UnscaledValue, MakeDecimal, CheckOverflow, Murmur3Hash,
StringSpace/Repeat/Split/Concat/ConcatWs/Lower/Upper, MakeArray, json fns).
Math functions map 1:1 to jnp ops; string functions ride the fixed-width
kernels in strings.py. Functions with no device story yet (regex, crypto
digests, json) raise NotImplementedError at compile time so the planner can
keep those subtrees on the JVM/fallback path — same degradation contract as
the reference's tryConvert (BlazeConverters.scala:224-236).
"""

from __future__ import annotations

from typing import Callable, Dict, List

import jax.numpy as jnp

from blaze_tpu.columnar.batch import Column, ColumnBatch, StringData
from blaze_tpu.columnar.types import (
    BOOLEAN, DataType, FLOAT64, INT32, INT64, STRING, TypeKind,
)
from blaze_tpu.exprs import ir
from blaze_tpu.exprs import strings as S
from blaze_tpu.exprs.cast import _and_valid, civil_from_days

# fn(cols, batch, expr) -> Column
FunctionImpl = Callable[[List[Column], ColumnBatch, ir.ScalarFn], Column]

_REGISTRY: Dict[str, FunctionImpl] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def is_supported(name: str) -> bool:
    """Plan-time check used by the convert strategy's expression walk."""
    return name.lower() in _REGISTRY


def compile_function(expr: ir.ScalarFn, schema):
    from blaze_tpu.exprs.compiler import compile_expr

    name = expr.name.lower()
    if name not in _REGISTRY:
        raise NotImplementedError(f"scalar function {expr.name} not supported on device")
    impl = _REGISTRY[name]
    arg_fns = [compile_expr(a, schema) for a in expr.args]
    return lambda b: impl([f(b) for f in arg_fns], b, expr)


def _strict(cols: List[Column]):
    v = None
    for c in cols:
        if c.validity is not None:
            v = c.validity if v is None else (v & c.validity)
    return v


def _math1(jnp_fn, domain=None, out_dtype: DataType = FLOAT64):
    def impl(cols, batch, expr):
        (c,) = cols
        x = c.data.astype(jnp.float64)
        valid = _strict(cols)
        if domain is not None:
            ok = domain(x)
            x = jnp.where(ok, x, 1.0)
            valid = _and_valid(valid, ok)
        return Column(out_dtype, jnp_fn(x), valid)

    return impl


for _name, _fn, _dom in [
    ("sqrt", jnp.sqrt, lambda x: x >= 0),
    ("exp", jnp.exp, None),
    ("ln", jnp.log, lambda x: x > 0),
    ("log", jnp.log, lambda x: x > 0),
    ("log10", jnp.log10, lambda x: x > 0),
    ("log2", jnp.log2, lambda x: x > 0),
    ("sin", jnp.sin, None),
    ("cos", jnp.cos, None),
    ("tan", jnp.tan, None),
    ("asin", jnp.arcsin, lambda x: jnp.abs(x) <= 1),
    ("acos", jnp.arccos, lambda x: jnp.abs(x) <= 1),
    ("atan", jnp.arctan, None),
    ("signum", jnp.sign, None),
]:
    _REGISTRY[_name] = _math1(_fn, _dom)


@register("abs")
def _abs(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, jnp.abs(c.data), c.validity)


@register("ceil")
def _ceil(cols, batch, expr):
    (c,) = cols
    if c.dtype.is_integral:
        return Column(INT64, c.data.astype(jnp.int64), c.validity)
    return Column(INT64, jnp.ceil(c.data.astype(jnp.float64)).astype(jnp.int64), c.validity)


@register("floor")
def _floor(cols, batch, expr):
    (c,) = cols
    if c.dtype.is_integral:
        return Column(INT64, c.data.astype(jnp.int64), c.validity)
    return Column(INT64, jnp.floor(c.data.astype(jnp.float64)).astype(jnp.int64), c.validity)


def _static_int_arg(expr, i: int, what: str) -> int:
    """Read a literal int argument from the IR (jit-safe; non-literal args
    make the whole expression fall back at plan time, ref tryConvert)."""
    from blaze_tpu.exprs import ir as _ir

    arg = expr.args[i]
    if not isinstance(arg, _ir.Literal) or arg.value is None:
        raise NotImplementedError(
            f"{expr.name}: {what} must be a non-null literal")
    return int(arg.value)


@register("round")
def _round(cols, batch, expr):
    c = cols[0]
    scale = 0
    if len(cols) > 1:
        scale = _static_int_arg(expr, 1, "scale")
    if c.dtype.is_integral and scale >= 0:
        return c
    x = c.data.astype(jnp.float64) * (10.0 ** scale)
    # spark rounds HALF_UP (away from zero), not banker's
    r = jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5)) / (10.0 ** scale)
    if c.dtype.is_integral:
        return Column(c.dtype, r.astype(c.dtype.jnp_dtype()), c.validity)
    return Column(c.dtype if c.dtype.is_floating else FLOAT64,
                  r.astype(jnp.float64 if not c.dtype.is_floating else c.dtype.jnp_dtype()),
                  c.validity)


@register("trunc")
def _trunc(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, jnp.trunc(c.data.astype(jnp.float64)).astype(c.data.dtype),
                  c.validity)


@register("pow")
@register("power")
def _pow(cols, batch, expr):
    a, b = cols
    x = a.data.astype(jnp.float64)
    y = b.data.astype(jnp.float64)
    return Column(FLOAT64, jnp.power(x, y), _strict(cols))


@register("atan2")
def _atan2(cols, batch, expr):
    a, b = cols
    return Column(FLOAT64, jnp.arctan2(a.data.astype(jnp.float64),
                                       b.data.astype(jnp.float64)), _strict(cols))


@register("nullif")
def _nullif(cols, batch, expr):
    a, b = cols
    if a.is_string:
        eq = S.equals(a.data, b.data)
    else:
        eq = a.data == b.data
    return Column(a.dtype, a.data, _and_valid(a.validity, ~(eq & b.valid_mask())))


@register("nullifzero")
def _nullifzero(cols, batch, expr):
    (a,) = cols
    return Column(a.dtype, a.data, _and_valid(a.validity, a.data != 0))


@register("coalesce")
def _coalesce(cols, batch, expr):
    out_dtype = cols[0].dtype
    if cols[0].is_string:
        w = max(c.data.width for c in cols)
        cols = [Column(c.dtype, S.ensure_width(c.data, w), c.validity) for c in cols]
        acc_b = jnp.zeros_like(cols[0].data.bytes)
        acc_l = jnp.zeros_like(cols[0].data.lengths)
        acc_v = jnp.zeros((batch.capacity,), jnp.bool_)
        for c in cols:
            fire = c.valid_mask() & ~acc_v
            acc_b = jnp.where(fire[:, None], c.data.bytes, acc_b)
            acc_l = jnp.where(fire, c.data.lengths, acc_l)
            acc_v = acc_v | fire
        return Column(out_dtype, StringData(acc_b, acc_l), acc_v)
    acc = jnp.zeros_like(cols[0].data)
    acc_v = jnp.zeros((batch.capacity,), jnp.bool_)
    for c in cols:
        fire = c.valid_mask() & ~acc_v
        acc = jnp.where(fire, c.data.astype(acc.dtype), acc)
        acc_v = acc_v | fire
    return Column(out_dtype, acc, acc_v)


# ---- string functions ----

@register("upper")
def _upper(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, S.upper_ascii(c.data), c.validity)


@register("lower")
def _lower(cols, batch, expr):
    (c,) = cols
    return Column(c.dtype, S.lower_ascii(c.data), c.validity)


@register("character_length")
@register("char_length")
@register("length")
def _char_length(cols, batch, expr):
    (c,) = cols
    return Column(INT32, S.char_length(c.data), c.validity)


@register("octet_length")
def _octet_length(cols, batch, expr):
    (c,) = cols
    return Column(INT32, c.data.lengths, c.validity)


@register("bit_length")
def _bit_length(cols, batch, expr):
    (c,) = cols
    return Column(INT32, c.data.lengths * 8, c.validity)


@register("ascii")
def _ascii(cols, batch, expr):
    (c,) = cols
    first = c.data.bytes[:, 0].astype(jnp.int32)
    return Column(INT32, jnp.where(c.data.lengths > 0, first, 0), c.validity)


@register("substr")
@register("substring")
def _substr(cols, batch, expr):
    c = cols[0]
    start = cols[1].data.astype(jnp.int32)
    if len(cols) > 2:
        length = cols[2].data.astype(jnp.int32)
    else:
        length = jnp.full((batch.capacity,), c.data.width, jnp.int32)
    return Column(c.dtype, S.substring(c.data, start, length), _strict(cols))


@register("concat")
def _concat(cols, batch, expr):
    # spark concat: null if any arg null
    return Column(STRING, S.concat([c.data for c in cols]), _strict(cols))


@register("concat_ws")
def _concat_ws(cols, batch, expr):
    """First arg separator; null args are SKIPPED (spark semantics)."""
    sep = cols[0].data
    parts = cols[1:]
    if not parts:
        from blaze_tpu.exprs.cast import _const_string

        return Column(STRING, _const_string(b"", batch.capacity), None)
    # build: for each part, an effective (possibly empty) piece + conditional sep
    pieces = []
    seen_any = jnp.zeros((batch.capacity,), jnp.bool_)
    for c in parts:
        v = c.valid_mask()
        need_sep = seen_any & v
        sep_piece = StringData(sep.bytes, jnp.where(need_sep, sep.lengths, 0))
        body = StringData(c.data.bytes, jnp.where(v, c.data.lengths, 0))
        pieces += [sep_piece, body]
        seen_any = seen_any | v
    return Column(STRING, S.concat(pieces), cols[0].validity)


@register("trim")
@register("btrim")
def _trim(cols, batch, expr):
    (c,) = cols[:1]
    return Column(c.dtype, S.trim(c.data, True, True), c.validity)


@register("ltrim")
def _ltrim(cols, batch, expr):
    (c,) = cols[:1]
    return Column(c.dtype, S.trim(c.data, True, False), c.validity)


@register("rtrim")
def _rtrim(cols, batch, expr):
    (c,) = cols[:1]
    return Column(c.dtype, S.trim(c.data, False, True), c.validity)


@register("repeat")
def _repeat(cols, batch, expr):
    c = cols[0]
    n = _static_int_arg(expr, 1, "repeat count")
    return Column(c.dtype, S.repeat(c.data, n), c.validity)


@register("string_space")
def _string_space(cols, batch, expr):
    (n,) = cols
    from blaze_tpu.columnar.batch import bucket_width

    count = jnp.clip(n.data.astype(jnp.int32), 0, 128)
    w = bucket_width(128)
    j = jnp.arange(w, dtype=jnp.int32)
    mat = jnp.where(j[None, :] < count[:, None], jnp.uint8(0x20), jnp.uint8(0))
    return Column(STRING, StringData(mat, count), n.validity)


# ---- date functions ----

@register("year")
def _year(cols, batch, expr):
    (c,) = cols
    y, _, _ = civil_from_days(c.data)
    return Column(INT32, y, c.validity)


@register("month")
def _month(cols, batch, expr):
    (c,) = cols
    _, m, _ = civil_from_days(c.data)
    return Column(INT32, m, c.validity)


@register("day")
@register("dayofmonth")
def _day(cols, batch, expr):
    (c,) = cols
    _, _, d = civil_from_days(c.data)
    return Column(INT32, d, c.validity)


@register("dayofweek")
def _dayofweek(cols, batch, expr):
    (c,) = cols
    # 1970-01-01 is Thursday; spark dayofweek: 1=Sunday..7=Saturday
    dow = (c.data.astype(jnp.int64) + 4) % 7  # 0=Sunday
    dow = jnp.where(dow < 0, dow + 7, dow)
    return Column(INT32, (dow + 1).astype(jnp.int32), c.validity)


@register("date_add")
def _date_add(cols, batch, expr):
    a, b = cols
    return Column(a.dtype, a.data + b.data.astype(jnp.int32), _strict(cols))


@register("date_sub")
def _date_sub(cols, batch, expr):
    a, b = cols
    return Column(a.dtype, a.data - b.data.astype(jnp.int32), _strict(cols))


@register("datediff")
def _datediff(cols, batch, expr):
    a, b = cols
    return Column(INT32, a.data - b.data, _strict(cols))


# ---- hash ----

@register("murmur3_hash")
@register("hash")
def _murmur3(cols, batch, expr):
    from blaze_tpu.exprs.hash import hash_columns

    return Column(INT32, hash_columns(cols, 42), None)
