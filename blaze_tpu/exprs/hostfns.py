"""Host-evaluated scalar kernels: crypto digests, CRC32, JSON path.

Ref: datafusion-ext-functions lib.rs:28-53 registers Md5/Sha*/Crc32 digests
and spark_get_json_object.rs (577 LoC) implements the Spark JSON path
evaluator with a parsed-JSON cache. These are bytewise-serial algorithms
with no vector/MXU formulation worth building — the TPU-native translation
is a `jax.pure_callback` host kernel inside the jit program, the same
boundary the engine already uses for Spark UDFs (exprs/compiler.py
_compile_udf_wrapper). Data crosses as the fixed-width byte matrices the
string columns already are, so there is no serialization step.

The JSON path evaluator supports the Spark/Hive subset: `$`, `.field`,
`['field']`, `[n]`, `[*]`. A small parsed-JSON LRU mirrors the reference's
GetParsedJsonObject/ParseJson caching pair (UserDefinedArray) without the
opaque-array machinery: parse results are memoized by content so a
projection evaluating several paths over one column parses each value once.
"""

from __future__ import annotations

import hashlib
import json
import zlib
from collections import OrderedDict
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from blaze_tpu.columnar.batch import Column, ColumnBatch, StringData
from blaze_tpu.columnar.types import INT64, STRING

# ---------------------------------------------------------------------------
# host crossing
# ---------------------------------------------------------------------------


def host_apply(callback: Callable, shapes, *args):
    """Run a host computation over device arrays.

    On concrete (non-traced) inputs — the normal path, because operators
    containing host expressions are executed UNJITTED (executor checks
    Operator.jit_safe) — this pulls to numpy, runs the callback, and pushes
    the results back: no jax callback machinery, which the axon TPU backend
    does not implement (its PJRT rejects host send/recv callbacks even in
    eager mode). Under a tracer (CPU-mesh tests jit whole pipelines, where
    XLA host callbacks DO work) it degrades to jax.pure_callback."""
    import jax.core as jcore

    if any(isinstance(a, jcore.Tracer) for a in args):
        return jax.pure_callback(callback, shapes, *args,
                                 vmap_method="sequential")
    outs = callback(*[np.asarray(a) for a in args])
    if isinstance(outs, tuple):
        return tuple(jnp.asarray(o) for o in outs)
    return jnp.asarray(outs)


def host_bytes_to_string(col: Column, batch: ColumnBatch, out_width: int,
                         row_fn: Callable[[bytes], Optional[bytes]]) -> Column:
    """Apply `row_fn` to each live, valid row's bytes on the host.

    row_fn returning None marks the row null; results longer than
    `out_width` are nulled too (never silently truncated)."""
    sd = col.data
    nrows = batch.num_rows
    valid = col.valid_mask() & batch.row_mask()

    def callback(b, lens, ok, n):
        b, lens, ok = np.asarray(b), np.asarray(lens), np.asarray(ok)
        n = int(n)
        cap = b.shape[0]
        out_b = np.zeros((cap, out_width), np.uint8)
        out_l = np.zeros((cap,), np.int32)
        out_ok = np.zeros((cap,), bool)
        for i in range(n):
            if not ok[i]:
                continue
            r = row_fn(b[i, :lens[i]].tobytes())
            if r is None or len(r) > out_width:
                continue
            out_b[i, :len(r)] = np.frombuffer(r, np.uint8)
            out_l[i] = len(r)
            out_ok[i] = True
        return out_b, out_l, out_ok

    cap = batch.capacity
    shapes = (jax.ShapeDtypeStruct((cap, out_width), np.uint8),
              jax.ShapeDtypeStruct((cap,), np.int32),
              jax.ShapeDtypeStruct((cap,), np.bool_))
    ob, ol, ook = host_apply(callback, shapes, sd.bytes, sd.lengths,
                             valid, nrows)
    return Column(STRING, StringData(ob, ol), ook)


def host_bytes_to_int64(col: Column, batch: ColumnBatch,
                        row_fn: Callable[[bytes], int]) -> Column:
    sd = col.data
    valid = col.valid_mask() & batch.row_mask()

    def callback(b, lens, ok, n):
        b, lens, ok = np.asarray(b), np.asarray(lens), np.asarray(ok)
        cap = b.shape[0]
        out = np.zeros((cap,), np.int64)
        for i in range(int(n)):
            if ok[i]:
                out[i] = row_fn(b[i, :lens[i]].tobytes())
        return out

    cap = batch.capacity
    out = host_apply(
        callback, jax.ShapeDtypeStruct((cap,), np.int64),
        sd.bytes, sd.lengths, valid, batch.num_rows)
    return Column(INT64, out, col.validity)


# ---------------------------------------------------------------------------
# digests (ref lib.rs digest registrations)
# ---------------------------------------------------------------------------

DIGESTS = {
    "md5": (32, lambda b: hashlib.md5(b).hexdigest().encode()),
    "sha224": (56, lambda b: hashlib.sha224(b).hexdigest().encode()),
    "sha256": (64, lambda b: hashlib.sha256(b).hexdigest().encode()),
    "sha384": (96, lambda b: hashlib.sha384(b).hexdigest().encode()),
    "sha512": (128, lambda b: hashlib.sha512(b).hexdigest().encode()),
}


def crc32_value(b: bytes) -> int:
    return zlib.crc32(b) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# JSON path (ref spark_get_json_object.rs)
# ---------------------------------------------------------------------------


def parse_json_path(path: str) -> Optional[List]:
    """'$.a.b[0][*]' -> [('key','a'), ('key','b'), ('idx',0), ('star',)].
    Returns None for malformed paths (spark: result is NULL)."""
    if not path.startswith("$"):
        return None
    steps: List[Tuple] = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            j = i + 1
            while j < n and path[j] not in ".[":
                j += 1
            name = path[i + 1:j]
            if not name:
                return None
            steps.append(("key", name))
            i = j
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                return None
            inner = path[i + 1:j].strip()
            if inner == "*":
                steps.append(("star",))
            elif (len(inner) >= 2 and inner[0] in "'\""
                  and inner[-1] == inner[0]):
                steps.append(("key", inner[1:-1]))
            else:
                try:
                    steps.append(("idx", int(inner)))
                except ValueError:
                    return None
            i = j + 1
        else:
            return None
    return steps


_PARSE_CACHE: "OrderedDict[bytes, object]" = OrderedDict()
_PARSE_CACHE_MAX = 4096
_INVALID = object()


def cached_parse(raw: bytes):
    """Parsed-JSON memo (ref: ParseJson + UserDefinedArray caching)."""
    hit = _PARSE_CACHE.get(raw)
    if hit is not None:
        _PARSE_CACHE.move_to_end(raw)
        return hit
    try:
        v = json.loads(raw)
        if v is None:
            v = _INVALID
    except Exception:
        v = _INVALID
    _PARSE_CACHE[raw] = v
    if len(_PARSE_CACHE) > _PARSE_CACHE_MAX:
        _PARSE_CACHE.popitem(last=False)
    return v


def eval_json_path(value, steps: List[Tuple]):
    """Returns (found, value). [*] fans out and collects matches."""
    cur = [value]
    for st in steps:
        nxt = []
        if st[0] == "key":
            for v in cur:
                if isinstance(v, dict) and st[1] in v:
                    nxt.append(v[st[1]])
        elif st[0] == "idx":
            for v in cur:
                if isinstance(v, list) and -len(v) <= st[1] < len(v):
                    nxt.append(v[st[1]])
        else:  # star
            for v in cur:
                if isinstance(v, list):
                    nxt.extend(v)
        cur = nxt
        if not cur:
            return False, None
    if len(cur) == 1:
        return True, cur[0]
    return True, cur


def render_json_value(v) -> Optional[bytes]:
    """Spark rendering: strings raw (unquoted), null -> NULL, containers as
    compact JSON."""
    if v is None:
        return None
    if isinstance(v, str):
        return v.encode()
    if isinstance(v, bool):
        return b"true" if v else b"false"
    if isinstance(v, (int, float)):
        return json.dumps(v).encode()
    return json.dumps(v, separators=(",", ":")).encode()


def get_json_object_row(raw: bytes, steps: List[Tuple]) -> Optional[bytes]:
    v = cached_parse(raw)
    if v is _INVALID:
        return None
    found, out = eval_json_path(v, steps)
    if not found:
        return None
    return render_json_value(out)


def validate_json_row(raw: bytes) -> Optional[bytes]:
    """parse_json: NULL for invalid documents, input text otherwise."""
    return raw if cached_parse(raw) is not _INVALID else None
