"""Spark cast semantics on device (TryCast: invalid -> null, ANSI off).

Ref: datafusion-ext-exprs/src/cast.rs (TryCastExpr) and
datafusion-ext-commons/src/cast.rs (spark-specific rules: float->int
saturation, string parsing, decimal rescale with HALF_UP). Implemented as
dense jax ops over fixed-width columns; string parsing runs on device over
the byte matrix (no host round-trip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blaze_tpu.columnar.batch import Column, StringData, bucket_width
from blaze_tpu.columnar.types import DataType, TypeKind

Array = jax.Array

_INT_BOUNDS = {
    TypeKind.INT8: (-(2**7), 2**7 - 1),
    TypeKind.INT16: (-(2**15), 2**15 - 1),
    TypeKind.INT32: (-(2**31), 2**31 - 1),
    TypeKind.INT64: (-(2**63), 2**63 - 1),
}


def cast_column(col: Column, target: DataType) -> Column:
    src = col.dtype
    if src == target:
        return col
    if src.is_string_like and target.is_string_like:
        return Column(target, col.data, col.validity)

    if src.is_string_like:
        return _from_string(col, target)
    if target.is_string_like:
        return _to_string(col, target)

    if src.wide_decimal or target.wide_decimal:
        from blaze_tpu.exprs import wide_decimal as W

        if target.wide_decimal:
            return W.cast_to_wide(col, target)
        return W.cast_from_wide(col, target)

    k, tk = src.kind, target.kind
    valid = col.validity
    data = col.data

    if k == TypeKind.NULL:
        from blaze_tpu.columnar.batch import _zero_column

        z = _zero_column(target, col.capacity)
        return Column(target, z.data, jnp.zeros((col.capacity,), jnp.bool_))

    if k == TypeKind.BOOLEAN:
        if target.is_integral or target.is_floating:
            return Column(target, data.astype(target.jnp_dtype()), valid)
        if target.is_decimal:
            return _int_to_decimal(data.astype(jnp.int64), valid, target)
    if tk == TypeKind.BOOLEAN:
        if src.is_numeric and not src.is_decimal:
            return Column(target, data != 0, valid)
        if src.is_decimal:
            return Column(target, data != 0, valid)

    # date/timestamp as their underlying ints
    if k == TypeKind.DATE and target.is_integral:
        return _int_to_int(data, valid, src, target)
    if src.is_integral and tk == TypeKind.DATE:
        return _int_to_int(data, valid, src, target)
    if k == TypeKind.TIMESTAMP and (target.is_integral or target.is_floating):
        # spark: timestamp -> long = seconds; -> double = fractional seconds
        if target.is_integral:
            secs = jnp.floor_divide(data, 1_000_000)
            return _int_to_int(secs, valid, DataType(TypeKind.INT64), target)
        return Column(target, data.astype(jnp.float64) / 1e6, valid)
    if src.is_integral and tk == TypeKind.TIMESTAMP:
        return Column(target, data.astype(jnp.int64) * 1_000_000, valid)
    if k == TypeKind.DATE and tk == TypeKind.TIMESTAMP:
        return Column(target, data.astype(jnp.int64) * 86_400_000_000, valid)
    if k == TypeKind.TIMESTAMP and tk == TypeKind.DATE:
        return Column(target, jnp.floor_divide(data, 86_400_000_000).astype(jnp.int32), valid)

    if src.is_integral:
        if target.is_integral:
            return _int_to_int(data, valid, src, target)
        if target.is_floating:
            return Column(target, data.astype(target.jnp_dtype()), valid)
        if target.is_decimal:
            return _int_to_decimal(data.astype(jnp.int64), valid, target)
    if src.is_floating:
        if target.is_floating:
            return Column(target, data.astype(target.jnp_dtype()), valid)
        if target.is_integral:
            return _float_to_int(data, valid, target)
        if target.is_decimal:
            return _float_to_decimal(data, valid, target)
    if src.is_decimal:
        scale_div = 10 ** src.scale
        if target.is_floating:
            return Column(target, data.astype(jnp.float64) / scale_div, valid)
        if target.is_integral:
            trunc = jnp.sign(data) * (jnp.abs(data) // scale_div)  # toward zero
            return _int_to_int(trunc, valid, DataType(TypeKind.INT64), target)
        if target.is_decimal:
            return _decimal_rescale(data, valid, src, target)

    raise TypeError(f"unsupported cast {src} -> {target}")


# ---- numeric helpers ----

def _int_to_int(data: Array, valid, src: DataType, target: DataType) -> Column:
    # Java narrowing semantics: wrap (two's complement truncation)
    return Column(target, data.astype(target.jnp_dtype()), valid)


def _float_to_int(data: Array, valid, target: DataType) -> Column:
    lo, hi = _INT_BOUNDS[target.kind if target.kind in _INT_BOUNDS else TypeKind.INT64]
    # saturate; NaN -> 0 (spark semantics, ext-commons cast.rs)
    clamped = jnp.clip(data, lo, hi)
    out = jnp.where(jnp.isnan(data), 0, clamped).astype(target.jnp_dtype())
    return Column(target, out, valid)


def _int_to_decimal(data: Array, valid, target: DataType) -> Column:
    mul = 10 ** target.scale
    out = data * mul
    bound = 10 ** target.precision
    overflow = (jnp.abs(out) >= bound) | (data != out // mul)  # mul overflow
    return Column(target, jnp.where(overflow, 0, out), _and_valid(valid, ~overflow))


def _float_to_decimal(data: Array, valid, target: DataType) -> Column:
    scaled = data.astype(jnp.float64) * (10.0 ** target.scale)
    # HALF_UP
    rounded = jnp.where(scaled >= 0, jnp.floor(scaled + 0.5), jnp.ceil(scaled - 0.5))
    bound = float(10 ** target.precision)
    bad = jnp.isnan(scaled) | (jnp.abs(rounded) >= bound)
    out = jnp.where(bad, 0.0, rounded).astype(jnp.int64)
    return Column(target, out, _and_valid(valid, ~bad))


def _decimal_rescale(data: Array, valid, src: DataType, target: DataType) -> Column:
    ds = target.scale - src.scale
    if ds >= 0:
        out = data * (10 ** ds)
        ok = (out // (10 ** ds)) == data if ds > 0 else jnp.ones_like(data, jnp.bool_)
    else:
        div = 10 ** (-ds)
        q = jnp.abs(data) // div
        r = jnp.abs(data) % div
        q = q + jnp.where(2 * r >= div, 1, 0)  # HALF_UP on magnitude
        out = jnp.sign(data) * q
        ok = jnp.ones_like(data, jnp.bool_)
    bound = 10 ** min(target.precision, 18)
    ok = ok & (jnp.abs(out) < bound)
    return Column(target, jnp.where(ok, out, 0), _and_valid(valid, ok))


def check_overflow(col: Column, precision: int, scale: int) -> Column:
    """Ref proto CheckOverflow: null out values exceeding precision."""
    target = DataType(TypeKind.DECIMAL, precision=precision, scale=scale)
    if col.dtype.wide_decimal or target.wide_decimal:
        from blaze_tpu.exprs import wide_decimal as W

        return W.check_overflow(col, precision, scale, target)
    bound = 10 ** min(precision, 18)
    ok = jnp.abs(col.data) < bound
    return Column(target,
                  jnp.where(ok, col.data, 0), _and_valid(col.validity, ok))


def _and_valid(valid, extra):
    return extra if valid is None else (valid & extra)


# ---- string parsing (device) ----

def _trimmed(s: StringData):
    """start index and length after trimming ASCII spaces."""
    j = jnp.arange(s.width, dtype=jnp.int32)
    in_len = j[None, :] < s.lengths[:, None]
    nonspace = in_len & (s.bytes != 0x20)
    any_ns = jnp.any(nonspace, axis=1)
    first = jnp.argmax(nonspace, axis=1).astype(jnp.int32)
    last = (s.width - 1 - jnp.argmax(nonspace[:, ::-1], axis=1)).astype(jnp.int32)
    start = jnp.where(any_ns, first, 0)
    length = jnp.where(any_ns, last + 1 - first, 0)
    return start, length


def _parse_int64(s: StringData):
    """(value, ok): optional sign + digits; overflow or junk -> not ok."""
    start, length = _trimmed(s)
    j = jnp.arange(s.width, dtype=jnp.int32)
    idx = jnp.clip(start[:, None] + j[None, :], 0, s.width - 1)
    b = jnp.take_along_axis(s.bytes, idx, axis=1)
    first = b[:, 0]
    neg = first == 0x2D
    has_sign = neg | (first == 0x2B)
    ndigits = length - has_sign.astype(jnp.int32)

    acc = jnp.zeros((s.capacity,), jnp.int64)
    ok = (ndigits > 0) & (ndigits <= 19)
    overflow = jnp.zeros((s.capacity,), jnp.bool_)
    for pos in range(min(s.width, 20)):
        p = pos + has_sign.astype(jnp.int32)
        c = jnp.take_along_axis(b, jnp.clip(p, 0, s.width - 1)[:, None], axis=1)[:, 0]
        in_num = pos < ndigits
        is_digit = (c >= 0x30) & (c <= 0x39)
        ok = ok & (~in_num | is_digit)
        new_acc = acc * 10 + jnp.where(in_num, (c - 0x30).astype(jnp.int64), 0)
        overflow = overflow | (in_num & (new_acc < acc) & (acc > 0))
        acc = jnp.where(in_num, new_acc, acc)
    # values longer than width can't be digits-complete
    ok = ok & (ndigits <= s.width) & ~overflow
    val = jnp.where(neg, -acc, acc)
    return val, ok


def _parse_float64(s: StringData):
    """(value, ok): [+-]digits[.digits][eE[+-]digits]."""
    start, length = _trimmed(s)
    j = jnp.arange(s.width, dtype=jnp.int32)
    idx = jnp.clip(start[:, None] + j[None, :], 0, s.width - 1)
    b = jnp.take_along_axis(s.bytes, idx, axis=1)
    in_len = j[None, :] < length[:, None]

    is_digit = (b >= 0x30) & (b <= 0x39) & in_len
    is_dot = (b == 0x2E) & in_len
    is_e = ((b == 0x65) | (b == 0x45)) & in_len
    is_sign = ((b == 0x2B) | (b == 0x2D)) & in_len

    # locate 'e' (first occurrence) and '.' before e
    has_e = jnp.any(is_e, axis=1)
    e_pos = jnp.where(has_e, jnp.argmax(is_e, axis=1).astype(jnp.int32), length)
    before_e = j[None, :] < e_pos[:, None]
    dot_in_mant = is_dot & before_e
    has_dot = jnp.any(dot_in_mant, axis=1)
    dot_pos = jnp.where(has_dot, jnp.argmax(dot_in_mant, axis=1).astype(jnp.int32), e_pos)

    neg = (b[:, 0] == 0x2D) & in_len[:, 0]
    msign = ((b[:, 0] == 0x2B) | (b[:, 0] == 0x2D)) & in_len[:, 0]
    mstart = msign.astype(jnp.int32)

    # mantissa digits: positions in [mstart, e_pos) except dot_pos
    mant = jnp.zeros((s.capacity,), jnp.float64)
    frac_digits = jnp.zeros((s.capacity,), jnp.int32)
    valid_chars = jnp.ones((s.capacity,), jnp.bool_)
    for pos in range(s.width):
        here = (pos >= mstart) & (pos < e_pos) & in_len[:, pos]
        d = here & is_digit[:, pos]
        dot_here = here & (pos == dot_pos) & has_dot
        valid_chars = valid_chars & (~here | d | dot_here)
        mant = jnp.where(d, mant * 10 + (b[:, pos] - 0x30).astype(jnp.float64), mant)
        frac_digits = frac_digits + jnp.where(d & (pos > dot_pos) & has_dot, 1, 0)
    any_mant_digit = jnp.any(is_digit & (j[None, :] < e_pos[:, None]), axis=1)

    # exponent
    es_start = e_pos + 1
    esign_b = jnp.take_along_axis(b, jnp.clip(es_start, 0, s.width - 1)[:, None], axis=1)[:, 0]
    eneg = has_e & (esign_b == 0x2D)
    e_has_sign = has_e & ((esign_b == 0x2B) | (esign_b == 0x2D))
    ed_start = es_start + e_has_sign.astype(jnp.int32)
    exp = jnp.zeros((s.capacity,), jnp.int32)
    any_exp_digit = jnp.zeros((s.capacity,), jnp.bool_)
    for pos in range(s.width):
        here = has_e & (pos >= ed_start) & (pos < length) & in_len[:, pos]
        d = here & is_digit[:, pos]
        valid_chars = valid_chars & (~here | d)
        exp = jnp.where(d, jnp.minimum(exp * 10 + (b[:, pos] - 0x30), 400), exp)
        any_exp_digit = any_exp_digit | d
    exp = jnp.where(eneg, -exp, exp).astype(jnp.float64)

    ok = (length > 0) & valid_chars & any_mant_digit & (~has_e | any_exp_digit)
    val = mant * jnp.power(10.0, exp - frac_digits.astype(jnp.float64))
    val = jnp.where(neg, -val, val)
    return val, ok


def _from_string(col: Column, target: DataType) -> Column:
    s: StringData = col.data
    tk = target.kind
    if tk == TypeKind.DATE:
        return _string_to_date(col)
    if target.is_integral:
        val, ok = _parse_int64(s)
        lo, hi = _INT_BOUNDS[tk]
        ok = ok & (val >= lo) & (val <= hi)
        return Column(target, jnp.where(ok, val, 0).astype(target.jnp_dtype()),
                      _and_valid(col.validity, ok))
    if target.is_floating:
        val, ok = _parse_float64(s)
        return Column(target, jnp.where(ok, val, 0.0).astype(target.jnp_dtype()),
                      _and_valid(col.validity, ok))
    if target.is_decimal:
        val, ok = _parse_float64(s)
        c = _float_to_decimal(jnp.where(ok, val, 0.0), _and_valid(col.validity, ok), target)
        return c
    if tk == TypeKind.BOOLEAN:
        from blaze_tpu.exprs import strings as S

        low = S.lower_ascii(s)
        truthy = jnp.zeros((col.capacity,), jnp.bool_)
        falsy = jnp.zeros((col.capacity,), jnp.bool_)
        for t in (b"true", b"t", b"yes", b"y", b"1"):
            truthy = truthy | S.equals(low, _const_string(t, col.capacity, s.width))
        for f in (b"false", b"f", b"no", b"n", b"0"):
            falsy = falsy | S.equals(low, _const_string(f, col.capacity, s.width))
        ok = truthy | falsy
        return Column(target, truthy, _and_valid(col.validity, ok))
    if tk == TypeKind.TIMESTAMP:
        raise TypeError("string->timestamp not yet device-native")
    raise TypeError(f"unsupported cast string -> {target}")


def _string_to_date(col: Column) -> Column:
    """Parse yyyy-[m]m-[d]d (also bare yyyy / yyyy-mm) -> days since epoch."""
    s: StringData = col.data
    start, length = _trimmed(s)
    j = jnp.arange(s.width, dtype=jnp.int32)
    idx = jnp.clip(start[:, None] + j[None, :], 0, s.width - 1)
    b = jnp.take_along_axis(s.bytes, idx, axis=1)
    in_len = j[None, :] < length[:, None]
    is_digit = (b >= 0x30) & (b <= 0x39)
    is_dash = (b == 0x2D)

    # split on dashes into up to 3 numeric parts
    part = jnp.cumsum(jnp.where(is_dash & in_len, 1, 0), axis=1)
    part = jnp.concatenate([jnp.zeros((s.capacity, 1), part.dtype), part[:, :-1]], axis=1)
    vals = jnp.zeros((s.capacity, 3), jnp.int32)
    counts = jnp.zeros((s.capacity, 3), jnp.int32)
    ok = jnp.ones((s.capacity,), jnp.bool_)
    for pos in range(s.width):
        here = in_len[:, pos]
        p = jnp.clip(part[:, pos], 0, 2)
        d = here & is_digit[:, pos]
        dash = here & is_dash[:, pos]
        ok = ok & (~here | d | dash) & (~here | (part[:, pos] <= 2))
        onehot = jax.nn.one_hot(p, 3, dtype=jnp.int32)
        digit = (b[:, pos] - 0x30).astype(jnp.int32)
        vals = jnp.where(d[:, None],
                         vals * jnp.where(onehot == 1, 10, 1) + onehot * digit[:, None],
                         vals)
        counts = counts + jnp.where(d[:, None], onehot, 0)
    nparts = jnp.clip(jnp.max(jnp.where(in_len, part, 0), axis=1), 0, 2) + 1
    year, month, day = vals[:, 0], vals[:, 1], vals[:, 2]
    month = jnp.where(nparts >= 2, month, 1)
    day = jnp.where(nparts >= 3, day, 1)
    ok = ok & (length > 0) & (counts[:, 0] >= 1) & (counts[:, 0] <= 4)
    ok = ok & ((nparts < 2) | (counts[:, 1] >= 1)) & ((nparts < 3) | (counts[:, 2] >= 1))
    ok = ok & (month >= 1) & (month <= 12) & (day >= 1) & (day <= 31)
    days = days_from_civil(year, month, day)
    from blaze_tpu.columnar.types import DATE

    return Column(DATE, jnp.where(ok, days, 0).astype(jnp.int32),
                  _and_valid(col.validity, ok))


def days_from_civil(y: Array, m: Array, d: Array) -> Array:
    """Howard Hinnant's algorithm; vectorized integer math."""
    y = y.astype(jnp.int64)
    m = m.astype(jnp.int64)
    d = d.astype(jnp.int64)
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def civil_from_days(z: Array):
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _const_string(value: bytes, cap: int, min_width: int = 4) -> StringData:
    import numpy as np

    w = bucket_width(max(len(value), 1))
    w = max(w, min_width if min_width % 4 == 0 else bucket_width(min_width))
    mat = np.zeros((cap, w), np.uint8)
    if value:
        mat[:, : len(value)] = np.frombuffer(value, np.uint8)
    lens = np.full((cap,), len(value), np.int32)
    return StringData(jnp.asarray(mat), jnp.asarray(lens))


# ---- number -> string (device digit formatting) ----

def _int_to_string(data: Array, valid, capacity: int) -> Column:
    """int64 -> decimal digits. Width 20 covers -9223372036854775808."""
    from blaze_tpu.columnar.types import STRING

    v = data.astype(jnp.int64)
    neg = v < 0
    # abs in unsigned space to handle INT64_MIN
    mag = jnp.where(neg, (-(v + 1)).astype(jnp.uint64) + 1, v.astype(jnp.uint64))
    W = 20
    digits = []
    rem = mag
    for _ in range(W):
        digits.append((rem % 10).astype(jnp.uint8))
        rem = rem // 10
    digit_mat = jnp.stack(digits[::-1], axis=1)  # most significant first
    ndig = jnp.maximum(
        W - jnp.argmax(digit_mat != 0, axis=1).astype(jnp.int32),
        1)
    ndig = jnp.where(mag == 0, 1, ndig)
    total = ndig + neg.astype(jnp.int32)
    w = bucket_width(W + 1)
    j = jnp.arange(w, dtype=jnp.int32)
    # output char j: '-' at 0 if neg; digit index = W - ndig + (j - neg)
    src = W - ndig[:, None] + j[None, :] - neg.astype(jnp.int32)[:, None]
    dig = jnp.take_along_axis(digit_mat, jnp.clip(src, 0, W - 1), axis=1) + 0x30
    out = jnp.where(neg[:, None] & (j[None, :] == 0), jnp.uint8(0x2D), dig.astype(jnp.uint8))
    mask = j[None, :] < total[:, None]
    return Column(STRING, StringData(jnp.where(mask, out, jnp.uint8(0)), total), valid)


def _to_string(col: Column, target: DataType) -> Column:
    k = col.dtype.kind
    if col.dtype.is_integral or k == TypeKind.BOOLEAN:
        if k == TypeKind.BOOLEAN:
            # spark: 'true' / 'false'
            from blaze_tpu.exprs import strings as S

            t = _const_string(b"true", col.capacity)
            f = _const_string(b"false", col.capacity)
            t, f = S.common_width(t, f)
            bts = jnp.where(col.data[:, None], t.bytes, f.bytes)
            lens = jnp.where(col.data, t.lengths, f.lengths)
            return Column(target, StringData(bts, lens), col.validity)
        return _int_to_string(col.data, col.validity, col.capacity)
    if k == TypeKind.DATE:
        return _date_to_string(col, target)
    raise TypeError(f"cast {col.dtype} -> string not yet device-native")


def _date_to_string(col: Column, target: DataType) -> Column:
    y, m, d = civil_from_days(col.data)
    w = bucket_width(10)
    cap = col.capacity
    chars = []
    for div in (1000, 100, 10, 1):
        chars.append((jnp.clip(y, 0, 9999) // div % 10 + 0x30).astype(jnp.uint8))
    chars.append(jnp.full((cap,), 0x2D, jnp.uint8))
    chars.append((m // 10 + 0x30).astype(jnp.uint8))
    chars.append((m % 10 + 0x30).astype(jnp.uint8))
    chars.append(jnp.full((cap,), 0x2D, jnp.uint8))
    chars.append((d // 10 + 0x30).astype(jnp.uint8))
    chars.append((d % 10 + 0x30).astype(jnp.uint8))
    mat = jnp.stack(chars, axis=1)
    pad = jnp.zeros((cap, w - 10), jnp.uint8)
    return Column(target, StringData(jnp.concatenate([mat, pad], axis=1),
                                     jnp.full((cap,), 10, jnp.int32)), col.validity)
