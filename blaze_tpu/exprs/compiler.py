"""Expression compiler: IR -> jax column functions.

Ref analog: the physical-expression construction in from_proto.rs (lib.rs:
191-535) + CachedExprsEvaluator (datafusion-ext-plans common/
cached_exprs_evaluator.rs). Unlike the reference we do no explicit common-
subexpression elimination or short-circuiting: everything traces into one XLA
program where CSE is automatic and both branches of a select are data-flow
(no branch cost on a vector machine — "short-circuit" SC_AND/SC_OR exists in
the reference to skip expensive UDFs, which run on the host path here anyway).

A compiled expression is `fn(batch: ColumnBatch) -> Column`; null semantics
are Spark's (strict nulls for most ops, Kleene AND/OR, null-prop selects).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from blaze_tpu.columnar.batch import Column, ColumnBatch, StringData
from blaze_tpu.columnar.types import (BOOLEAN, DataType, FLOAT64, INT64,
    TypeKind)
from blaze_tpu.exprs import ir
from blaze_tpu.exprs import strings as S
from blaze_tpu.exprs.cast import cast_column, check_overflow, _const_string, _and_valid

CompiledExpr = Callable[[ColumnBatch], Column]

# ---------------------------------------------------------------------------
# common-subexpression elimination (ref cached_exprs_evaluator.rs:38-60).
# XLA CSEs identical subgraphs AFTER tracing; this memo removes the
# TRACE-TIME cost (and the eager-path re-evaluation cost for unjitted
# host-fn chains): within one cse_scope — one batch flowing through one
# fused chain — each distinct expression key evaluates once.
# ---------------------------------------------------------------------------

import contextlib
import threading

_cse_tls = threading.local()


@contextlib.contextmanager
def cse_scope():
    prev = getattr(_cse_tls, "memo", None)
    _cse_tls.memo = {}
    try:
        yield
    finally:
        _cse_tls.memo = prev


def compile_expr(expr: ir.Expr, schema) -> CompiledExpr:
    """Bind + lower an expression against an input schema (with CSE when
    evaluated inside a cse_scope)."""
    inner = _compile_expr(expr, schema)
    key = ("cse", expr.key())

    def run(b: ColumnBatch) -> Column:
        memo = getattr(_cse_tls, "memo", None)
        if memo is None:
            return inner(b)
        # the entry RETAINS the batch: keying by id() alone would let a
        # freed batch's address be recycled within the scope and serve a
        # stale Column for the new object
        bkey = (id(b),) + key
        hit = memo.get(bkey)
        if hit is None:
            hit = (b, inner(b))
            memo[bkey] = hit
        return hit[1]

    return run


def _compile_expr(expr: ir.Expr, schema) -> CompiledExpr:
    """Bind + lower an expression against an input schema."""
    if isinstance(expr, ir.Col):
        idx = schema.index_of(expr.name)
        return lambda b: b.columns[idx]
    if isinstance(expr, ir.BoundRef):
        idx = expr.index
        return lambda b: b.columns[idx]
    if isinstance(expr, ir.Literal):
        return _compile_literal(expr)
    if isinstance(expr, ir.Binary):
        return _compile_binary(expr, schema)
    if isinstance(expr, ir.Not):
        c = compile_expr(expr.child, schema)
        return lambda b: _map_col(c(b), BOOLEAN, lambda d: ~d)
    if isinstance(expr, ir.Negate):
        c = compile_expr(expr.child, schema)

        def run_neg(b):
            col = c(b)
            if col.dtype.wide_decimal:
                from blaze_tpu.exprs import wide_decimal as W

                return W.negate(col)
            return Column(col.dtype, -col.data, col.validity)

        return run_neg
    if isinstance(expr, ir.IsNull):
        c = compile_expr(expr.child, schema)
        return lambda b: Column(BOOLEAN, ~c(b).valid_mask(), None)
    if isinstance(expr, ir.IsNotNull):
        c = compile_expr(expr.child, schema)
        return lambda b: Column(BOOLEAN, c(b).valid_mask(), None)
    if isinstance(expr, ir.Cast):
        c = compile_expr(expr.child, schema)
        dt = expr.dtype
        return lambda b: cast_column(c(b), dt)
    if isinstance(expr, ir.If):
        return _compile_case(((expr.cond, expr.then),), expr.otherwise, schema)
    if isinstance(expr, ir.CaseWhen):
        return _compile_case(expr.branches, expr.otherwise, schema)
    if isinstance(expr, ir.InList):
        return _compile_inlist(expr, schema)
    if isinstance(expr, ir.StringPredicate):
        c = compile_expr(expr.child, schema)
        fn = {"starts_with": S.starts_with, "ends_with": S.ends_with,
              "contains": S.contains}[expr.op]
        pat = expr.pattern

        def run_pred(b):
            col = c(b)
            return Column(BOOLEAN, fn(col.data, pat), col.validity)

        return run_pred
    if isinstance(expr, ir.Like):
        c = compile_expr(expr.child, schema)
        pat, esc = expr.pattern, expr.escape

        def run_like(b):
            col = c(b)
            return Column(BOOLEAN, S.like_match(col.data, pat, esc), col.validity)

        return run_like
    if isinstance(expr, ir.ScalarFn):
        from blaze_tpu.exprs.functions import compile_function

        return compile_function(expr, schema)
    if isinstance(expr, ir.MakeDecimal):
        c = compile_expr(expr.child, schema)
        dt = DataType(TypeKind.DECIMAL, precision=expr.precision, scale=expr.scale)
        return lambda b: Column(dt, c(b).data.astype(jnp.int64), c(b).validity)
    if isinstance(expr, ir.UnscaledValue):
        c = compile_expr(expr.child, schema)
        return lambda b: Column(INT64, c(b).data.astype(jnp.int64), c(b).validity)
    if isinstance(expr, ir.CheckOverflow):
        c = compile_expr(expr.child, schema)
        p, s = expr.precision, expr.scale
        return lambda b: check_overflow(c(b), p, s)
    if isinstance(expr, ir.UdfWrapper):
        return _compile_udf_wrapper(expr, schema)
    if isinstance(expr, ir.ScalarSubquery):
        return _compile_scalar_subquery(expr)
    if isinstance(expr, ir.GetStructField):
        c = compile_expr(expr.child, schema)
        i = expr.index

        def run_gsf(b):
            col = c(b)
            child = col.data.children[i]
            v = _and_valid(col.validity, child.valid_mask()) \
                if (col.validity is not None or child.validity is not None) \
                else None
            return Column(child.dtype, child.data, v)

        return run_gsf
    if isinstance(expr, ir.GetIndexedField):
        return _compile_get_indexed(expr, schema)
    if isinstance(expr, ir.GetMapValue):
        return _compile_get_map_value(expr, schema)
    if isinstance(expr, ir.NamedStruct):
        val_fns = [compile_expr(v, schema) for v in expr.values]
        rt = expr.result_type

        def run_ns(b):
            from blaze_tpu.columnar.batch import StructData

            return Column(rt, StructData([fn(b) for fn in val_fns]), None)

        return run_ns
    raise NotImplementedError(f"cannot compile {type(expr).__name__}")


def _compile_get_indexed(expr: ir.GetIndexedField, schema) -> CompiledExpr:
    """spark GetArrayItem: 0-based element gather; negative or out-of-range
    index -> null (ref get_indexed_field.rs)."""
    c = compile_expr(expr.child, schema)
    # null index: i = -1 makes every row null while keeping the element
    # dtype (returning a null column of the INDEX dtype would corrupt the
    # output schema)
    i = -1 if expr.index.value is None else int(expr.index.value)

    def run(b: ColumnBatch) -> Column:
        col = c(b)
        ld = col.data
        lens = ld.lengths()
        ok = col.valid_mask() & (i >= 0) & (lens > i)
        src = jnp.clip(ld.offsets[:-1] + i, 0, ld.elements.capacity - 1)
        elem = ld.elements.take(jnp.where(ok, src, 0))
        return Column(elem.dtype, elem.data,
                      _and_valid(elem.validity, ok))

    return run


def _compile_get_map_value(expr: ir.GetMapValue, schema) -> CompiledExpr:
    """map[key]: match the literal key against each row's entries (stored as
    list<struct<key,value>>, types.storage_element) and gather the first
    match's value; absent -> null (ref get_map_value.rs)."""
    c = compile_expr(expr.child, schema)
    key_lit = expr.map_key

    def run(b: ColumnBatch) -> Column:
        import jax

        from blaze_tpu.ops.segment import element_rows

        mcol = c(b)
        ld = mcol.data
        entries = ld.elements.data  # StructData(key, value)
        kcol, vcol = entries.children
        ecap = kcol.capacity
        cap = mcol.capacity
        if key_lit.value is None:
            # map[NULL] is NULL for every row (spark strict-null lookup)
            return Column(vcol.dtype, vcol.take(jnp.zeros((cap,), jnp.int32)).data,
                          jnp.zeros((cap,), jnp.bool_))
        slot, row, _, in_row = element_rows(ld.offsets, cap, ecap)
        in_row = in_row & (slot >= ld.offsets[row])
        lit_col = _compile_literal(
            ir.Literal(key_lit.dtype, key_lit.value))
        # build a capacity-ecap batch to evaluate the literal against
        kmatch = _equal_values(kcol, lit_col, ecap)
        hit = in_row & kmatch & kcol.valid_mask()
        # first matching entry per row
        idx = jax.ops.segment_min(
            jnp.where(hit, slot, jnp.int32(ecap)),
            jnp.where(hit, row, jnp.int32(cap)), num_segments=cap)
        ok = (idx < ecap) & mcol.valid_mask()
        val = vcol.take(jnp.clip(idx, 0, ecap - 1))
        return Column(vcol.dtype, val.data, _and_valid(val.validity, ok))

    return run


def _equal_values(col: Column, lit_fn, cap: int):
    """Row-wise equality of a column against a literal value."""
    class _FakeBatch:
        capacity = cap

        def row_mask(self):
            return jnp.ones((cap,), jnp.bool_)

    lit_col = lit_fn(_FakeBatch())
    if col.is_string:
        return S.equals(col.data, lit_col.data)
    return col.data == lit_col.data


def _compile_udf_wrapper(expr: ir.UdfWrapper, schema) -> CompiledExpr:
    """Host-callback evaluation of an engine-external expression.

    Ref: SparkUDFWrapperExpr (spark_udf_wrapper.rs) — natively-computed
    param columns cross to the embedding layer, which evaluates the
    serialized expression row-by-row and returns the result array
    (SparkUDFWrapperContext.scala:63-111). The crossing here is
    jax.pure_callback, so the surrounding pipeline stays one jit program.
    The registered resource is `fn(*param_numpy_arrays, num_rows) ->
    (values ndarray, validity ndarray|None)`.
    """
    import jax

    from blaze_tpu.runtime import resources as _res

    param_fns = [compile_expr(p, schema) for p in expr.params]
    rt = expr.return_type
    if rt.is_string_like or rt.kind in (TypeKind.LIST, TypeKind.MAP,
                                        TypeKind.STRUCT):
        raise NotImplementedError(
            f"udf wrapper return type {rt} not yet supported")
    rid = expr.resource_id

    def run(b: ColumnBatch) -> Column:
        params = [fn(b) for fn in param_fns]
        host_args = []
        for p in params:
            if p.is_string:
                host_args += [p.data.bytes, p.data.lengths]
            else:
                host_args.append(p.data)
            host_args.append(p.valid_mask())
        host_args.append(b.num_rows)

        def callback(*arrs):
            fn = _res.get(rid)
            vals, validity = fn(*[np.asarray(a) for a in arrs])
            out_v = np.zeros((b.capacity,), rt.np_dtype())
            out_ok = np.zeros((b.capacity,), bool)
            n = min(len(vals), b.capacity)
            out_v[:n] = np.asarray(vals)[:n]
            out_ok[:n] = (np.ones(n, bool) if validity is None
                          else np.asarray(validity)[:n])
            return out_v, out_ok

        from blaze_tpu.exprs.hostfns import host_apply

        out_shape = (jax.ShapeDtypeStruct((b.capacity,), rt.np_dtype()),
                     jax.ShapeDtypeStruct((b.capacity,), np.bool_))
        vals, ok = host_apply(callback, out_shape, *host_args)
        validity = ok & b.row_mask() if expr.nullable else None
        return Column(rt, vals, validity)

    return run


def _compile_scalar_subquery(expr: ir.ScalarSubquery) -> CompiledExpr:
    """Ref: SparkScalarSubqueryWrapperExpr — the provider resource returns
    the (python) scalar on first evaluation; it becomes a literal column."""
    from blaze_tpu.runtime import resources as _res

    def run(b: ColumnBatch) -> Column:
        value = _res.get(expr.resource_id)()
        return _compile_literal(ir.Literal(expr.return_type, value))(b)

    return run


def _compile_literal(expr: ir.Literal) -> CompiledExpr:
    dt, v = expr.dtype, expr.value

    def run(b: ColumnBatch) -> Column:
        cap = b.capacity
        if v is None:
            from blaze_tpu.columnar.batch import _zero_column

            z = _zero_column(dt if not dt.is_string_like else dt, cap)
            return Column(dt, z.data, jnp.zeros((cap,), jnp.bool_))
        if dt.is_string_like:
            raw = v.encode() if isinstance(v, str) else bytes(v)
            return Column(dt, _const_string(raw, cap), None)
        if dt.kind == TypeKind.BOOLEAN:
            return Column(dt, jnp.full((cap,), bool(v)), None)
        if dt.wide_decimal:
            from blaze_tpu.columnar import int128 as i128
            from blaze_tpu.exprs import wide_decimal as W

            hi, lo = i128.np_from_ints([int(v)])
            return W.build(dt, jnp.full((cap,), hi[0], jnp.int64),
                           jnp.full((cap,), lo[0], jnp.int64), None)
        return Column(dt, jnp.full((cap,), v, dt.jnp_dtype()), None)

    return run


def _map_col(col: Column, dtype: DataType, fn) -> Column:
    return Column(dtype, fn(col.data), col.validity)


_CMP = {ir.BinOp.EQ, ir.BinOp.NEQ, ir.BinOp.LT, ir.BinOp.LE, ir.BinOp.GT,
        ir.BinOp.GE, ir.BinOp.EQ_NULLSAFE}


def _compile_binary(expr: ir.Binary, schema) -> CompiledExpr:
    lf = compile_expr(expr.left, schema)
    rf = compile_expr(expr.right, schema)
    op = expr.op

    if op in (ir.BinOp.AND, ir.BinOp.OR):
        return _compile_kleene(lf, rf, op)
    if op in _CMP:
        return lambda b: _compare(lf(b), rf(b), op)

    rt = expr.result_type

    def run(b: ColumnBatch) -> Column:
        lc, rc = lf(b), rf(b)
        return _arith(lc, rc, op, rt)

    return run


def _compare(lc: Column, rc: Column, op: ir.BinOp) -> Column:
    if lc.dtype.wide_decimal or rc.dtype.wide_decimal:
        from blaze_tpu.exprs import wide_decimal as W

        lt, eq, gt = W.compare(lc, rc)
    elif lc.is_string or rc.is_string:
        lt, eq = S.compare(lc.data, rc.data)
        gt = ~lt & ~eq
    else:
        ld, rd = _promote(lc, rc)
        lt, eq, gt = ld < rd, ld == rd, ld > rd
    res = {
        ir.BinOp.EQ: eq, ir.BinOp.NEQ: ~eq, ir.BinOp.LT: lt,
        ir.BinOp.LE: lt | eq, ir.BinOp.GT: gt, ir.BinOp.GE: gt | eq,
        ir.BinOp.EQ_NULLSAFE: eq,
    }[op]
    lv, rv = lc.valid_mask(), rc.valid_mask()
    if op == ir.BinOp.EQ_NULLSAFE:
        both_null = ~lv & ~rv
        return Column(BOOLEAN, both_null | (lv & rv & res), None)
    return Column(BOOLEAN, res, _strict(lc, rc))


def _strict(*cols: Column):
    v = None
    for c in cols:
        v = c.validity if v is None else (v if c.validity is None else (v & c.validity))
    return v


def _promote(lc: Column, rc: Column):
    ld, rd = lc.data, rc.data
    if ld.dtype != rd.dtype:
        target = jnp.promote_types(ld.dtype, rd.dtype)
        ld, rd = ld.astype(target), rd.astype(target)
    return ld, rd


def _compile_kleene(lf, rf, op) -> CompiledExpr:
    def run(b: ColumnBatch) -> Column:
        lc, rc = lf(b), rf(b)
        lv, rv = lc.valid_mask(), rc.valid_mask()
        ld = lc.data & lv if lc.validity is not None else lc.data
        rd = rc.data & rv if rc.validity is not None else rc.data
        lt, rt_ = ld.astype(jnp.bool_), rd.astype(jnp.bool_)
        if op == ir.BinOp.AND:
            val = lt & rt_
            # false & anything = false (valid); else null if either null
            valid = (lv & rv) | (lv & ~lt) | (rv & ~rt_)
        else:
            val = lt | rt_
            valid = (lv & rv) | (lv & lt) | (rv & rt_)
        if lc.validity is None and rc.validity is None:
            return Column(BOOLEAN, val, None)
        return Column(BOOLEAN, val & valid, valid)

    return run


def _arith(lc: Column, rc: Column, op: ir.BinOp, result_type: Optional[DataType]) -> Column:
    validity = _strict(lc, rc)
    if lc.dtype.is_decimal or rc.dtype.is_decimal:
        return _decimal_arith(lc, rc, op, result_type, validity)

    ld, rd = _promote(lc, rc)
    out_dt = result_type or (lc.dtype if lc.dtype.is_numeric else rc.dtype)
    if op == ir.BinOp.ADD:
        return Column(out_dt, ld + rd, validity)
    if op == ir.BinOp.SUB:
        return Column(out_dt, ld - rd, validity)
    if op == ir.BinOp.MUL:
        return Column(out_dt, ld * rd, validity)
    if op == ir.BinOp.DIV:
        if lc.dtype.is_integral and rc.dtype.is_integral:
            ld = ld.astype(jnp.float64)
            rd = rd.astype(jnp.float64)
            out_dt = result_type or FLOAT64
        zero = rd == 0
        res = ld / jnp.where(zero, 1, rd)
        return Column(out_dt, jnp.where(zero, 0, res), _and_valid(validity, ~zero))
    if op == ir.BinOp.MOD:
        zero = rd == 0
        safe = jnp.where(zero, 1, rd)
        # spark/java remainder: sign follows dividend
        res = ld - jnp.trunc(ld / safe) * safe if lc.dtype.is_floating else (
            jnp.sign(ld) * (jnp.abs(ld) % jnp.abs(safe)))
        return Column(out_dt, jnp.where(zero, 0, res), _and_valid(validity, ~zero))
    if op == ir.BinOp.BIT_AND:
        return Column(out_dt, ld & rd, validity)
    if op == ir.BinOp.BIT_OR:
        return Column(out_dt, ld | rd, validity)
    if op == ir.BinOp.BIT_XOR:
        return Column(out_dt, ld ^ rd, validity)
    if op == ir.BinOp.SHIFT_LEFT:
        return Column(out_dt, ld << rd, validity)
    if op == ir.BinOp.SHIFT_RIGHT:
        return Column(out_dt, ld >> rd, validity)
    raise NotImplementedError(f"arith op {op}")


def _decimal_arith(lc: Column, rc: Column, op: ir.BinOp,
                   result_type: Optional[DataType], validity) -> Column:
    """Unscaled int64 decimal arithmetic (ref NativeConverters.scala:599-676
    decimal special cases; plan supplies the result precision/scale)."""
    if (lc.dtype.wide_decimal or rc.dtype.wide_decimal
            or (result_type is not None and result_type.wide_decimal)):
        from blaze_tpu.exprs import wide_decimal as W

        if result_type is None or not result_type.is_decimal:
            raise NotImplementedError(
                "wide decimal arithmetic needs a planned result type")
        return W.arith(lc, rc, op, result_type, validity)
    ls = lc.dtype.scale if lc.dtype.is_decimal else 0
    rs = rc.dtype.scale if rc.dtype.is_decimal else 0
    ld = lc.data.astype(jnp.int64)
    rd = rc.data.astype(jnp.int64)
    if result_type is None or not result_type.is_decimal:
        # fall back to a plausible result type
        if op in (ir.BinOp.ADD, ir.BinOp.SUB):
            scale = max(ls, rs)
        elif op == ir.BinOp.MUL:
            scale = ls + rs
        else:
            scale = max(6, ls + rs + 1)
        prec = 18
        result_type = DataType(TypeKind.DECIMAL, precision=prec, scale=scale)
    out_s = result_type.scale
    if op in (ir.BinOp.ADD, ir.BinOp.SUB):
        lu = ld * (10 ** max(out_s - ls, 0))
        ru = rd * (10 ** max(out_s - rs, 0))
        res = lu + ru if op == ir.BinOp.ADD else lu - ru
        return Column(result_type, res, validity)
    if op == ir.BinOp.MUL:
        prod = ld * rd  # scale ls+rs
        ds = out_s - (ls + rs)
        if ds >= 0:
            return Column(result_type, prod * (10 ** ds), validity)
        div = 10 ** (-ds)
        q = jnp.abs(prod) // div
        r = jnp.abs(prod) % div
        q = q + (2 * r >= div)
        return Column(result_type, jnp.sign(prod) * q, validity)
    if op == ir.BinOp.DIV:
        zero = rd == 0
        safe = jnp.where(zero, 1, rd)
        # q = l / r scaled to out_s: (ld * 10^(out_s + rs - ls)) / rd, HALF_UP
        shift = out_s + rs - ls
        num = ld * (10 ** max(shift, 0))
        den = safe * (10 ** max(-shift, 0))
        q = jnp.abs(num) // jnp.abs(den)
        r = jnp.abs(num) % jnp.abs(den)
        q = q + (2 * r >= jnp.abs(den))
        res = jnp.sign(num) * jnp.sign(den) * q
        return Column(result_type, jnp.where(zero, 0, res), _and_valid(validity, ~zero))
    raise NotImplementedError(f"decimal op {op}")


def _compile_case(branches, otherwise, schema) -> CompiledExpr:
    conds = [compile_expr(c, schema) for c, _ in branches]
    vals = [compile_expr(v, schema) for _, v in branches]
    other = compile_expr(otherwise, schema) if otherwise is not None else None

    def run(b: ColumnBatch) -> Column:
        vcols = [f(b) for f in vals]
        ocol = other(b) if other is not None else None
        all_vals = vcols + ([ocol] if ocol is not None else [])
        out_dtype = all_vals[0].dtype

        is_str = all_vals[0].is_string
        if is_str:
            w = max(v.data.width for v in all_vals)
            all_vals = [Column(v.dtype, S.ensure_width(v.data, w), v.validity)
                        for v in all_vals]
            vcols = all_vals[: len(vcols)]
            ocol = all_vals[-1] if ocol is not None else None

        # start from else branch (or null), then apply branches so that
        # earlier (higher-priority) branches win via the `taken` mask
        if ocol is not None:
            acc_data, acc_valid = ocol.data, ocol.valid_mask()
        else:
            proto = all_vals[0]
            if is_str:
                acc_data = StringData(jnp.zeros_like(proto.data.bytes),
                                      jnp.zeros_like(proto.data.lengths))
            else:
                acc_data = jnp.zeros_like(proto.data)
            acc_valid = jnp.zeros((b.capacity,), jnp.bool_)
        taken = jnp.zeros((b.capacity,), jnp.bool_)
        for cf, vcol in zip(conds, vcols):
            ccol = cf(b)
            fire = ccol.data.astype(jnp.bool_) & ccol.valid_mask() & ~taken
            if is_str:
                acc_data = StringData(
                    jnp.where(fire[:, None], vcol.data.bytes, acc_data.bytes),
                    jnp.where(fire, vcol.data.lengths, acc_data.lengths))
            else:
                acc_data = jnp.where(fire, vcol.data, acc_data)
            acc_valid = jnp.where(fire, vcol.valid_mask(), acc_valid)
            taken = taken | fire
        return Column(out_dtype, acc_data, acc_valid)

    return run


def _compile_inlist(expr: ir.InList, schema) -> CompiledExpr:
    cf = compile_expr(expr.child, schema)
    lits = [compile_expr(v, schema) for v in expr.values]
    negated = expr.negated

    # Spark 3VL: `x IN (a, b, NULL)` is TRUE on a match, NULL when x is null
    # or the (unmatched) list contains a null, FALSE otherwise; NOT IN flips
    # the value and keeps nullness.
    has_null_lit = any(isinstance(v, ir.Literal) and v.value is None
                       for v in expr.values)

    def run(b: ColumnBatch) -> Column:
        ccol = cf(b)
        hit = jnp.zeros((b.capacity,), jnp.bool_)
        for lf in lits:
            lcol = lf(b)
            if ccol.is_string:
                eq = S.equals(ccol.data, lcol.data)
            else:
                ld, rd = _promote(ccol, lcol)
                eq = ld == rd
            hit = hit | (eq & lcol.valid_mask())
        res = ~hit if negated else hit
        if ccol.validity is None and not has_null_lit:
            return Column(BOOLEAN, res, None)
        valid = ccol.valid_mask()
        if has_null_lit:
            valid = valid & hit
        return Column(BOOLEAN, res, valid)

    return run
