"""Config/flag system — three tiers like the reference (SURVEY.md §5.6).

Ref: spark-extension BlazeConf.java (batchSize/memoryFraction/... read lazily
from native over JNI). Here the native side IS this process, so the conf is a
plain singleton the JVM bridge (or tests) can populate; defaults mirror the
reference's (BlazeConf.java:23-70) where semantics carry over, with
TPU-specific knobs added.

The ``KNOBS`` registry below is the SINGLE SOURCE OF TRUTH for every knob:
name, default, type, doc string, and env-var override live in one ``Knob``
declaration, and everything else derives from it — ``BlazeConf`` instances
are built from the registry, ``tools/blazelint``'s knob-registry checker
validates every ``conf.<name>`` access (and the README catalog) against it,
and ``knob_catalog_md()`` renders the README table. To add a knob: add one
``Knob(...)`` entry here, read it somewhere in the runtime, and document it
in README.md ("Configuration knobs") — `make check-lint` fails until all
three agree.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import threading
from typing import Any, Callable, Dict, Iterator, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared configuration knob.

    ``default_factory`` (mutable defaults: dicts) wins over ``default``;
    ``env`` names an environment variable consulted once at BlazeConf
    construction (the value is cast through ``type``).

    ``step``/``min``/``max`` are the autopilot actuation schedule: a knob
    that declares all three may be moved one bounded step at a time by
    runtime/autopilot.py (``geometric=True`` multiplies/divides by
    ``step`` instead of adding/subtracting it). Knobs without the triple
    are never actuated — blazelint's doctor-knob-sync rule enforces that
    every knob in autopilot.ACTUATORS declares it."""

    name: str
    default: Any = None
    doc: str = ""
    env: str = ""
    default_factory: Optional[Callable[[], Any]] = None
    step: Optional[float] = None
    min: Optional[float] = None
    max: Optional[float] = None
    geometric: bool = False

    @property
    def type(self) -> type:
        if self.default_factory is not None:
            return type(self.default_factory())
        return type(self.default)

    def resolve(self) -> Any:
        if self.env:
            raw = os.environ.get(self.env)
            if raw is not None:
                t = self.type
                if t is bool:
                    return raw.lower() in ("1", "true", "yes", "on")
                return t(raw)
        if self.default_factory is not None:
            return self.default_factory()
        return self.default

    def propose_step(self, current: Any, direction: int) -> Optional[Any]:
        """One bounded step from ``current`` in ``direction`` (+1/-1).

        Returns the clamped next value, or None when the knob declares
        no schedule or the clamp leaves the value unchanged (already
        pinned at the min/max rail)."""
        if self.step is None or self.min is None or self.max is None:
            return None
        if self.geometric:
            nxt = current * self.step if direction > 0 else current / self.step
        else:
            nxt = current + self.step * direction
        nxt = sorted((self.min, nxt, self.max))[1]
        if self.type is bool:
            # validate_overlay is strict on bool knobs — a proposed 0/1
            # int would be rejected at apply time
            nxt = bool(round(nxt))
        elif self.type is int:
            nxt = int(round(nxt))
        return None if nxt == current else nxt


_DECLARATIONS: Tuple[Knob, ...] = (
    # -- reference-equivalent knobs (BlazeConf.java) --
    Knob("batch_size", 8192,
         doc="Rows per batch; ref default 10000 — 8192 is TPU/XLA tile "
             "friendly."),
    Knob("enable_smj_inequality_join", False,
         doc="Allow sort-merge joins with inequality conditions."),
    Knob("enable_bhj_fallbacks_to_smj", True,
         doc="Fall back from broadcast-hash join to sort-merge join when "
             "the build side exceeds the thresholds below."),
    Knob("bhj_fallback_rows_threshold", 1_000_000,
         doc="Build-side row count above which BHJ falls back to SMJ."),
    Knob("bhj_fallback_mem_threshold", 128 << 20,
         doc="Build-side byte size above which BHJ falls back to SMJ."),
    Knob("enable_input_batch_statistics", False,
         doc="Per-operator input-batch byte/row statistics at every "
             "stream boundary (ref batch_statisitcs module)."),
    Knob("ignore_corrupt_files", False,
         doc="Skip unreadable/corrupt input files instead of failing the "
             "task."),

    # -- TPU-native knobs --
    Knob("min_capacity", 1024,
         doc="Smallest power-of-two capacity bucket: the jit cache is "
             "keyed on (plan, capacity, string-width), so padding to "
             "buckets bounds the number of compilations."),
    Knob("min_string_width", 4,
         doc="Smallest fixed string width (string columns are fixed-width "
             "uint8 matrices; width is bucketed like capacity)."),
    Knob("max_string_width", 4096,
         doc="Cap on the bucketed fixed string width."),
    Knob("memory_budget", 0,
         doc="HBM budget for MemManager in bytes; 0 = derive from device "
             "memory stats."),
    Knob("spill_dir", "/tmp/blaze_tpu_spill", env="BLAZE_TPU_SPILL_DIR",
         doc="Directory for host spill files (MemManager/SpillFile)."),
    Knob("zstd_level", 1,
         doc="Compression level for shuffle/spill/broadcast frames (ref "
             "uses zstd level 1; this build's frame codec is zlib at the "
             "same level knob)."),
    Knob("enable_stage_compiler", True,
         doc="Whole-stage single-dispatch compiler "
             "(runtime/stage_compiler.py): amortizes the ~90ms-per-"
             "dispatch cost of remote-attached TPUs."),
    Knob("dense_agg_range", 1 << 16,
         doc="Dense grouped-agg key range for the MXU one-hot path "
             "(<= 2^16: 256x256 byte decomposition); stages whose keys "
             "exceed it fall back.",
         step=2.0, min=1 << 12, max=1 << 22, geometric=True),
    Knob("float_sum_digit_planes", 6,
         doc="Precision policy for FLOAT sums on the MXU digit-plane "
             "path: 6 planes digitize to 46 bits (the TPU's emulated-f64 "
             "mantissa class). 5 is a documented perf opt-in (~14% fewer "
             "one-hot matmul FLOPs, ~2^-38 relative error); 7 is "
             "stricter. Int sums always use the exact 8-chunk int64 "
             "path."),
    Knob("spill_frame_rows", 1 << 16,
         doc="External-sort spill frame rows: merge cost is one dispatch "
             "trio per pooled frame, so bigger frames amortize the fixed "
             "per-dispatch overhead."),
    Knob("target_batch_bytes", 128 << 20,
         doc="Adaptive macro-batching target: batch sources size batches "
             "toward this many bytes, clamped by the memory budget "
             "(ops/common.adaptive_batch_rows).",
         step=2.0, min=16 << 10, max=1 << 30, geometric=True),
    Knob("max_batch_rows", 1 << 21,
         doc="Hard row cap on adaptive macro-batches."),
    Knob("aqe_broadcast_threshold", 10 << 20,
         doc="AQE dynamic join selection: a planned SMJ whose shuffled "
             "input came in under this many bytes becomes a broadcast "
             "join (Spark autoBroadcastJoinThreshold analog; 0 "
             "disables)."),
    Knob("enable_compile_canonicalization", True,
         doc="Compile-service shape canonicalization: above "
             "canonical_pow2_limit, power-of-two capacity buckets "
             "collapse onto power-of-four rungs, halving the large end "
             "of the compiled-program shape space."),
    Knob("canonical_pow2_limit", 1 << 14,
         doc="Capacity above which canonicalization switches to "
             "power-of-four rungs."),
    Knob("profiler_dir", "", env="BLAZE_TPU_PROFILE_DIR",
         doc="JAX profiler trace output dir ('' disables) — consumed by "
             "trace.profiled_span (jax.profiler TensorBoard captures "
             "recorded as 'profile' spans in the engine trace)."),

    # -- continuous sampling profiler (runtime/profiler.py) --
    Knob("profile_enabled", False, env="BLAZE_TPU_PROFILE",
         doc="Always-on wall-clock sampling profiler: a daemon thread "
             "samples every live thread's stack (sys._current_frames) "
             "each profile_sample_ms and folds it into a bounded "
             "aggregated table attributed to (query, stage, task, "
             "tenant) via the thread-local trace context; pooled "
             "executors ship folded-stack deltas driver-ward on the "
             "telemetry frames (sidecar-recoverable). Off (default) "
             "every profiler hook is one truthiness check and no "
             "sampler thread exists."),
    Knob("profile_sample_ms", 25,
         doc="Sampling period of the profiler daemon thread. 25ms "
             "(40Hz) keeps measured overhead under the 2% chaos gate "
             "while resolving stage-scale hot spots; the sampler also "
             "self-limits to a ~1% duty cycle when a pass runs long."),
    Knob("profile_max_frames", 64,
         doc="Per-sample stack-depth bound: frames beyond this many "
             "(leaf-ward from the root) are truncated before folding, "
             "bounding both fold cost and table key size."),
    Knob("profile_export_dir", "", env="BLAZE_TPU_PROFILE_EXPORT_DIR",
         doc="Per-query profile export dir ('' disables): "
             "profile_<query_id>.collapsed (flamegraph.pl collapsed-"
             "stack text) plus profile_<query_id>.speedscope.json, "
             "written at query end; render/convert with "
             "tools/blaze_prof.py."),

    # -- structured query tracing (runtime/trace.py) --
    Knob("trace_enabled", False,
         doc="Record correlated span/event records (query/stage/task/"
             "attempt ids) for every runtime decision. Off (default) "
             "every trace call site is one truthiness check."),
    Knob("trace_buffer_events", 1 << 17,
         doc="Bounded ring capacity of the process-global TraceLog; "
             "overflow drops the OLDEST record and counts it "
             "(TraceLog.dropped)."),
    Knob("trace_export_dir", "", env="BLAZE_TPU_TRACE_DIR",
         doc="Per-query export dir ('' disables): trace_<query_id>.json "
             "(Chrome/Perfetto) plus one ledger.jsonl line per query."),

    # -- execution resilience (runtime/faults.py, runtime/executor.py) --
    Knob("fault_injection_spec", default_factory=dict,
         doc="Fault-injection spec ({} disables; see faults.py docstring "
             "for the {'seed':..., 'points':...} shape). Install via "
             "faults.install() so the deterministic schedule state "
             "resets with the spec."),
    Knob("max_task_retries", 2,
         doc="Bounded per-task retries for RetryableError-classified "
             "failures."),
    Knob("retry_backoff_ms", 10,
         doc="Base backoff before retry i is ~retry_backoff_ms * 2^i "
             "(+-25% jitter)."),
    Knob("enable_degradation_ladder", True,
         doc="Resource-exhaustion degradation ladder: halve macro-batch "
             "-> force MemManager spill -> CPU fallback interpreter. "
             "Off = resource errors get plain bounded retries."),

    # -- task supervisor (runtime/supervisor.py) --
    Knob("enable_supervisor", True,
         doc="Off = the PR-2 sequential runner: tasks run inline on the "
             "driver thread with retries/ladder only (no pool, watchdog, "
             "speculation)."),
    Knob("max_concurrent_tasks", 4,
         doc="Bounded worker pool for shuffle-map/broadcast/result "
             "tasks. Deterministic chaos replay forces 1 while a fault "
             "spec without {'concurrent': true} is armed."),
    Knob("task_deadline_ms", 0,
         doc="Wall-clock budget per task (all attempts incl. retries/"
             "backoff); 0 = unlimited. Exhaustion raises "
             "faults.DeadlineError."),
    Knob("query_deadline_ms", 0,
         doc="Wall-clock budget per query; 0 = unlimited."),
    Knob("hang_detect_ms", 0,
         doc="Watchdog hang detection: an attempt whose heartbeat stalls "
             "past this is cancelled and relaunched under the resilience "
             "ladder. 0 disables."),
    Knob("speculation_multiplier", 0.0,
         doc="Straggler speculation: a running attempt exceeding "
             "multiplier x the median completed-attempt duration of its "
             "stage gets a speculative twin; first commit wins. 0 "
             "disables."),
    Knob("breaker_failure_threshold", 4,
         doc="Per-operator circuit breaker: after this many classified "
             "failures attributed to one operator kind within a query, "
             "that operator trips to the row-interpreter fallback. 0 "
             "disables."),

    # -- multi-tenant query service (runtime/service.py) --
    Knob("max_concurrent_queries", 4,
         doc="QueryService admission control: queries running at once. "
             "Arrivals beyond this park in the bounded admission queue "
             "(wait counts against query_deadline_ms)."),
    Knob("admission_queue_depth", 16,
         doc="Bounded admission queue: parked queries waiting for a run "
             "slot. A full queue load-sheds new arrivals with a typed "
             "faults.AdmissionRejected (and a run-ledger line)."),
    Knob("tenant_quota_spec", default_factory=dict,
         doc="Per-tenant MemManager quota ({'tenant': bytes} or a 0-1 "
             "float fraction of the budget; {} = no quotas). An "
             "over-quota tenant spills/parks its OWN consumers; it "
             "cannot evict another tenant's working set."),
    Knob("tenant_priority_spec", default_factory=dict,
         doc="Per-tenant scheduling weight ({'tenant': weight}, default "
             "1.0): the service pool dispatches TaskSpecs deficit-"
             "weighted round robin across live sessions, not FIFO."),
    Knob("tenant_slo_spec", default_factory=dict,
         doc="Per-tenant latency objective ({'tenant': {'latency_ms': "
             "500, 'target': 0.99}}; {} disables): the service tracks "
             "rolling attainment + burn rate over the last "
             "slo_window_queries arrivals (shed queries count as "
             "misses), exports blaze_slo_* gauges and emits a "
             "'slo_burn' trace event when the error budget burns past "
             "slo_burn_alert_rate."),
    Knob("slo_window_queries", 128,
         doc="Rolling window (per tenant, in completed arrivals) over "
             "which SLO attainment and burn rate are computed."),
    Knob("slo_burn_alert_rate", 2.0,
         doc="Burn-rate alert threshold: miss_rate / error_budget above "
             "this emits the 'slo_burn' trace event (1.0 = burning "
             "exactly at budget; 2.0 = budget gone in half the window)."),

    # -- query doctor (runtime/doctor.py, tools/blaze_doctor.py) --
    Knob("doctor_enabled", True,
         doc="Stamp the additive critical-path breakdown into run-ledger "
             "lines / history records and render the doctor section "
             "(breakdown + ranked findings) in explain_analyze. The "
             "stamp is computed from already-recorded spans at export "
             "time — no hot-path cost."),
    Knob("doctor_skew_ratio", 4.0,
         doc="Skew/straggler rule threshold: a stage's worst clean task "
             "must exceed the stage's median task duration by this "
             "factor (and the stage must be a significant share of the "
             "query) before the doctor flags it."),

    # -- pipelined async execution (runtime/pipeline.py) --
    Knob("enable_pipeline", True,
         doc="Overlap host-side stages (parquet read+decode, serde, "
             "shuffle frame I/O, spill I/O) with device compute via a "
             "shared I/O pool behind bounded queues. False restores the "
             "serial streams; an armed fault spec without "
             "{'concurrent': true} also forces serial."),
    Knob("io_threads", 4,
         doc="Shared I/O pool width (pipeline.io_pool). Host stages "
             "release the GIL (zlib + numpy + file I/O), so a few "
             "threads overlap well even under CPython."),
    Knob("prefetch_batches", 2,
         doc="Bounded queue depth per pipelined stream; in-flight bytes "
             "are reserved against the MemManager budget (backpressure, "
             "not OOM).",
         step=1, min=1, max=8),

    # -- resource accounting & live metrics (runtime/monitor.py) --
    Knob("monitor_enabled", True,
         doc="Byte accounting at every copy boundary with per-query/"
             "stage attribution. Off, every boundary call site is one "
             "truthiness check and all counters read 0; the always-on "
             "leak telemetry is independent of this flag."),
    Knob("metrics_port", 0,
         doc="Metrics + debug-endpoint HTTP server (stdlib http.server "
             "daemon thread) serving GET /metrics, /healthz, /queries "
             "and /queries/<qid>; 0 disables."),
    Knob("metrics_host", "127.0.0.1", env="BLAZE_TPU_METRICS_HOST",
         doc="Bind address for the metrics/debug HTTP server. Loopback "
             "by default — set 0.0.0.0 only when the endpoints should "
             "be reachable off-host (they expose query metadata)."),
    Knob("monitor_sample_ms", 200,
         doc="Background ResourceMonitor sampling period (MemManager "
             "usage, spill pages, pool occupancy, queue depths, "
             "compile-cache stats); <= 0 disables the sampler thread."),
    Knob("monitor_ring_samples", 2048,
         doc="Bounded sample-ring capacity (deque maxlen; 2048 x 200ms "
             "is about the last ~7 minutes)."),

    # -- query history store (runtime/history.py) --
    Knob("history_dir", "", env="BLAZE_TPU_HISTORY_DIR",
         doc="Persistent per-run statistics keyed by plan fingerprint: "
             "sharded JSONL under this directory. '' disables (every "
             "history call site is one truthiness check)."),
    Knob("history_retention_runs", 512,
         doc="Total run records retained across shards; also bounds the "
             "trace_export_dir rotation applied on driver start."),
    Knob("history_shard_runs", 128,
         doc="Records per JSONL shard before rotating to a new shard "
             "file (retention prunes whole oldest shards)."),
    Knob("history_regression_pct", 25.0,
         doc="Cross-run regression threshold: latest per-stage wall time "
             "/ copy traffic flagged when it exceeds the fingerprint's "
             "historical median by more than this percentage (plus an "
             "absolute noise grace — history.detect_regressions)."),

    # -- flight recorder & live introspection (runtime/flight_recorder,
    # -- runtime/progress.py) --
    Knob("flight_dir", "", env="BLAZE_TPU_FLIGHT_DIR",
         doc="Incident dossier directory ('' disables): when a query "
             "fails / is shed / exceeds its deadline / hangs / breaches "
             "its tenant SLO / trips a breaker / leaks resources, a "
             "self-contained JSON dossier (trace slice, monitor samples, "
             "doctor breakdown + findings, resolved knobs, ledger line) "
             "is committed crash-atomically under this directory."),
    Knob("flight_retention", 64,
         doc="Bounded dossier retention: the newest N dossiers are kept, "
             "older ones pruned after each capture."),
    Knob("flight_triggers", "all",
         doc="Comma list selecting which incident classes capture "
             "(failure, shed, deadline, hang, slo_breach, breaker_trip, "
             "resource_leak, driver_restart, driver_failover, "
             "stream_stall); 'all' arms every class."),
    Knob("progress_enabled", False,
         doc="Live per-query progress tracking (runtime/progress.py): "
             "per-stage rows/attempts/ETA served at /queries and "
             "/queries/<qid>. Off (default) every hook site is one "
             "truthiness check — same posture as trace/monitor."),

    # -- process-isolated executors (runtime/executor_pool.py) --
    Knob("executor_count", 0,
         doc="Process-isolated executor pool width: N worker processes "
             "each owning a virtual device slice, fed TaskSpecs over a "
             "length-prefixed control socket. 0 (default) keeps the "
             "single-process thread runtime."),
    Knob("executor_slots", 2,
         doc="Concurrent task slots per executor process; the service's "
             "admission capacity degrades to live_executors x slots when "
             "a pool is attached."),
    Knob("executor_heartbeat_ms", 100,
         doc="Executor -> driver heartbeat period over the control "
             "socket (a worker thread pushes beats; any inbound frame "
             "also refreshes liveness)."),
    Knob("executor_death_ms", 2000,
         doc="Heartbeat staleness past which the driver declares an "
             "executor dead (fences its epoch, re-queues its in-flight "
             "tasks, recomputes capacity). A reaped PID is declared "
             "dead immediately regardless of this threshold."),
    Knob("executor_restart_max", 3,
         doc="Replacement spawns per executor seat after a death; "
             "exhausting it retires the seat (capacity stays degraded)."),
    Knob("executor_restart_backoff_ms", 100,
         doc="Base backoff before replacement spawn i of a seat is "
             "~backoff * 2^i."),
    Knob("telemetry_ship_ms", 250,
         doc="Executor -> driver telemetry ship period: buffered span/"
             "event records and monitor counter deltas are batched into "
             "a 'telemetry' frame on the control socket at this cadence "
             "(a flush also rides every task result). <= 0 disables "
             "the timer; results still carry their flush.",
         step=2.0, min=50, max=2000, geometric=True),
    Knob("executor_trace_events", 4096,
         doc="Bounded ring capacity of each executor process's local "
             "TraceLog (worker-side spans buffer here between ships; "
             "overflow drops the OLDEST record and counts it). The "
             "unshipped tail is also spilled crash-atomically to a "
             "per-worker sidecar file so a SIGKILL loses nothing the "
             "driver can't recover."),
    Knob("clock_skew_bound_ms", 5000,
         doc="Bound on the per-executor clock offset estimated from the "
             "hello handshake echo (executor monotonic clocks are "
             "rebased onto the driver's before trace federation). An "
             "estimate outside +-bound is clamped so one bad echo "
             "cannot scramble merged-trace ordering."),
    Knob("control_reconnect_max", 4,
         doc="Bounded reconnect attempts a worker makes after a control-"
             "socket transport error before treating the driver as "
             "unreachable (the lease then governs self-fencing). The "
             "driver keeps a broken-but-alive seat's tasks in flight "
             "while it waits for the resume handshake, bounded by "
             "executor_death_ms."),
    Knob("control_reconnect_backoff_ms", 50,
         doc="Base backoff before worker reconnect attempt i "
             "(~backoff * 2^i, jittered) after a control-socket error; "
             "the resume handshake re-delivers unacked TaskSpecs and "
             "results, deduped by (task_id, attempt, epoch).",
         step=2.0, min=10, max=1600, geometric=True),
    Knob("executor_drain_grace_ms", 5000,
         doc="Graceful-decommission budget: a draining executor "
             "(ExecutorPool.decommission or SIGTERM) finishes in-flight "
             "tasks for up to this long, flushes its telemetry sidecar, "
             "hands registered shuffle rids back, then exits. In-flight "
             "work still unfinished at expiry is requeued without an "
             "executor_death dossier."),

    # -- durable execution (runtime/artifacts.py, runtime/journal.py) --
    Knob("artifact_checksums", True,
         doc="Per-frame CRC32 + whole-file digests stamped into shuffle "
             ".index files at commit time and verified on every read "
             "path (server segment fetch, local shuffle reads, spill "
             "re-read). A mismatch quarantines the artifact and triggers "
             "lineage re-execution of the producing map task under a "
             "fresh epoch. Off = commit/read behave as before (legacy "
             "footer-less indexes are always accepted)."),
    Knob("journal_dir", "", env="BLAZE_TPU_JOURNAL_DIR",
         doc="Write-ahead query journal directory ('' disables): one "
             "crash-atomic JSONL per query recording admission, plan "
             "fingerprints, each stage commit (artifact paths, epochs, "
             "checksums) and completion — the recovery scan replays "
             "incomplete journals after a driver crash."),
    Knob("journal_retention", 256,
         doc="Journal files retained (newest N complete journals; "
             "incomplete ones are never pruned until recovered)."),
    Knob("recovery_enabled", True,
         doc="Driver-crash recovery scan at driver start (beside the "
             "orphan sweep): incomplete journals are replayed — verified "
             "committed stages become resumable, unverifiable queries "
             "are billed failed with a driver_restart dossier. Needs "
             "journal_dir."),
    Knob("shuffle_connect_timeout_ms", 5000,
         doc="ShuffleClient socket connect/read timeout and total retry "
             "budget: fetches retry with exponential backoff within this "
             "window instead of blocking forever on a hung shuffle "
             "server. 0 = legacy blocking socket with one reconnect."),

    # -- zero-copy data plane (shuffle mmap + dictionary strings) --
    Knob("shuffle_mmap_enabled", True,
         doc="Same-host shuffle fast path: when the committed "
             ".data/.index pair for a fetched rid is host-local, the "
             "ShuffleClient mmaps the .data file read-only and slices "
             "partition segments as zero-copy memoryviews (booked as "
             "bytes_moved only), verifying per-frame CRC32 lazily on "
             "first touch; a mismatch falls back to the BCS2 socket "
             "fetch whose server-side read quarantines + lineage-"
             "repairs. Off = every pooled fetch streams over the "
             "socket.",
         step=1, min=0, max=1),
    Knob("dict_encode_strings", True,
         doc="Dictionary-encode string columns in serde frames: ship "
             "(dict, codes) once and keep filter/join/groupby on i32 "
             "codes, decoding only at the result-merge edge. Columns "
             "whose slice cardinality exceeds dict_max_cardinality (or "
             "where the dict form is not smaller) fall back to plain "
             "length-prefixed encoding per column.",
         step=1, min=0, max=1),
    Knob("dict_max_cardinality", 64 << 10,
         doc="Distinct-value ceiling for dictionary-encoded string "
             "columns: a serde slice with more unique strings than this "
             "is written in plain form (the dict no longer pays for "
             "itself and the code gather stops being cache-friendly).",
         step=2.0, min=256, max=1 << 20, geometric=True),

    # -- elastic fleet & driver HA (runtime/autoscaler.py,
    # -- runtime/standby.py) --
    Knob("autoscale_enabled", False,
         doc="SLO-driven fleet autoscaler: a driver-side policy loop "
             "reads admission parked arrivals, SLO burn rate and per-"
             "seat busy-slot utilization, then actuates pool.spawn() / "
             "pool.decommission() within [autoscale_min, autoscale_max] "
             "seats. Scale-down drains the idlest seat through the "
             "drain-ack barrier so in-flight queries never notice."),
    Knob("autoscale_min", 1,
         doc="Autoscaler floor: the fleet never drains below this many "
             "serving seats, regardless of how idle they are."),
    Knob("autoscale_max", 4,
         doc="Autoscaler ceiling: scale-up stops here even while parked "
             "arrivals persist (doctor's fleet_underprovisioned finding "
             "suggests raising it when the policy pins at the ceiling).",
         step=1, min=1, max=8),
    Knob("autoscale_cooldown_ms", 5000,
         doc="Hysteresis between autoscaler actuations: after a "
             "scale_up/scale_down decision the policy observes without "
             "acting for this long, so a burst cannot thrash spawn/"
             "drain cycles."),
    Knob("standby_enabled", False,
         doc="Warm-standby driver (runtime/standby.py): a second "
             "process tails journal_dir + the leader lease, detects "
             "primary death by pid-liveness and takes over — rebinding "
             "the executor control socket, replaying dead-writer "
             "journals into resumable queries and resuming admission."),
    Knob("leader_lease_ms", 2000,
         doc="Leader lease freshness window: a lease whose holder pid "
             "is dead, or unrenewed for longer than this, is up for "
             "grabs. Takeover bumps the lease epoch so a paused-then-"
             "resumed old primary self-fences on its next renew — the "
             "same epoch posture PR 15 gave executors."),

    # -- durable micro-batch streaming (runtime/streaming.py) --
    Knob("stream_poll_ms", 200,
         doc="Micro-batch tick cadence: a StreamingQuery sleeps this "
             "long between TailSource discovery passes when the source "
             "is caught up (a tick that found new files immediately "
             "polls again, so a backlog drains at full speed)."),
    Knob("stream_checkpoint_interval", 1,
         doc="Micro-batches between durable checkpoints. 1 (default) "
             "checkpoints after every committed batch — exactly-once "
             "resume never re-processes more than the in-flight batch. "
             "N>1 amortizes the fsync over N batches; a crash then "
             "re-processes up to N batches into the last checkpointed "
             "state (still exactly-once externally: offsets and state "
             "travel in the same atomic record)."),
    Knob("stream_max_lag_ms", 10000,
         doc="End-to-end lag objective for a stream (oldest undiscovered-"
             "or-unprocessed input age). Sustained lag past this cuts a "
             "stream_stall flight dossier (once per stream) and a doctor "
             "stream_lag finding suggesting the knob to turn."),

    # -- self-tuning autopilot (runtime/autopilot.py) --
    Knob("autopilot_enabled", False, env="BLAZE_AUTOPILOT",
         doc="Guarded per-fingerprint knob adaptation: each run's top "
             "doctor finding proposes ONE bounded knob step (the knob's "
             "declared step/min/max schedule), canary runs are verdicted "
             "against the settled baseline by detect_regressions(), and "
             "a regression rolls the overlay back immediately and "
             "quarantines the value. Needs autopilot_dir."),
    Knob("autopilot_dir", "", env="BLAZE_AUTOPILOT_DIR",
         doc="Crash-atomic OverlayStore directory ('' disables): one "
             "journal-style JSONL of propose/promote/rollback/quarantine "
             "events, folded into per-fingerprint state on open — "
             "settled overlays and quarantine lists survive driver "
             "restart and standby failover."),
    Knob("autopilot_canary_runs", 3,
         doc="Consecutive canary runs that must beat the settled p50 "
             "before a proposed overlay value is promoted to settled; a "
             "canary that can't produce this streak within 3x the budget "
             "is reverted as inconclusive (and quarantined, so the "
             "explorer never oscillates on it)."),
    Knob("autopilot_max_active_canaries", 4,
         doc="Cap on concurrently-canarying fingerprints across the "
             "store; proposals beyond it are deferred until a canary "
             "promotes or rolls back."),

    # -- per-operator enable flags (tier b, spark.blaze.enable.<op>) --
    Knob("enable_ops", default_factory=dict,
         doc="Per-operator enable flags ({'filter': False} routes that "
             "operator to the fallback path); read through "
             "conf.op_enabled(op)."),
)

KNOBS: Dict[str, Knob] = {k.name: k for k in _DECLARATIONS}

# Overlay layers in precedence order (later wins). ``base`` is the
# BlazeConf singleton itself; the other three are plain dicts validated
# against KNOBS and composed per query by resolve_overlay().
OVERLAY_LAYERS: Tuple[str, ...] = ("base", "tenant", "fingerprint", "pin")

# Thread-scoped overlay application: a query thread enters
# overlay_scope(...) and every conf.<knob> read on THAT thread sees the
# overlaid value; concurrent queries on other threads keep reading base
# (or their own overlay) — one query's canary can never leak into
# another tenant's resolved conf.
_overlay_tls = threading.local()


class BlazeConf:
    """The process-wide knob singleton, built from ``KNOBS``.

    Attribute surface is exactly the registry: reading/writing an
    undeclared name is an AttributeError/blazelint finding, and
    ``update()`` keeps the historical KeyError contract for the JVM
    bridge's property plumbing. Reads are overlay-aware: inside an
    overlay_scope() the calling thread sees the scoped values."""

    __slots__ = tuple(KNOBS)

    def __init__(self) -> None:
        for knob in KNOBS.values():
            setattr(self, knob.name, knob.resolve())

    def __getattribute__(self, name: str) -> Any:
        ov = _overlay_tls.__dict__.get("values")
        if ov is not None and name in ov:
            return ov[name]
        return object.__getattribute__(self, name)

    def op_enabled(self, op: str) -> bool:
        return self.enable_ops.get(op, True)

    def update(self, **kwargs: Any) -> "BlazeConf":
        for k, v in kwargs.items():
            if k not in KNOBS:
                raise KeyError(f"unknown conf key: {k}")
            setattr(self, k, v)
        return self


def validate_overlay(mapping: Dict[str, Any],
                     layer: str = "overlay") -> Dict[str, Any]:
    """Validate one overlay layer against the Knob registry.

    Unknown knob names raise KeyError (the conf.update contract);
    type-incompatible values raise TypeError. int/float coerce to the
    declared type; bool is strict (it IS an int to isinstance)."""
    out: Dict[str, Any] = {}
    for name, value in dict(mapping).items():
        knob = KNOBS.get(name)
        if knob is None:
            raise KeyError(f"unknown conf key in {layer} overlay: {name}")
        t = knob.type
        if t is bool:
            if not isinstance(value, bool):
                raise TypeError(
                    f"{layer} overlay {name}: expected bool, "
                    f"got {type(value).__name__}")
        elif isinstance(value, bool):
            raise TypeError(
                f"{layer} overlay {name}: expected {t.__name__}, got bool")
        elif t in (int, float) and isinstance(value, (int, float)):
            value = t(value)
        elif not isinstance(value, t):
            raise TypeError(
                f"{layer} overlay {name}: expected {t.__name__}, "
                f"got {type(value).__name__}")
        out[name] = value
    return out


_tenant_overlays: Dict[str, Dict[str, Any]] = {}


def set_tenant_overlay(tenant: str,
                       mapping: Optional[Dict[str, Any]]) -> None:
    """Install (or clear, with a falsy mapping) a tenant's overlay."""
    if not mapping:
        _tenant_overlays.pop(tenant, None)
    else:
        _tenant_overlays[tenant] = validate_overlay(mapping, layer="tenant")


def tenant_overlay(tenant: Optional[str]) -> Dict[str, Any]:
    return dict(_tenant_overlays.get(tenant) or {}) if tenant else {}


def overlay_hash(values: Dict[str, Any]) -> Optional[str]:
    """Stable short hash of a resolved overlay (None when empty) —
    stamped into history records so StatisticsFeed/detect_regressions
    compare like-with-like across overlay generations."""
    if not values:
        return None
    blob = json.dumps(values, sort_keys=True, default=repr)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


@dataclasses.dataclass
class ResolvedOverlay:
    """The composed non-base layers for one query: what differs from
    base, which layer each value came from, and the stable hash."""

    values: Dict[str, Any] = dataclasses.field(default_factory=dict)
    provenance: Dict[str, str] = dataclasses.field(default_factory=dict)
    canary: bool = False
    canary_knob: str = ""

    @property
    def hash(self) -> Optional[str]:
        return overlay_hash(self.values)

    def as_record(self) -> Dict[str, Any]:
        """JSON-safe stamp for ledger lines / dossiers / run_info."""
        return {"overlay": dict(self.values),
                "provenance": dict(self.provenance),
                "overlay_hash": self.hash,
                "canary": self.canary,
                "canary_knob": self.canary_knob}


def resolve_overlay(tenant: Optional[str] = None,
                    fingerprint_overlay: Optional[Dict[str, Any]] = None,
                    pin: Optional[Dict[str, Any]] = None) -> ResolvedOverlay:
    """Compose base -> tenant -> per-fingerprint -> per-query pin.

    Each layer is validated against KNOBS; later layers win and the
    winning layer is recorded per knob in ``provenance`` (knobs absent
    from every layer stay 'base' and are not listed)."""
    resolved = ResolvedOverlay()
    for layer, mapping in (("tenant", tenant_overlay(tenant)),
                           ("fingerprint", fingerprint_overlay),
                           ("pin", pin)):
        if not mapping:
            continue
        for name, value in validate_overlay(mapping, layer=layer).items():
            resolved.values[name] = value
            resolved.provenance[name] = layer
    return resolved


@contextlib.contextmanager
def overlay_scope(values: Optional[Dict[str, Any]],
                  provenance: Optional[Dict[str, str]] = None
                  ) -> Iterator[None]:
    """Apply an overlay to every conf read on the calling thread.

    Nests: an inner scope merges over (and restores) the outer one.
    supervisor/pipeline task threads inherit the submitting thread's
    scope via current_overlay() capture."""
    tls = _overlay_tls.__dict__
    prev = (tls.get("values"), tls.get("provenance"))
    merged = dict(prev[0] or {})
    merged.update(values or {})
    merged_prov = dict(prev[1] or {})
    merged_prov.update(provenance or {})
    tls["values"] = merged or None
    tls["provenance"] = merged_prov or None
    try:
        yield
    finally:
        tls["values"], tls["provenance"] = prev


def current_overlay() -> Dict[str, Any]:
    """The calling thread's active overlay values ({} outside a scope)."""
    return dict(_overlay_tls.__dict__.get("values") or {})


def current_provenance() -> Dict[str, str]:
    return dict(_overlay_tls.__dict__.get("provenance") or {})


def knob_catalog_md() -> str:
    """Render the README 'Configuration knobs' table from the registry
    (python -c "from blaze_tpu.config import knob_catalog_md; ..." — or
    regenerate via tools/blazelint's docs helper)."""
    lines = ["| knob | default | env | purpose |",
             "|---|---|---|---|"]
    for k in _DECLARATIONS:
        default = "`{}`".format(
            "{}" if k.default_factory is not None else repr(k.default))
        env = f"`{k.env}`" if k.env else ""
        doc = " ".join(k.doc.split())
        lines.append(f"| `{k.name}` | {default} | {env} | {doc} |")
    return "\n".join(lines)


conf = BlazeConf()
