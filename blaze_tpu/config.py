"""Config/flag system — three tiers like the reference (SURVEY.md §5.6).

Ref: spark-extension BlazeConf.java (batchSize/memoryFraction/... read lazily
from native over JNI). Here the native side IS this process, so the conf is a
plain singleton the JVM bridge (or tests) can populate; defaults mirror the
reference's (BlazeConf.java:23-70) where semantics carry over, with
TPU-specific knobs added.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict


@dataclasses.dataclass
class BlazeConf:
    # -- reference-equivalent knobs (BlazeConf.java) --
    batch_size: int = 8192  # ref default 10000; 8192 is TPU/XLA tile friendly
    memory_fraction: float = 0.6
    enable_smj_inequality_join: bool = False
    enable_bhj_fallbacks_to_smj: bool = True
    bhj_fallback_rows_threshold: int = 1_000_000
    bhj_fallback_mem_threshold: int = 128 << 20
    enable_caseconvert_functions: bool = False
    udf_wrapper_num_threads: int = 1
    enable_input_batch_statistics: bool = False
    ignore_corrupt_files: bool = False

    # -- TPU-native knobs --
    # capacity buckets are powers of two: jit cache is keyed on (plan, capacity,
    # string-width) so padding to buckets bounds the number of compilations.
    min_capacity: int = 1024
    # string columns are fixed-width uint8 matrices; width is bucketed too.
    min_string_width: int = 4
    max_string_width: int = 4096
    # HBM budget for MemManager (bytes); 0 = derive from device memory stats.
    memory_budget: int = 0
    # spill directory for host spill files
    spill_dir: str = os.environ.get("BLAZE_TPU_SPILL_DIR", "/tmp/blaze_tpu_spill")
    # zstd level for shuffle/spill/broadcast frames (ref uses level 1)
    zstd_level: int = 1
    # whole-stage single-dispatch compiler (runtime/stage_compiler.py):
    # amortizes the ~90ms-per-dispatch cost of remote-attached TPUs
    enable_stage_compiler: bool = True
    # dense grouped-agg key range for the MXU one-hot path (<= 2^16:
    # 256x256 byte decomposition); stages whose keys exceed it fall back
    dense_agg_range: int = 1 << 16
    # precision policy for FLOAT sums on the MXU digit-plane path: each
    # plane is one base-256 digit of the per-stage max magnitude. The
    # default 6 planes digitize to 46 bits — the TPU's emulated-f64
    # mantissa class, so float sums stay in the same precision class as
    # every other f64 op. Lowering to 5 (38-bit, relative sum error
    # ~2^-38 per value) is a documented opt-in perf setting that cuts
    # one-hot matmul FLOPs ~14%; raise to 7 for stricter accumulation
    # (int sums always use the exact 8-chunk int64 path).
    float_sum_digit_planes: int = 6
    # external-sort spill frame rows: merge cost is one dispatch trio
    # per pooled frame, so bigger frames amortize the fixed per-dispatch
    # overhead (~90ms each on the remote-attached chip)
    spill_frame_rows: int = 1 << 16
    # adaptive macro-batching: batch sources (scan, shuffle/broadcast
    # readers) size batches toward this many bytes, clamped by the
    # memory budget (ops/common.adaptive_batch_rows). On a
    # remote-attached chip every per-batch dispatch/pull carries a fixed
    # ~90ms round trip, so fewer, larger batches are strictly better
    # until HBM pressure; under a small spill budget the clamp restores
    # small bounded batches.
    target_batch_bytes: int = 128 << 20
    max_batch_rows: int = 1 << 21
    # AQE dynamic join selection: a planned SMJ whose shuffled input came
    # in under this many bytes becomes a broadcast join (Spark's
    # autoBroadcastJoinThreshold analog; 0 disables)
    aqe_broadcast_threshold: int = 10 << 20
    # compile-service shape canonicalization (runtime/compile_service.py):
    # above canonical_pow2_limit, power-of-two capacity buckets collapse
    # onto power-of-four rungs anchored at the limit, halving the large
    # end of the compiled-program shape space. At or below the limit
    # shapes are identical to the plain pow2 buckets.
    enable_compile_canonicalization: bool = True
    canonical_pow2_limit: int = 1 << 14
    # JAX profiler trace output dir ("" disables) — runtime/tracing.py
    profiler_dir: str = os.environ.get("BLAZE_TPU_PROFILE_DIR", "")
    # -- structured query tracing (runtime/trace.py) --
    # Record correlated span/event records (query/stage/task/attempt ids)
    # for every runtime decision: stage transport, task attempts, retries,
    # ladder rungs, speculation, breaker trips, spills, compile cache
    # traffic. Off (default) every trace call is one truthiness check.
    trace_enabled: bool = False
    # bounded ring capacity of the process-global TraceLog; overflow
    # drops the OLDEST record and counts it (TraceLog.dropped — surfaced
    # in the run ledger so a truncated trace is never mistaken for a
    # quiet one)
    trace_buffer_events: int = 1 << 17
    # per-query export dir ("" disables): the local runner writes
    # trace_<query_id>.json (Chrome/Perfetto trace-event JSON) and
    # appends one JSONL line to ledger.jsonl per query
    trace_export_dir: str = os.environ.get("BLAZE_TPU_TRACE_DIR", "")
    # -- execution resilience (runtime/faults.py, runtime/executor.py) --
    # fault-injection spec ({} disables; see faults.py docstring for the
    # {"seed": ..., "points": {...}} shape). Install via faults.install()
    # so the deterministic schedule state resets with the spec.
    fault_injection_spec: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    # bounded per-task retries for RetryableError-classified failures
    max_task_retries: int = 2
    # base backoff before retry i is ~retry_backoff_ms * 2^i (+-25% jitter)
    retry_backoff_ms: int = 10
    # resource-exhaustion degradation ladder: halve macro-batch ->
    # force MemManager spill -> route the task to the CPU fallback
    # interpreter. Off = resource errors get plain bounded retries.
    enable_degradation_ladder: bool = True
    # -- task supervisor (runtime/supervisor.py) --
    # Off = the PR-2 sequential runner: tasks run inline on the driver
    # thread with retries/ladder only (no pool, watchdog, speculation).
    enable_supervisor: bool = True
    # bounded worker pool for shuffle-map / broadcast / result tasks.
    # Deterministic chaos replay forces this to 1 while a fault spec
    # without {"concurrent": true} is armed (scheduling order is part of
    # the injection schedule).
    max_concurrent_tasks: int = 4
    # wall-clock budget per task (all attempts incl. retries/backoff) and
    # per query; 0 = unlimited. Exhaustion raises faults.DeadlineError.
    task_deadline_ms: int = 0
    query_deadline_ms: int = 0
    # watchdog hang detection: an attempt whose heartbeat (kill-flag
    # checks at batch boundaries) stalls past this is cancelled and
    # relaunched under the resilience ladder. 0 disables — a first jit
    # compile can legitimately sit minutes without a batch boundary.
    hang_detect_ms: int = 0
    # straggler speculation: a running attempt exceeding multiplier x the
    # median completed-attempt duration of its stage gets a speculative
    # twin; first commit wins, the loser is cancelled. 0 disables
    # (Spark's spark.speculation default; its multiplier default is 1.5).
    speculation_multiplier: float = 0.0
    # per-operator circuit breaker: after this many classified failures
    # attributed to one operator kind within a query, that operator trips
    # to the row-interpreter fallback for the rest of the run. 0 disables.
    breaker_failure_threshold: int = 4
    # -- pipelined async execution (runtime/pipeline.py) --
    # Overlap host-side stages (parquet read+decode, serde compress/
    # decompress, shuffle frame write + read-side readahead, spill I/O)
    # with device compute: producers run on a shared I/O thread pool
    # behind bounded queues while the consumer thread keeps the device
    # busy. False restores the serial streams; an armed fault spec
    # without {"concurrent": true} also forces serial (thread timing
    # would otherwise perturb deterministic chaos schedules).
    enable_pipeline: bool = True
    # shared I/O pool width (pipeline.io_pool). Host stages are
    # zlib/zstd + numpy + file I/O — they release the GIL, so a few
    # threads overlap well even under CPython.
    io_threads: int = 4
    # bounded queue depth per pipelined stream: at most this many
    # batches sit decoded-but-unconsumed. In-flight bytes are reserved
    # against the MemManager budget (backpressure, not OOM), so raising
    # this trades memory for tolerance to bursty producers.
    prefetch_batches: int = 2
    # -- resource accounting & live metrics (runtime/monitor.py) --
    # Byte accounting at every copy boundary (serde framing, FFI
    # host<->device, shuffle partition split, spill write/read,
    # row-interpreter fallback export) with per-query/stage attribution
    # via the trace context, rolled into the run ledger and
    # explain_analyze. Off, every boundary call site is one truthiness
    # check and all counters read 0. The always-on leak telemetry
    # (resource_leak events) is independent of this flag.
    monitor_enabled: bool = True
    # Prometheus text-format scrape endpoint (stdlib http.server daemon
    # thread) serving GET /metrics; 0 (default) disables. The local
    # runner starts it lazily on the first query (monitor.ensure_started
    # also spins up the background sampler).
    metrics_port: int = 0
    # background ResourceMonitor sampling period: MemManager usage incl.
    # pipeline_reserved, spill pages, pool occupancy, pipeline queue
    # depths, and compile-cache stats into a bounded time-series ring.
    # <= 0 disables the sampler thread.
    monitor_sample_ms: int = 200
    # bounded sample-ring capacity (deque maxlen — oldest samples drop
    # first; 2048 x 200ms ≈ the last ~7 minutes)
    monitor_ring_samples: int = 2048
    # -- query history store (runtime/history.py) --
    # Persistent per-run statistics keyed by plan fingerprint
    # (plan/fingerprint.py): sharded JSONL under this directory, one
    # record per query — stage wall times, copy traffic, per-operator
    # row counts, dense-vs-fallback groupby cardinality. "" disables
    # (every history call site is one truthiness check).
    history_dir: str = os.environ.get("BLAZE_TPU_HISTORY_DIR", "")
    # total run records retained across shards; also bounds the
    # trace_export_dir rotation (ledger lines + trace_<qid>.json files
    # kept) applied on driver start alongside the orphan sweep
    history_retention_runs: int = 512
    # records per JSONL shard before rotating to a new shard file
    # (retention prunes whole oldest shards)
    history_shard_runs: int = 128
    # cross-run regression threshold: the latest run's per-stage wall
    # time / copy traffic is flagged when it exceeds the fingerprint's
    # historical median by more than this percentage (plus an absolute
    # noise grace — see history.detect_regressions)
    history_regression_pct: float = 25.0
    # per-operator enable flags (tier b, spark.blaze.enable.<op>)
    enable_ops: Dict[str, bool] = dataclasses.field(default_factory=dict)

    def op_enabled(self, op: str) -> bool:
        return self.enable_ops.get(op, True)

    def update(self, **kwargs: Any) -> "BlazeConf":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise KeyError(f"unknown conf key: {k}")
            setattr(self, k, v)
        return self


conf = BlazeConf()
