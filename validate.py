#!/usr/bin/env python
"""Query-level correctness gate (the reference's TPC-DS validator analog).

Runs the BASELINE config query shapes through the full driver path
(tagging -> conversion -> stage splitting -> multi-stage execution) against
pandas goldens, across both join configs (BHJ and forced SMJ — the
reference's autoBroadcastJoinThreshold=-1 axis, tpcds.yml:131-147).

    python validate.py [--rows N] [--queries q3_join_agg_sort,...]

Exit code 0 iff every (query, join-mode) cell passes.
"""

import argparse
import sys
import tempfile


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=20_000,
                    help="store_sales row count")
    ap.add_argument("--queries", type=str, default="",
                    help="comma-separated subset of query names")
    args = ap.parse_args()

    from blaze_tpu.spark.validator import print_report, run_matrix

    queries = [q for q in args.queries.split(",") if q] or None
    with tempfile.TemporaryDirectory(prefix="blaze_tpu_validate_") as tmp:
        results = run_matrix(tmp, rows=args.rows, queries=queries)
    return 0 if print_report(results) else 1


if __name__ == "__main__":
    sys.exit(main())
