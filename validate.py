#!/usr/bin/env python
"""Query-level correctness gate (the reference's TPC-DS validator analog).

Runs the BASELINE config query shapes through the full driver path
(tagging -> conversion -> stage splitting -> multi-stage execution) against
pandas goldens, across both join configs (BHJ and forced SMJ — the
reference's autoBroadcastJoinThreshold=-1 axis, tpcds.yml:131-147).

    python validate.py [--rows N] [--queries q3_join_agg_sort,...]

Exit code 0 iff every (query, join-mode) cell passes.
"""

import argparse
import os
import sys
import tempfile

# honor an explicit JAX_PLATFORMS=cpu BEFORE blaze imports: the
# .axon_site hook otherwise force-selects an attached TPU, which makes
# "CPU mesh" gate runs silently ride (or hang on) the chip tunnel
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=20_000,
                    help="store_sales row count")
    ap.add_argument("--queries", type=str, default="",
                    help="comma-separated subset of query names")
    ap.add_argument("--spill-budget", type=int, default=0,
                    help="force-spill mode: MemManager byte budget per "
                    "cell (e.g. 2000000 with --rows 2000000 makes every "
                    "sort/agg/shuffle spill in query context)")
    ap.add_argument("--json-out", type=str, default="",
                    help="also write the per-cell results as JSON")
    ap.add_argument("--suite", type=str, default="core",
                    choices=["core", "tpcds", "all"],
                    help="core = BASELINE config shapes; tpcds = the "
                    "hand-constructed TPC-DS q01-q10 catalogue")
    args = ap.parse_args()

    from blaze_tpu.spark.validator import print_report, run_matrix

    queries = [q for q in args.queries.split(",") if q] or None
    suites = (["core", "tpcds"] if args.suite == "all" else [args.suite])
    results = []
    with tempfile.TemporaryDirectory(prefix="blaze_tpu_validate_") as tmp:
        for suite in suites:
            os.makedirs(f"{tmp}/{suite}", exist_ok=True)
            results += run_matrix(f"{tmp}/{suite}", rows=args.rows,
                                  queries=queries,
                                  spill_budget=args.spill_budget or None,
                                  suite=suite)
    ok = print_report(results)
    if args.json_out:
        import dataclasses
        import json

        with open(args.json_out, "w") as f:
            json.dump({"rows": args.rows,
                       "spill_budget": args.spill_budget,
                       "results": [dataclasses.asdict(r) for r in results]},
                      f, indent=1)
    if args.spill_budget and ok and not any(r.spill_count for r in results):
        print("FORCE-SPILL MODE: no spill observed — budget too large?")
        return 1
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
