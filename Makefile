# The commit gate. Run `make check` before EVERY snapshot commit —
# round 3 shipped with 38/252 tests red because this didn't exist.
# Mirrors the reference's CI gate (.github/workflows/tpcds.yml): the
# full suite on the virtual 8-device CPU mesh, plus the query-level
# validator matrix (which runs on the real chip when one is attached —
# the axon hook overrides JAX_PLATFORMS for plain scripts).

PYENV = XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu

.PHONY: check check-fast check-faults check-supervisor check-trace \
	check-durability check-dist-obs check-network check-elastic \
	check-streaming check-autopilot check-profile check-zerocopy \
	check-pipeline \
	check-pipeline-soak \
	check-perf \
	check-perf-update check-obs check-history check-lint check-service \
	check-doctor check-flight check-executors test test-fast validate \
	validate-fast warm

check: check-lint test validate check-perf check-history check-service \
	check-doctor check-flight check-executors check-durability \
	check-dist-obs check-network check-elastic check-streaming \
	check-autopilot check-profile check-zerocopy
	@echo "CHECK OK — safe to commit"

# Static invariant gate (tools/blazelint): lock discipline, knob
# registry sync, resource pairing, hot-path gating, name-registry sync
# and a pyflakes-equivalent pass — stdlib ast only, no jax import, so
# it runs first (seconds) and fails fast. New findings must be fixed
# or added to LINT_BASELINE.json with a justification (README "Static
# analysis"). Emits LINT_r12.json.
check-lint:
	python -m tools.blazelint --json-out LINT_r12.json

# The every-commit bar (< 5 min): full unit suite minus the two
# slowest end-to-end suites, plus a 3-cell validator subset. Slow gates
# get skipped under pressure — that is how round 3 shipped red — so the
# fast tier exists to keep SOME query-level gate on every commit; run
# the full `make check` before snapshot commits.
check-fast: test-fast validate-fast
	@echo "CHECK-FAST OK — run full 'make check' before snapshots"

test:
	$(PYENV) python -m pytest tests/ -q

test-fast:
	$(PYENV) python -m pytest tests/ -q -x \
	  --ignore=tests/test_fuzz_scale.py \
	  --ignore=tests/test_validator.py

validate:
	$(PYENV) python validate.py --suite all

validate-fast:
	$(PYENV) python validate.py \
	  --queries q2_q06_core_agg,q3_join_agg_sort

# Chaos soak: sweep every fault-injection point x kind over the
# validator mini-catalogue; every armed run must recover to the pandas
# oracle (or fail classified) and leave no orphans/leaked reservations.
# Emits FAULTS_r06.json.
check-faults:
	$(PYENV) python tools/chaos_soak.py --kinds io,oom,stall \
	  --stall-ms 300 --json-out FAULTS_r06.json

# Supervisor soak: the same point x kind sweep — plus the "stall" kind —
# under the CONCURRENT supervised pool (4 workers, hang detection +
# straggler speculation armed). Stall cells must recover via watchdog
# kill + relaunch, answers must match the pandas oracle, and no cell may
# leave orphans or leaked reservations. Emits SUPERVISOR_r07.json.
check-supervisor:
	$(PYENV) python tools/chaos_soak.py --supervisor \
	  --json-out SUPERVISOR_r07.json

# Pipeline gate: I/O-bound shuffle microbench serial vs pipelined (must
# show >= 1.3x from overlapping synthetic I/O with consumer compute),
# plus the validator mini-catalogue with enable_pipeline off vs on (both
# directions within noise — the off path restores serial behavior, the
# on path must not slow real queries). Emits PIPELINE_r09.json.
check-pipeline:
	$(PYENV) python tools/pipeline_bench.py --json-out PIPELINE_r09.json

# Pipeline chaos soak: the fault sweep with the async pipeline layer
# kept live under every armed spec (pool-thread errors — including the
# io.prefetch queue hand-off — must classify + recover, answers must
# match the oracle, and no cell may leak prefetch streams, sinks, or
# pipeline memory reservations). Emits PIPELINE_SOAK_r09.json.
check-pipeline-soak:
	$(PYENV) python tools/chaos_soak.py --pipeline \
	  --json-out PIPELINE_SOAK_r09.json

# Trace gate: validator mini-catalogue tracing-off vs tracing-on — the
# enabled path must drop zero events at the default ring size and stay
# within noise of the disabled path, and the exported Chrome trace must
# be structurally valid. Emits TRACE_r08.json.
check-trace:
	$(PYENV) python tools/trace_report.py --bench --json-out TRACE_r08.json

# Perf-regression gate: the validator mini-catalogue against the
# committed PERF_BASELINE.json. Durations gate loosely (x2.5 + 2s —
# shared hosts are noisy); bytes_copied/moved per boundary gate tightly
# (x1.25 + 64KiB — byte counts are deterministic, a copy regression
# fails loudly). `make check-perf-update` rewrites the baseline after an
# intended change.
check-perf:
	$(PYENV) python tools/perf_baseline.py

check-perf-update:
	$(PYENV) python tools/perf_baseline.py --update

# Observability gate: catalogue A/B with resource accounting off vs on
# (sampler + live /metrics endpoint scraped mid-query and
# format-checked), one chaos cell under the monitor, and zero resource
# leaks. Emits OBS_r10.json.
check-obs:
	$(PYENV) python tools/perf_baseline.py --obs --json-out OBS_r10.json

# History gate: the catalogue recorded twice into a fresh history
# store, then a third pass with one 400ms serde.encode stall injected
# into q2 — the cross-run regression detector must flag the slowed
# stage with zero false positives on unperturbed stages, and the
# history-on catalogue must stay within noise of history-off. Emits
# HISTORY_r11.json.
check-history:
	$(PYENV) python tools/history_report.py --gate \
	  --json-out HISTORY_r11.json

# Multi-tenant service soak: 8 concurrent client sessions across 3
# tenants through runtime/service.QueryService — a clean round, a
# deterministic weighted-fairness probe, one round per representative
# (fault point x kind) with {"concurrent": true} specs, and an
# admission-stress round (1 slot, tiny queue). Every session must match
# the pandas oracle, rounds must leak nothing (consumers, pipeline
# streams, namespaced resources, orphans), breaker state must stay
# per-query, and overload must shed with typed rejections. Emits
# SERVICE_r13.json.
check-service:
	$(PYENV) python tools/chaos_soak.py --service \
	  --json-out SERVICE_r13.json

# Doctor gate: the validator catalogue run clean (every critical-path
# breakdown must sum to wall time within 5%, zero findings on clean
# queries), then two seeded perturbations the doctor must top-rank — a
# 400ms serde.encode stall (serde_bound) and a skewed-partition input
# (skewed_partition) — plus a byte-identical x3 determinism check and a
# mid-query scrape of the per-tenant blaze_slo_* gauges. Emits
# DOCTOR_r14.json.
check-doctor:
	$(PYENV) python tools/blaze_doctor.py --gate --json-out DOCTOR_r14.json

# Flight-recorder gate: the catalogue run clean with the recorder armed
# and live progress on (zero spurious dossiers, tap overhead under 1%
# min-of-repeats), a seeded 400ms serde.encode stall paired with an
# unmeetable 5ms tenant SLO through the service (exactly one slo_breach
# dossier, top finding serde_bound), and a mid-query /queries scrape
# (valid summary schema, monotone progress). Emits FLIGHT_r15.json.
check-flight:
	$(PYENV) python tools/blaze_inspect.py --gate --json-out FLIGHT_r15.json

# Process-executor gate (ISSUE 12): weak-scaling smoke at 1/2/4
# executor processes (task throughput must grow with seats), the
# validator catalogue carried by the pool at each seat count (answers
# diffed against the pandas oracle, >= 1 stage actually pooled), and
# SIGKILL / SIGTERM / hung kill-recovery rounds fired at a busy
# executor mid-stage — each must recover to the oracle with exactly one
# executor_death dossier, a shrink-then-recover capacity timeline, zero
# leaks, and zombie late results epoch-fenced. Emits EXECUTORS_r16.json.
check-executors:
	$(PYENV) python tools/chaos_soak.py --executors \
	  --json-out EXECUTORS_r16.json

# Durability gate (ISSUE 13): the corruption sweep bit-flips committed
# artifacts (shuffle .data frame, .index offsets, spill frame) at every
# CORRUPT_POINTS cell — each flip must be DETECTED by the checksum
# layer, the file QUARANTINED, shuffle outputs lineage-REPAIRED by
# re-running only the producing map task under a new epoch, and the
# answer still oracle-equal — plus the driver-crash round: a journaling
# subprocess driver SIGKILLed mid-query must, on restart, replay its
# write-ahead journal (verified committed stages reused with ZERO map
# tasks re-run, the crashed attempt billed failed with a driver_restart
# flight dossier) and answer oracle-equal. Emits DURABILITY_r17.json.
check-durability:
	$(PYENV) python tools/chaos_soak.py --durability --driver \
	  --json-out DURABILITY_r17.json

# Distributed-telemetry gate (ISSUE 14): a pooled chaos round (SIGKILL
# mid-stage) with the telemetry plane ON must answer oracle-equal AND
# yield ONE merged Chrome trace — driver + executor spans sharing
# query/task ids on per-executor pid rows, clock-aligned timestamps —
# with zero executors reporting dropped span rings and the run ledger
# carrying the workers' federated copy bytes; a telemetry on/off A/B
# over the pooled catalogue gates the plane's overhead below 2%.
# Emits DIST_OBS_r18.json.
check-dist-obs:
	$(PYENV) python tools/chaos_soak.py --dist-obs \
	  --json-out DIST_OBS_r18.json

# Partition-tolerance gate (ISSUE 15): every net.* wire-fault cell
# (delay / reset / blackhole / torn frame / duplicate delivery at the
# control channel, shuffle fetch, and telemetry paths) armed under a
# live 2-seat pool must answer oracle-equal with zero executor deaths
# and zero leaks; a transient control-socket reset must reconnect +
# resume (capacity untouched, no executor_death dossier, a
# control_reconnect trace event); an asymmetric partition held past
# executor_death_ms must cut exactly ONE dossier while the worker's
# lease expires and it self-fences (exit 17); and a rolling SIGTERM
# drain/restart of every seat under concurrent service load must lose
# zero queries with zero drain-attributed requeues. Emits
# NETWORK_r19.json.
check-network:
	$(PYENV) python tools/chaos_soak.py --network \
	  --json-out NETWORK_r19.json

# Elastic fleet & driver-HA gate (ISSUE 16): an 8-client catalogue
# burst against a 1-seat pool must autoscale UP on parked arrivals
# (typed scale_up decisions, ceiling respected) and drain back DOWN to
# the floor after quiesce through the decommission barrier (zero drain
# requeues, every answer oracle-equal); then a warm-standby subprocess
# must survive SIGKILL of the primary driver AND two of its four
# executors mid-query — epoch-bumped lease fencing, control-plane
# rebind with the two survivors ADOPTED, dead-writer journal replay,
# every query oracle-equal, exactly one driver_failover dossier, zero
# orphans. Emits ELASTIC_r20.json.
check-elastic:
	$(PYENV) python tools/chaos_soak.py --elastic \
	  --json-out ELASTIC_r20.json

# Durable exactly-once streaming gate (ISSUE 17): a checkpointed
# micro-batch stream over a growing parquet directory (QueryService
# session, 4-seat subprocess primary with fenced lease + manifest)
# must survive an executor SIGKILL mid-batch (checkpoints keep
# committing) AND a primary-driver SIGKILL with warm-standby takeover
# — the stream ADOPTED from its journal (streams_adoptable >= 1,
# never billed driver_restart), resumed from the last committed
# checkpoint (resumed_batches >= 1), final aggregation state
# pandas-oracle equal over EVERY published file (0 dropped, 0
# double-counted rows), checkpoint epochs strictly monotone across
# both drivers, exactly one driver_failover dossier. Emits
# STREAMING_r21.json.
check-streaming:
	$(PYENV) python tools/chaos_soak.py --streaming \
	  --json-out STREAMING_r21.json

check-autopilot:
	$(PYENV) python tools/chaos_soak.py --autopilot \
	  --json-out AUTOPILOT_r22.json

# Continuous-profiling acceptance (ISSUE 19): seeded-stall attribution
# in the collapsed-stack export, pooled SIGKILL sidecar recovery of
# executor samples, and the profiler on/off overhead A/B (<2%).
check-profile:
	$(PYENV) python tools/chaos_soak.py --profile \
	  --json-out PROFILE_r23.json

# Zero-copy data-plane acceptance (tools/zerocopy_bench.py): same-host
# mmap shuffle A/B on the real server/client (latency collapse +
# moved-only booking), the q3 catalogue query on a live pool (mmap
# on/off, oracle-equal, copied-bytes drop), and a 2M-row string-heavy
# dict-encoding A/B against the pandas oracle. Emits ZEROCOPY_r24.json.
check-zerocopy:
	$(PYENV) python tools/zerocopy_bench.py \
	  --json-out ZEROCOPY_r24.json

# Pre-warm the persistent compile caches (runtime/compile_service):
# replays the shape manifest + the TPC-DS catalogue into the XLA cache.
# Drop JAX_PLATFORMS=cpu (run bare `python -m ...`) to warm an attached
# chip; override scale/budget via WARM_ARGS.
WARM_ARGS = --rows 20000 --budget-seconds 1800
warm:
	$(PYENV) python -m blaze_tpu.runtime.compile_service --warm $(WARM_ARGS)
