# The commit gate. Run `make check` before EVERY snapshot commit —
# round 3 shipped with 38/252 tests red because this didn't exist.
# Mirrors the reference's CI gate (.github/workflows/tpcds.yml): the
# full suite on the virtual 8-device CPU mesh, plus the query-level
# validator matrix (which runs on the real chip when one is attached —
# the axon hook overrides JAX_PLATFORMS for plain scripts).

PYENV = XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu

.PHONY: check test validate

check: test validate
	@echo "CHECK OK — safe to commit"

test:
	$(PYENV) python -m pytest tests/ -q

validate:
	$(PYENV) python validate.py
