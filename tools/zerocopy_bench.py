"""Zero-copy data-plane acceptance bench (`make check-zerocopy`).

Proves the two ISSUE-24 fast paths actually deliver, on the REAL
runtime objects, and gates on it:

  fetch_ab    same-host shuffle A/B over a live ShuffleServer +
              ShuffleClient: serde frames committed through the
              crash-atomic pair commit (checksum footer stamped), then
              every partition fetched repeatedly with
              conf.shuffle_mmap_enabled on vs off. Gates: the mmap
              side answers byte-identical to the socket side, books
              bytes_moved ONLY (bytes_copied == 0 reader-side), and
              its p50 fetch latency is >= MIN_FETCH_SPEEDUP lower.

  pooled_ab   the q3 catalogue query on a live 2-seat ExecutorPool,
              mmap on vs off (a fresh pool per arm — workers snapshot
              conf at spawn). Gates: pandas-oracle-equal both arms,
              pool really carried stages, the on-arm recorded mmap
              hits and STRICTLY fewer bytes_copied_shuffle than the
              off-arm.

  dict_ab     string-heavy DICT_ROWS-row serde round trip, dict on vs
              off, decoded output compared against the pandas oracle
              column both arms. Gates: oracle-equal both arms, dict
              arm ships fewer serialized bytes AND fewer
              bytes_copied_serde, and dict_cols_encoded counted.

Emits ZEROCOPY_r24.json. Usage:
    JAX_PLATFORMS=cpu python tools/zerocopy_bench.py \
        --json-out ZEROCOPY_r24.json
"""

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# gate thresholds: latency gates loosely vs the x3 acceptance ask
# (shared CI hosts are noisy; the observed collapse is >>10x), byte
# counts gate strictly (deterministic for a fixed workload)
MIN_FETCH_SPEEDUP = 3.0
DICT_ROWS = 2_000_000
FETCH_PARTITIONS = 8
FETCH_ITERS = 40


def _commit_string_pair(tmpdir, rows=120_000):
    """Commit one string-heavy shuffle .data/.index pair (one serde
    frame per partition) through the real crash-atomic commit, returning
    (data_path, index_path, [frame bytes per partition])."""
    import numpy as np

    from blaze_tpu.columnar import (INT64, STRING, ColumnBatch, Field,
                                    Schema, serde)
    from blaze_tpu.runtime import artifacts

    rng = np.random.default_rng(7)
    cities = np.array([f"city_{i:03d}" for i in range(64)])
    schema = Schema([Field("k", INT64), Field("s", STRING)])
    per = rows // FETCH_PARTITIONS
    frames = []
    for p in range(FETCH_PARTITIONS):
        batch = ColumnBatch.from_numpy(
            {"k": rng.integers(0, 1 << 40, per),
             "s": list(cities[rng.integers(0, len(cities), per)])},
            schema)
        frames.append(serde.serialize_batch(batch))
    data = os.path.join(tmpdir, "zc_bench_0_0.data")
    index = os.path.join(tmpdir, "zc_bench_0_0.index")
    offsets = [0]
    for fr in frames:
        offsets.append(offsets[-1] + len(fr))

    def write(tmp_data, tmp_index):
        import struct

        with open(tmp_data, "wb") as f:
            f.write(b"".join(frames))
        with open(tmp_index, "wb") as f:
            f.write(struct.pack(f"<{len(offsets)}Q", *offsets))
        return tuple(len(fr) for fr in frames)

    artifacts.commit_shuffle_pair(write, data, index)
    return data, index, frames


def _fetch_arm(client, rid, mmap_on):
    """One A/B arm: fetch every partition FETCH_ITERS times, returning
    (per-call latencies, concatenated answer bytes, counter deltas)."""
    from blaze_tpu.config import conf
    from blaze_tpu.runtime import monitor

    saved = conf.shuffle_mmap_enabled
    conf.shuffle_mmap_enabled = mmap_on
    copied0, moved0 = monitor.copy_totals()
    zc0 = monitor.zerocopy_stats()
    lats = []
    answer = []
    try:
        for i in range(FETCH_ITERS):
            for p in range(FETCH_PARTITIONS):
                t0 = time.perf_counter()
                frames = client.fetch_frames(rid, p)
                lats.append(time.perf_counter() - t0)
                if i == 0:
                    answer.append(b"".join(bytes(f) for f in frames))
    finally:
        conf.shuffle_mmap_enabled = saved
    copied1, moved1 = monitor.copy_totals()
    zc1 = monitor.zerocopy_stats()
    return lats, b"".join(answer), {
        "bytes_copied_shuffle": copied1["shuffle"] - copied0["shuffle"],
        "bytes_moved_shuffle": moved1["shuffle"] - moved0["shuffle"],
        "mmap_hits": zc1["shuffle_mmap_hits"] - zc0["shuffle_mmap_hits"],
        "mmap_fallbacks": (zc1["shuffle_mmap_fallbacks"]
                           - zc0["shuffle_mmap_fallbacks"]),
    }


def _fetch_ab():
    from blaze_tpu.config import conf
    from blaze_tpu.runtime import monitor
    from blaze_tpu.runtime import shuffle_server as ss

    saved = (conf.artifact_checksums, conf.monitor_enabled)
    conf.artifact_checksums = True
    conf.monitor_enabled = True
    tmpdir = tempfile.mkdtemp(prefix="zc_fetch_")
    server = client = None
    rec = {"round": "fetch_ab", "partitions": FETCH_PARTITIONS,
           "iters": FETCH_ITERS}
    try:
        data, index, frames = _commit_string_pair(tmpdir)
        rec["segment_bytes"] = sum(len(f) for f in frames)
        server = ss.ShuffleServer(os.path.join(tmpdir, "zc.sock"))
        server.register_shuffle("zc/shuffle:0", [(data, index)])
        server.start()
        client = ss.ShuffleClient(server.sock_path)
        off_lats, off_ans, off_ctr = _fetch_arm(client, "zc/shuffle:0",
                                                mmap_on=False)
        on_lats, on_ans, on_ctr = _fetch_arm(client, "zc/shuffle:0",
                                             mmap_on=True)
        p50_off = statistics.median(off_lats)
        p50_on = statistics.median(on_lats)
        speedup = p50_off / p50_on if p50_on > 0 else float("inf")
        rec.update({
            "p50_off_us": round(p50_off * 1e6, 1),
            "p50_on_us": round(p50_on * 1e6, 1),
            "speedup_p50": round(speedup, 1),
            "off": off_ctr, "on": on_ctr,
            "answers_identical": on_ans == off_ans,
        })
        rec["ok"] = (
            rec["answers_identical"]
            and speedup >= MIN_FETCH_SPEEDUP
            # mmap hits book moved-only: the reader-side copy counter
            # must stay flat while moved carries the full volume
            and on_ctr["mmap_hits"] == FETCH_ITERS * FETCH_PARTITIONS
            and on_ctr["bytes_copied_shuffle"] == 0
            and on_ctr["bytes_moved_shuffle"] > 0
            and off_ctr["mmap_hits"] == 0
            and off_ctr["bytes_copied_shuffle"] > 0)
    finally:
        if client is not None:
            client.close()
        if server is not None:
            server.close()
        shutil.rmtree(tmpdir, ignore_errors=True)
        conf.artifact_checksums, conf.monitor_enabled = saved
        monitor.reset()
    return rec


def _pooled_arm(tables, mmap_on):
    from blaze_tpu.config import conf
    from blaze_tpu.runtime import executor_pool as ep
    from blaze_tpu.spark import validator
    from blaze_tpu.spark.local_runner import run_plan

    paths, frames = tables
    saved = conf.shuffle_mmap_enabled
    conf.shuffle_mmap_enabled = mmap_on
    pool = ep.ExecutorPool(count=2, slots=2)
    wd = tempfile.mkdtemp(prefix="zc_pool_")
    arm = {"mmap": mmap_on}
    try:
        pool.start()
        ep.activate(pool)
        plan, oracle = validator.QUERIES["q3_join_agg_sort"](
            paths, frames, "smj")
        info = {}
        t0 = time.perf_counter()
        out = run_plan(plan, num_partitions=4, work_dir=wd,
                       mesh_exchange="off", run_info=info)
        arm["seconds"] = round(time.perf_counter() - t0, 3)
        diff = validator._compare(
            validator._to_pandas(out).reset_index(drop=True),
            oracle().reset_index(drop=True))
        arm["oracle_equal"] = diff is None
        if diff is not None:
            arm["diff"] = diff
        arm["pool_stages"] = int(info.get("pool_stages", 0))
        for k in ("bytes_copied_shuffle", "bytes_moved_shuffle",
                  "bytes_copied_total", "shuffle_mmap_hits",
                  "shuffle_mmap_fallbacks"):
            arm[k] = int(info.get(k, 0))
    finally:
        ep.deactivate(pool)
        pool.close()
        shutil.rmtree(wd, ignore_errors=True)
        conf.shuffle_mmap_enabled = saved
    return arm


def _pooled_ab(tables):
    from blaze_tpu.config import conf
    from blaze_tpu.runtime import monitor

    saved = conf.monitor_enabled
    conf.monitor_enabled = True
    rec = {"round": "pooled_ab", "query": "q3_join_agg_sort",
           "executors": 2}
    try:
        rec["off"] = _pooled_arm(tables, mmap_on=False)
        rec["on"] = _pooled_arm(tables, mmap_on=True)
        on, off = rec["on"], rec["off"]
        rec["ok"] = (
            on["oracle_equal"] and off["oracle_equal"]
            and on["pool_stages"] > 0 and off["pool_stages"] > 0
            and on["shuffle_mmap_hits"] > 0
            and off["shuffle_mmap_hits"] == 0
            and on["bytes_copied_shuffle"] < off["bytes_copied_shuffle"])
    finally:
        conf.monitor_enabled = saved
        monitor.reset()
    return rec


def _dict_arm(vals_np, dict_on):
    import numpy as np

    from blaze_tpu.columnar import (INT64, STRING, ColumnBatch, Field,
                                    Schema, serde)
    from blaze_tpu.config import conf
    from blaze_tpu.runtime import monitor

    n = len(vals_np)
    schema = Schema([Field("k", INT64), Field("s", STRING)])
    batch = ColumnBatch.from_numpy(
        {"k": np.arange(n, dtype=np.int64), "s": list(vals_np)}, schema)
    saved = conf.dict_encode_strings
    conf.dict_encode_strings = dict_on
    copied0, _ = monitor.copy_totals()
    zc0 = monitor.zerocopy_stats()
    try:
        t0 = time.perf_counter()
        blob = serde.serialize_batch(batch)
        t_enc = time.perf_counter() - t0
        t0 = time.perf_counter()
        hb = serde.deserialize_batch_host(blob, schema)
        t_dec = time.perf_counter() - t0
    finally:
        conf.dict_encode_strings = saved
    copied1, _ = monitor.copy_totals()
    zc1 = monitor.zerocopy_stats()

    col = hb.cols[1]
    if col.kind == "dict":
        mat = np.ascontiguousarray(col.data[col.codes[:hb.num_rows]])
    else:
        mat = np.ascontiguousarray(col.data[:hb.num_rows])
    decoded = mat.view(f"S{mat.shape[1]}").ravel()
    # pandas oracle: the same column through a DataFrame round trip
    # (fixed-width S-compare strips trailing NULs on both sides)
    import pandas as pd

    oracle = pd.DataFrame({"s": vals_np})["s"].to_numpy().astype("S")
    return {
        "dict": dict_on, "rows": n,
        "encoded_kind": col.kind,
        "frame_bytes": len(blob),
        "encode_s": round(t_enc, 3), "decode_s": round(t_dec, 3),
        "bytes_copied_serde": copied1["serde"] - copied0["serde"],
        "dict_cols_encoded": (zc1["dict_cols_encoded"]
                              - zc0["dict_cols_encoded"]),
        "oracle_equal": bool(np.array_equal(decoded, oracle)),
    }


def _dict_ab(rows):
    import numpy as np

    from blaze_tpu.config import conf
    from blaze_tpu.runtime import monitor

    saved = conf.monitor_enabled
    conf.monitor_enabled = True
    rec = {"round": "dict_ab", "rows": rows}
    try:
        rng = np.random.default_rng(11)
        cities = np.array(
            ["tokyo", "delhi", "shanghai", "dhaka", "sao_paulo", "cairo",
             "mexico_city", "beijing", "mumbai", "osaka", "chongqing",
             "karachi", "kinshasa", "lagos", "istanbul", "buenos_aires"])
        vals = cities[rng.integers(0, len(cities), rows)]
        rec["off"] = _dict_arm(vals, dict_on=False)
        rec["on"] = _dict_arm(vals, dict_on=True)
        on, off = rec["on"], rec["off"]
        rec["frame_bytes_ratio"] = round(
            on["frame_bytes"] / max(off["frame_bytes"], 1), 3)
        rec["ok"] = (
            on["oracle_equal"] and off["oracle_equal"]
            and on["encoded_kind"] == "dict"
            and off["encoded_kind"] == "str"
            and on["dict_cols_encoded"] >= 1
            and off["dict_cols_encoded"] == 0
            and on["frame_bytes"] < off["frame_bytes"]
            and on["bytes_copied_serde"] < off["bytes_copied_serde"])
    finally:
        conf.monitor_enabled = saved
        monitor.reset()
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=8000,
                    help="catalogue table scale for the pooled A/B")
    ap.add_argument("--dict-rows", type=int, default=DICT_ROWS)
    ap.add_argument("--json-out", default="ZEROCOPY_r24.json")
    args = ap.parse_args()

    from blaze_tpu.spark import validator

    tmpdir = tempfile.mkdtemp(prefix="zc_tables_")
    try:
        tables = validator.generate_tables(tmpdir, rows=args.rows)
        rounds = [_fetch_ab(), _pooled_ab(tables), _dict_ab(args.dict_rows)]
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    for r in rounds:
        if r["round"] == "fetch_ab":
            print(f"[fetch_ab]  p50 off={r.get('p50_off_us')}us "
                  f"on={r.get('p50_on_us')}us "
                  f"speedup=x{r.get('speedup_p50')} "
                  f"{'OK' if r.get('ok') else 'FAILED'}", flush=True)
        elif r["round"] == "pooled_ab":
            print(f"[pooled_ab] copied_shuffle "
                  f"off={r['off'].get('bytes_copied_shuffle')} "
                  f"on={r['on'].get('bytes_copied_shuffle')} "
                  f"hits={r['on'].get('shuffle_mmap_hits')} "
                  f"{'OK' if r.get('ok') else 'FAILED'}", flush=True)
        else:
            print(f"[dict_ab]   frame off={r['off'].get('frame_bytes')} "
                  f"on={r['on'].get('frame_bytes')} "
                  f"(x{r.get('frame_bytes_ratio')}) "
                  f"{'OK' if r.get('ok') else 'FAILED'}", flush=True)

    report = {
        "rows": args.rows, "dict_rows": args.dict_rows,
        "ok": all(r.get("ok") for r in rounds),
        "bad": [r["round"] for r in rounds if not r.get("ok")],
        "rounds": rounds,
    }
    with open(args.json_out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"\nzerocopy bench {'OK' if report['ok'] else 'FAILED'} "
          f"-> {args.json_out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
