"""Mesh-exchange scaling measurement (VERDICT r4 #7 artifact).

Times the grouped all_to_all exchange (parallel/shuffle.py) at a given
virtual-CPU-mesh size and prints one JSON line. Driven per device count
by tools/run_mesh_scaling.sh, which aggregates MESH_SCALING_r{N}.json —
the multi-chip perf story the correctness-only dryrun lacked.

    XLA_FLAGS=--xla_force_host_platform_device_count=D \
    JAX_PLATFORMS=cpu python tools/mesh_scaling.py [P]

Measures steady-state per-exchange time (jit warm, scan-differenced so
dispatch overhead is excluded) for a per-device batch of 2^16 rows x
(i64 key + f64 value), P logical partitions over the D devices.
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402

# the .axon_site hook force-selects the TPU even with JAX_PLATFORMS=cpu
# in the env; the scaling curve is a virtual-CPU-mesh measurement
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as PS  # noqa: E402

from blaze_tpu.columnar import types as T  # noqa: E402
from blaze_tpu.columnar.batch import ColumnBatch  # noqa: E402
from blaze_tpu.parallel.shuffle import (  # noqa: E402
    mesh_shuffle_batch_grouped,
)

ROWS = 1 << 16
SCHEMA = T.Schema([T.Field("k", T.INT64), T.Field("v", T.FLOAT64)])


def main() -> None:
    P = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    D = len(jax.devices())
    kpd = -(-P // D)
    rng = np.random.default_rng(3)
    n = D * ROWS
    batch = ColumnBatch.from_numpy(
        {"k": rng.integers(0, 1 << 20, n).astype(np.int64),
         "v": rng.random(n)}, SCHEMA, capacity=n)
    num_rows = jnp.full((D,), ROWS, jnp.int32)
    mesh = Mesh(np.array(jax.devices()), ("p",))

    def step(local_cols, local_num_rows):
        b = ColumnBatch(SCHEMA, local_cols, local_num_rows[0], ROWS)
        out, counts, overflow = mesh_shuffle_batch_grouped(
            b, [0], "p", P, kpd, quota=ROWS * kpd)
        return out.columns, counts[None], overflow[None]

    from blaze_tpu.parallel.stage_exchange import _shard_map

    inner = _shard_map(step, mesh=mesh, in_specs=(PS("p"), PS("p")),
                       out_specs=(PS("p"), PS("p"), PS("p")))

    def scan_n(reps):
        def run(cols, num_rows):
            def body(c, _):
                out_cols, counts, ovf = inner(
                    jax.tree_util.tree_map(
                        lambda a: a + c.astype(a.dtype)
                        if jnp.issubdtype(a.dtype, jnp.integer) else a,
                        cols),
                    num_rows)
                s = sum(jnp.sum(x).astype(jnp.int64)
                        for x in jax.tree_util.tree_leaves(counts))
                return c + (s % 7).astype(jnp.int32), None
            c, _ = jax.lax.scan(body, jnp.int32(0), None, length=reps)
            return c
        return jax.jit(run)

    f1, f2 = scan_n(3), scan_n(13)
    args = (jax.tree_util.tree_map(lambda c: c, batch.columns), num_rows)
    np.asarray(f1(*args))
    np.asarray(f2(*args))
    t = time.time(); np.asarray(f1(*args)); d1 = time.time() - t
    t = time.time(); np.asarray(f2(*args)); d2 = time.time() - t
    per = (d2 - d1) / 10
    total_bytes = D * ROWS * 16  # i64 + f64, validity-free
    print(json.dumps({
        "devices": D, "partitions": P, "rows_per_device": ROWS,
        "exchange_ms": round(per * 1e3, 2),
        "bytes_per_s": round(total_bytes / per, 0),
    }))


if __name__ == "__main__":
    main()
