"""blaze-top: live console over the engine's resource registry.

Renders running queries, task-pool occupancy, memory high-water marks,
copy-boundary totals, compile-cache traffic and breaker state — either
from THIS process's registry (embedders, --demo) or by scraping a
running engine's Prometheus endpoint (--url, any process that set
conf.metrics_port).

Usage:
    python tools/blaze_top.py --once                  # one local snapshot
    python tools/blaze_top.py --url http://host:9109/metrics
    python tools/blaze_top.py --demo                  # run the catalogue
                                                      # in-process & watch
"""

import argparse
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BAR_W = 30


def _bar(used: float, total: float) -> str:
    frac = 0.0 if total <= 0 else min(max(used / total, 0.0), 1.0)
    n = int(round(frac * BAR_W))
    return "[" + "#" * n + "-" * (BAR_W - n) + f"] {frac * 100:5.1f}%"


def parse_prometheus(text: str) -> dict:
    """{metric_name: value} / {metric_name{labels}: value} from the text
    exposition format (enough structure for rendering, not a full
    client)."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            key, value = line.rsplit(" ", 1)
            out[key] = float(value)
        except ValueError:
            continue
    return out


def render(metrics: dict, source: str) -> str:
    def g(name, default=0.0):
        return metrics.get(name, default)

    from blaze_tpu.runtime.trace import human_bytes

    lines = [f"blaze-top — {source} — {time.strftime('%H:%M:%S')}", ""]
    used, total = g("blaze_mem_used_bytes"), g("blaze_mem_budget_bytes")
    lines.append(f"memory   {_bar(used, total)}  "
                 f"used={human_bytes(int(used))} "
                 f"budget={human_bytes(int(total))} "
                 f"hwm={human_bytes(int(g('blaze_mem_peak_bytes')))}")
    lines.append(
        f"         pipeline_reserved="
        f"{human_bytes(int(g('blaze_mem_pipeline_reserved_bytes')))} "
        f"spill_pages={human_bytes(int(g('blaze_spill_pages_bytes')))} "
        f"spilled={human_bytes(int(g('blaze_spilled_bytes_total')))} "
        f"({int(g('blaze_spill_count_total'))} spills)")
    lines.append("")
    copy_cells = []
    for b in ("serde", "ffi", "shuffle", "spill", "fallback"):
        key = 'blaze_bytes_copied_total{boundary="%s"}' % b
        copy_cells.append(f"{b}={human_bytes(int(g(key)))}")
    lines.append("copies   " + "  ".join(copy_cells))
    lines.append("")
    lines.append(
        f"tasks    active={int(g('blaze_supervisor_active_tasks'))} "
        f"queries={int(g('blaze_queries_running'))} "
        f"pipeline_streams={int(g('blaze_pipeline_live_streams'))} "
        f"queued={int(g('blaze_pipeline_queue_depth'))}")
    lines.append(
        f"compile  hits={int(g('blaze_compile_cache_hits'))} "
        f"misses={int(g('blaze_compile_cache_misses'))} "
        f"compiled={int(g('blaze_compile_compile_count'))}")
    dropped = int(g("blaze_trace_dropped_events_total"))
    lines.append(
        f"trace    buffered={int(g('blaze_trace_buffer_events'))}"
        f"/{int(g('blaze_trace_buffer_capacity'))} "
        f"dropped={dropped}"
        + ("  ** TRACE RING OVERFLOWED **" if dropped else "")
        + f"  monitor_ring={int(g('blaze_monitor_ring_samples'))}"
        f"/{int(g('blaze_monitor_ring_capacity'))}")
    trips = int(g("blaze_faults_breaker_trips"))
    lines.append(
        f"faults   retries={int(g('blaze_faults_retries'))} "
        f"injected={int(g('blaze_faults_faults_injected'))} "
        f"breaker_trips={trips}"
        + ("  ** BREAKER TRIPPED **" if trips else ""))
    rejected = int(g("blaze_admission_rejected_total"))
    lines.append(
        f"service  queue={int(g('blaze_admission_queue_depth'))} "
        f"admitted={int(g('blaze_admission_admitted_total'))} "
        f"parked={int(g('blaze_admission_parked_total'))} "
        f"rejected={rejected}"
        + ("  ** LOAD SHEDDING **" if rejected else ""))
    role_rows = [(k, v) for k, v in metrics.items()
                 if k.startswith("blaze_driver_role{") and v]
    if role_rows or g("blaze_autoscale_target_seats"):
        role = (role_rows[0][0].split('role="', 1)[-1].rstrip('"}')
                if role_rows else "primary")
        ups = int(g('blaze_autoscale_decisions_total{direction="up"}'))
        downs = int(
            g('blaze_autoscale_decisions_total{direction="down"}'))
        lines.append(
            f"fleet    role={role} "
            f"target_seats={int(g('blaze_autoscale_target_seats'))} "
            f"scale_ups={ups} scale_downs={downs}"
            + ("  ** STANDBY **" if role == "standby" else ""))
    rollback_rows = [(k, v) for k, v in metrics.items()
                     if k.startswith("blaze_autopilot_rollbacks_total{")]
    if ("blaze_autopilot_overlays_active" in metrics or rollback_rows):
        rollbacks = int(sum(v for _, v in rollback_rows))
        by_knob = " ".join(
            k.split('knob="', 1)[-1].rstrip('"}') + f"={int(v)}"
            for k, v in sorted(rollback_rows) if v)
        lines.append(
            f"autopilot overlays="
            f"{int(g('blaze_autopilot_overlays_active'))} "
            f"promotions={int(g('blaze_autopilot_promotions_total'))} "
            f"rollbacks={rollbacks}"
            + (f" [{by_knob}]" if by_knob else "")
            + ("  ** ROLLED BACK **" if rollbacks else ""))
    if "blaze_profile_samples_total" in metrics:
        p_dropped = int(g("blaze_profile_dropped_total"))
        lines.append(
            f"profile  samples={int(g('blaze_profile_samples_total'))} "
            f"remote={int(g('blaze_profile_remote_samples_total'))} "
            f"recovered="
            f"{int(g('blaze_profile_recovered_samples_total'))} "
            f"stacks={int(g('blaze_profile_stacks'))} "
            f"duty={g('blaze_profile_fleet_duty_pct'):.2f}%"
            + (f"  ** {p_dropped} SAMPLES DROPPED **" if p_dropped
               else ""))
    exec_rows = [(k, v) for k, v in metrics.items()
                 if k.startswith("blaze_executor_up{")]
    if exec_rows:
        live = int(g("blaze_executor_live"))
        draining = sum(
            1 for k, dv in metrics.items()
            if k.startswith("blaze_executor_draining{") and dv)

        def _state(key, up):
            if not up:
                return "=DOWN"
            sel = key[len("blaze_executor_up"):]
            if g("blaze_executor_draining" + sel):
                return "=draining"
            return "=up"

        up = " ".join(
            k.split('exec_id="', 1)[-1].rstrip('"}') + _state(k, v)
            for k, v in sorted(exec_rows))
        lines.append(
            f"execs    live={live} "
            f"capacity={int(g('blaze_service_capacity'))} "
            f"deaths={int(g('blaze_executor_deaths_total'))} "
            f"restarts={int(g('blaze_executor_restarts_total'))} "
            f"reconnects="
            f"{int(sum(v for k, v in metrics.items() if k.startswith('blaze_executor_reconnects_total{')))} "
            f"drains={int(g('blaze_executor_drains_total'))}  {up}"
            + ("  ** NO EXECUTORS LIVE **" if live == 0 else "")
            + (f"  ** {draining} DRAINING **" if draining else ""))
        # per-executor pane, fed by the federation gauges: one row per
        # exec_id with heartbeat freshness, occupancy and telemetry flow
        for key, v in sorted(exec_rows):
            ex = key.split('exec_id="', 1)[-1].rstrip('"}')
            sel = '{exec_id="' + ex + '"}'
            hb = g("blaze_executor_heartbeat_age_ms" + sel)
            lines.append(
                f"  exec   {ex:<16} "
                f"hb={hb:6.0f}ms "
                f"busy={int(g('blaze_executor_busy_slots' + sel))} "
                f"done={int(g('blaze_executor_tasks_done_total' + sel))} "
                f"tel={human_bytes(int(g('blaze_executor_telemetry_bytes_total' + sel)))}"
                + (f" rc={int(g('blaze_executor_reconnects_total' + sel))}"
                   if g("blaze_executor_reconnects_total" + sel) else "")
                + (" ** DRAINING **"
                   if g("blaze_executor_draining" + sel) else "")
                + ("" if v else "  ** DOWN **"))
    stream_rows = [(k, v) for k, v in metrics.items()
                   if k.startswith("blaze_stream_lag_ms{")]
    for key, lag in sorted(stream_rows):
        # blaze_stream_lag_ms{qid="stream-7"} -> stream-7
        sid = key.split('qid="', 1)[-1].rstrip('"}')
        sel = '{qid="' + sid + '"}'
        lines.append(
            f"stream   {sid:<16} lag={lag:6.0f}ms "
            f"batches={int(g('blaze_stream_batches_total' + sel))} "
            f"ckpt={human_bytes(int(g('blaze_stream_checkpoint_bytes' + sel)))}")
    tenants = [(k, v) for k, v in metrics.items()
               if k.startswith("blaze_tenant_mem_used_bytes{")]
    for key, v in sorted(tenants):
        # blaze_tenant_mem_used_bytes{tenant="a"} -> a
        label = key.split('tenant="', 1)[-1].rstrip('"}')
        lines.append(f"tenant   {label:<16} mem={human_bytes(int(v))}")
    slo_rows = [(k, v) for k, v in metrics.items()
                if k.startswith("blaze_slo_attainment{")]
    for key, v in sorted(slo_rows):
        label = key.split('tenant="', 1)[-1].rstrip('"}')
        sel = 'blaze_slo_%s{tenant="' + label + '"}'
        burn = metrics.get(sel % "burn_rate", 0.0)
        lines.append(
            f"slo      {label:<16} "
            f"objective={int(metrics.get(sel % 'objective_ms', 0))}ms "
            f"attainment={v * 100:5.1f}% "
            f"burn={burn:4.1f}x "
            f"breaches={int(metrics.get(sel % 'breaches_total', 0))}"
            + ("  ** SLO BURNING **" if burn > 1.0 else ""))
    leaks = int(g("blaze_resource_leaks_total"))
    if leaks:
        lines.append(f"LEAKS    {leaks} resource leak(s) recorded")
    return "\n".join(lines)


def local_metrics() -> dict:
    from blaze_tpu.runtime import monitor

    m = parse_prometheus(monitor.prometheus_text())
    # in-process bonus: per-query live rows (not in the scrape payload)
    running = monitor.running_queries()
    if running:
        m["__queries__"] = running
    return m


def render_queries(metrics: dict) -> str:
    rows = metrics.get("__queries__") or []
    if not rows:
        return ""
    from blaze_tpu.runtime.trace import human_bytes

    lines = ["", "queries:"]
    for q in rows:
        lines.append(f"  {q['query_id']:<16} {q['seconds']:>6.1f}s  "
                     f"copied={human_bytes(q['bytes_copied'])} "
                     f"moved={human_bytes(q['bytes_moved'])}")
    return "\n".join(lines)


def _demo_workload(rows: int):
    """Run the validator catalogue on a loop in a daemon thread so the
    console has something to watch."""
    import tempfile
    import threading

    from blaze_tpu.config import conf
    from blaze_tpu.spark import validator
    from blaze_tpu.spark.local_runner import run_plan

    conf.update(trace_enabled=True, monitor_enabled=True)
    tmp = tempfile.mkdtemp(prefix="blaze_top_demo_")
    paths, frames = validator.generate_tables(tmp, rows=rows)

    def loop():
        while True:
            for query, mode in (("q1_scan_filter_project", "bhj"),
                                ("q2_q06_core_agg", "bhj"),
                                ("q3_join_agg_sort", "smj")):
                plan, _ = validator.QUERIES[query](paths, frames, mode)
                run_plan(plan, num_partitions=4, mesh_exchange="off")

    threading.Thread(target=loop, daemon=True).start()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--url", default=None,
                    help="Prometheus endpoint of a running engine "
                         "(e.g. http://host:9109/metrics)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--demo", action="store_true",
                    help="drive a catalogue loop in-process to watch")
    ap.add_argument("--rows", type=int, default=4000)
    args = ap.parse_args()

    if args.demo:
        _demo_workload(args.rows)

    while True:
        if args.url:
            text = urllib.request.urlopen(args.url, timeout=10) \
                .read().decode()
            metrics, source = parse_prometheus(text), args.url
        else:
            metrics, source = local_metrics(), "in-process"
        frame = render(metrics, source) + render_queries(metrics)
        if args.once:
            print(frame)
            return 0
        # clear + home, no curses dependency
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
