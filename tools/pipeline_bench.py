"""Pipelined-execution benchmark (ISSUE 5 artifact: `PIPELINE_r09.json`).

Two measurements, both CPU-runnable in the tier-1 container:

  microbench  an I/O-bound shuffle-read loop over REAL serde frames with
              synthetic per-frame I/O latency (sleep) and synthetic
              per-batch device compute (sleep): serial iteration vs
              `pipeline.prefetch`. With producer and consumer each ~T
              per item the serial loop costs ~2T/item and the pipelined
              loop ~T/item, so the gate demands >= 1.3x (loose enough
              for shared-CPU jitter, far above noise). The write-side
              `pipeline.Sink` is measured the same way. Queue occupancy
              and overlap % come from the stream's own stats.

  catalogue   the validator mini-catalogue with enable_pipeline off vs
              on: BOTH directions must land within a loose noise gate —
              off slower than on out of noise means the serial
              (restores-PR-4-behavior) path regressed; on slower than
              off out of noise means pipelining costs real queries more
              than its machinery saves.

    JAX_PLATFORMS=cpu python tools/pipeline_bench.py \
        --json-out PIPELINE_r09.json
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERIES = [  # same coverage as tools/chaos_soak.py
    ("q1_scan_filter_project", "bhj"),
    ("q2_q06_core_agg", "bhj"),
    ("q3_join_agg_sort", "smj"),
]


def _make_frames(rows, n_frames):
    """Serialized shuffle-style frames of a realistic mixed schema."""
    import numpy as np

    from blaze_tpu.columnar import serde
    from blaze_tpu.columnar import types as T
    from blaze_tpu.columnar.batch import ColumnBatch
    from blaze_tpu.columnar.types import Field, Schema

    schema = Schema([Field("k", T.INT64), Field("v", T.FLOAT64),
                     Field("s", T.STRING)])
    rng = np.random.default_rng(7)
    frames = []
    for _ in range(n_frames):
        b = ColumnBatch.from_numpy(
            {"k": rng.integers(0, 1 << 20, rows),
             "v": rng.random(rows),
             "s": np.array([f"row-{i:08d}" for i in range(rows)])},
            schema)
        frames.append(serde.serialize_batch(b))
    return schema, frames


def microbench(args):
    from blaze_tpu.columnar import serde
    from blaze_tpu.runtime import pipeline

    schema, frames = _make_frames(args.rows, args.frames)
    io_s = args.io_ms / 1000.0
    compute_s = args.compute_ms / 1000.0

    def produce():
        # a shuffle read: fetch latency (synthetic) + a REAL frame
        # decompress+decode on whatever thread runs this generator
        for fr in frames:
            time.sleep(io_s)
            yield serde.deserialize_batch_host(fr, schema)

    def consume(stream):
        # "device compute" per batch, on the consumer thread
        n = 0
        for hb in stream:
            time.sleep(compute_s)
            n += hb.num_rows
        return n

    # warm (allocator, imports)
    consume(produce())

    t0 = time.perf_counter()
    rows_serial = consume(produce())
    t_serial = time.perf_counter() - t0

    t0 = time.perf_counter()
    s = pipeline.prefetch(produce(), args.depth, name="bench")
    rows_pipe = consume(s)
    t_pipe = time.perf_counter() - t0
    stats = s.stats()

    assert rows_serial == rows_pipe, (rows_serial, rows_pipe)

    # write side: compute (consumer thread) + frame write (sink worker)
    sunk = []

    def write(fr):
        time.sleep(io_s)
        sunk.append(len(fr))

    def drive(sink_like):
        for fr in frames:
            time.sleep(compute_s)
            sink_like(fr)

    t0 = time.perf_counter()
    drive(write)
    t_sink_serial = time.perf_counter() - t0

    sk = pipeline.Sink(write, args.depth, name="bench_sink")
    t0 = time.perf_counter()
    drive(lambda fr: sk.submit(fr, len(fr)))
    sk.close()
    t_sink_pipe = time.perf_counter() - t0

    return {
        "frames": args.frames,
        "rows_per_frame": args.rows,
        "synthetic_io_ms": args.io_ms,
        "synthetic_compute_ms": args.compute_ms,
        "prefetch_depth": args.depth,
        "serial_s": round(t_serial, 3),
        "pipelined_s": round(t_pipe, 3),
        "speedup": round(t_serial / t_pipe, 2) if t_pipe else None,
        "sink_serial_s": round(t_sink_serial, 3),
        "sink_pipelined_s": round(t_sink_pipe, 3),
        "sink_speedup": (round(t_sink_serial / t_sink_pipe, 2)
                         if t_sink_pipe else None),
        "queue_max_depth": stats["max_depth"],
        "producer_occupancy_pct": stats["producer_occupancy_pct"],
        "overlap_pct": stats["overlap_pct"],
    }


def catalogue_ab(args):
    from blaze_tpu.config import conf
    from blaze_tpu.spark import validator
    from blaze_tpu.spark.local_runner import run_plan

    tmpdir = tempfile.mkdtemp(prefix="pipeline_bench_tables_")
    try:
        paths, frames = validator.generate_tables(tmpdir,
                                                  rows=args.catalogue_rows)

        def catalogue():
            t0 = time.time()
            for query, mode in QUERIES:
                plan, _ = validator.QUERIES[query](paths, frames, mode)
                run_plan(plan, num_partitions=4, mesh_exchange="off")
            return round(time.time() - t0, 3)

        saved = conf.enable_pipeline
        try:
            catalogue()  # warm jit caches so the A/B measures the harness
            conf.enable_pipeline = False
            t_off = catalogue()
            conf.enable_pipeline = True
            t_on = catalogue()
        finally:
            conf.enable_pipeline = saved
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return {"catalogue_rows": args.catalogue_rows,
            "catalogue_pipeline_off_s": t_off,
            "catalogue_pipeline_on_s": t_on}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--frames", type=int, default=30)
    ap.add_argument("--rows", type=int, default=4096,
                    help="rows per microbench frame")
    ap.add_argument("--io-ms", type=float, default=8.0,
                    help="synthetic per-frame I/O latency")
    ap.add_argument("--compute-ms", type=float, default=8.0,
                    help="synthetic per-batch compute time")
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--catalogue-rows", type=int, default=8000)
    ap.add_argument("--json-out", default="PIPELINE_r09.json")
    args = ap.parse_args()

    from blaze_tpu.runtime import pipeline

    report = microbench(args)
    report.update(catalogue_ab(args))
    report["live_streams_after"] = pipeline.live_streams()

    problems = []
    if report["speedup"] is None or report["speedup"] < 1.3:
        problems.append(f"pipelined speedup {report['speedup']} < 1.3x "
                        f"on the I/O-bound microbench")
    t_off = report["catalogue_pipeline_off_s"]
    t_on = report["catalogue_pipeline_on_s"]
    # noise gates, not microbenches: a short catalogue pass jitters tens
    # of percent on a shared CPU host, so the bounds are deliberately
    # loose — they catch structural regressions, not 5% drifts
    if t_off > t_on * 1.5 + 1.0:
        problems.append(f"disabled-path overhead out of noise: "
                        f"off={t_off}s on={t_on}s")
    if t_on > t_off * 1.5 + 1.0:
        problems.append(f"pipelining slows the catalogue out of noise: "
                        f"on={t_on}s off={t_off}s")
    if report["live_streams_after"]:
        problems.append(f"{report['live_streams_after']} leaked streams")
    report["problems"] = problems
    report["ok"] = not problems

    with open(args.json_out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"pipeline bench: serial={report['serial_s']}s "
          f"pipelined={report['pipelined_s']}s "
          f"speedup={report['speedup']}x overlap={report['overlap_pct']}% "
          f"sink={report['sink_speedup']}x")
    print(f"catalogue: off={t_off}s on={t_on}s")
    print(f"pipeline bench {'OK' if report['ok'] else 'FAILED'} "
          f"-> {args.json_out}")
    for p in problems:
        print(f"  problem: {p}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
