"""blaze-prof: render/convert continuous-profiling artifacts.

The engine's sampling profiler (runtime/profiler.py, on while
conf.profile_enabled) exports two artifacts per query into
conf.profile_export_dir — ``profile_<qid>.collapsed`` (flamegraph.pl
collapsed-stack text) and ``profile_<qid>.speedscope.json`` — and
embeds a ``profile_window`` block in hang/deadline flight dossiers.
This tool reads any of those and prints a hot-frames table, the
collapsed text, or a speedscope document (paste into speedscope.app):

    python tools/blaze_prof.py PROF_DIR --query q123-1        # top frames
    python tools/blaze_prof.py PROF_DIR --list                # queries seen
    python tools/blaze_prof.py profile_q123-1.collapsed --format speedscope
    python tools/blaze_prof.py dossier_..._hang_q1.json --format collapsed

Collapsed lines lead with synthetic ``query:<id>;stage:<id>;exec:<id>``
frames, so flamegraph.pl groups the fleet-merged profile by query, then
stage, then executor.
"""

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

Pairs = List[Tuple[str, int]]

_COLLAPSED_RE = re.compile(r"^(?P<stack>.+) (?P<count>\d+)$")


def parse_collapsed(text: str) -> Pairs:
    """``frame;frame;frame count`` lines -> (stack, count) pairs.
    Malformed lines are skipped (the format is whitespace-hostile by
    construction: frames never contain spaces)."""
    pairs: Pairs = []
    for line in text.splitlines():
        m = _COLLAPSED_RE.match(line.strip())
        if m:
            pairs.append((m.group("stack"), int(m.group("count"))))
    return pairs


def window_pairs(window: dict) -> Pairs:
    """A flight dossier's profile_window block -> (stack, count)
    pairs with the same synthetic attribution prefix the engine's
    collapsed export uses."""
    pairs: Pairs = []
    qid = window.get("query_id") or "-"
    for s in window.get("stacks") or []:
        prefix = [f"query:{qid}"]
        if s.get("stage_id"):
            prefix.append(f"stage:{s['stage_id']}")
        if s.get("exec"):
            prefix.append(f"exec:{s['exec']}")
        pairs.append((";".join(prefix + [s.get("stack", "")]),
                      int(s.get("samples", 0))))
    return pairs


def hot_frames(pairs: Pairs, top: int = 10) -> List[dict]:
    """Leaf self-time ranking over (stack, count) pairs (attribution
    prefix frames never rank: a leaf is real code)."""
    agg: Dict[str, int] = {}
    total = 0
    for stack, n in pairs:
        leaf = stack.rsplit(";", 1)[-1]
        agg[leaf] = agg.get(leaf, 0) + n
        total += n
    if not total:
        return []
    ranked = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))[:top]
    return [{"frame": f, "samples": n,
             "pct": round(100.0 * n / total, 1)} for f, n in ranked]


def to_collapsed(pairs: Pairs) -> str:
    return "".join(f"{stack} {n}\n" for stack, n in pairs)


def to_speedscope(pairs: Pairs, name: str = "blaze profile") -> dict:
    from blaze_tpu.runtime.profiler import stacks_to_speedscope

    return stacks_to_speedscope(pairs, name=name)


def load_pairs(source: str, query: str = "") -> Tuple[Pairs, str]:
    """Resolve SOURCE (export dir / .collapsed file / dossier or
    speedscope .json) into (pairs, display name)."""
    if os.path.isdir(source):
        names = sorted(n for n in os.listdir(source)
                       if n.startswith("profile_")
                       and n.endswith(".collapsed"))
        if query:
            names = [n for n in names
                     if n == f"profile_{query}.collapsed"]
        if not names:
            raise SystemExit(f"no profile_*.collapsed under {source}"
                             + (f" for query {query!r}" if query else ""))
        pairs: Pairs = []
        for n in names:
            with open(os.path.join(source, n), encoding="utf-8") as f:
                pairs.extend(parse_collapsed(f.read()))
        return pairs, query or f"{len(names)} queries"
    with open(source, encoding="utf-8") as f:
        text = f.read()
    if source.endswith(".json"):
        doc = json.loads(text)
        if isinstance(doc.get("profile_window"), dict):  # flight dossier
            win = doc["profile_window"]
            return window_pairs(win), str(win.get("query_id") or source)
        if "profiles" in doc and "shared" in doc:  # speedscope passthru
            frames = [fr.get("name", "?")
                      for fr in doc["shared"].get("frames", [])]
            prof = (doc.get("profiles") or [{}])[0]
            pairs = []
            for ixs, w in zip(prof.get("samples") or [],
                              prof.get("weights") or []):
                pairs.append((";".join(frames[i] for i in ixs), int(w)))
            return pairs, str(doc.get("name") or source)
        raise SystemExit(f"{source}: json carries no profile_window "
                         f"and is not a speedscope document")
    return parse_collapsed(text), os.path.basename(source)


def list_queries(source: str) -> List[str]:
    if not os.path.isdir(source):
        raise SystemExit("--list needs an export dir")
    out = []
    for n in sorted(os.listdir(source)):
        if n.startswith("profile_") and n.endswith(".collapsed"):
            out.append(n[len("profile_"):-len(".collapsed")])
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render/convert blaze continuous-profiling artifacts")
    ap.add_argument("source", help="export dir, .collapsed file, flight "
                                   "dossier .json or speedscope .json")
    ap.add_argument("--query", default="", help="restrict an export dir "
                                                "to one query id")
    ap.add_argument("--format", default="top",
                    choices=("top", "collapsed", "speedscope"))
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the hot-frames table")
    ap.add_argument("--out", default="", help="write here instead of "
                                              "stdout")
    ap.add_argument("--list", action="store_true",
                    help="list query ids present in an export dir")
    args = ap.parse_args(argv)

    if args.list:
        for qid in list_queries(args.source):
            print(qid)
        return 0

    pairs, name = load_pairs(args.source, args.query)
    if args.format == "collapsed":
        text = to_collapsed(pairs)
    elif args.format == "speedscope":
        text = json.dumps(to_speedscope(pairs, name=f"blaze {name}"),
                          indent=1)
    else:
        total = sum(n for _, n in pairs)
        rows = hot_frames(pairs, top=args.top)
        head = f"{name}: {total} samples, {len(pairs)} distinct stacks"
        body = [f"  {r['frame']:<48} {r['samples']:>8}  {r['pct']:>5.1f}%"
                for r in rows]
        text = "\n".join([head] + body) + "\n"
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
