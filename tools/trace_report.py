"""Trace reporting + overhead gate (ISSUE 4 artifact: `TRACE_r08.json`).

Two modes:

  summarize   `python tools/trace_report.py <trace_dir>` — digest the
              directory runtime/trace.py exports into (ledger.jsonl +
              trace_<qid>.json): per-query durations, the slowest stages
              across all queries, retry/speculation/degrade rates, and
              merged histogram percentiles. The terminal analog of
              loading every Chrome trace into Perfetto at once.

  --bench     run the validator mini-catalogue (the chaos_soak QUERIES)
              tracing-off vs tracing-on and emit `TRACE_r08.json`: the
              enabled path must drop ZERO events at the default buffer
              size and stay within noise of the disabled path (the
              "tracing is cheap enough to leave on" claim), and the
              exported Chrome trace must be structurally valid
              (traceEvents list, X/i/M phases, µs timestamps).

    JAX_PLATFORMS=cpu python tools/trace_report.py --bench \
        --json-out TRACE_r08.json
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERIES = [  # same coverage as tools/chaos_soak.py
    ("q1_scan_filter_project", "bhj"),
    ("q2_q06_core_agg", "bhj"),
    ("q3_join_agg_sort", "smj"),
]


# -- summarize mode ----------------------------------------------------------


def load_ledger(trace_dir):
    path = os.path.join(trace_dir, "ledger.jsonl")
    entries = []
    if not os.path.exists(path):
        return entries
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    entries.append(json.loads(line))
                except ValueError:
                    continue  # crash-torn line: skip, don't die
    return entries


def summarize(trace_dir):
    from blaze_tpu.runtime.trace import human_bytes

    entries = load_ledger(trace_dir)
    if not entries:
        print(f"no ledger.jsonl under {trace_dir}")
        return 1
    lines = [f"== trace report: {trace_dir} ({len(entries)} queries) =="]

    durs = sorted(e.get("duration_ms") or 0 for e in entries)
    lines.append(
        f"query duration_ms: p50={durs[len(durs) // 2]:.1f} "
        f"max={durs[-1]:.1f}")

    # slowest stages across every query
    stages = [(s.get("ms", 0), e["query_id"], s) for e in entries
              for s in e.get("stages", [])]
    stages.sort(reverse=True)
    lines.append("-- slowest stages --")
    for ms, qid, s in stages[:8]:
        lines.append(
            f"  {ms:9.1f}ms  {qid} stage {s.get('stage_id')} "
            f"{s.get('kind')}[{s.get('transport') or '-'}] "
            f"tasks={s.get('tasks')} bytes={human_bytes(s.get('bytes') or 0)}")

    # resilience-event rates (events per query)
    totals = {}
    for e in entries:
        for k, v in (e.get("resilience_events") or {}).items():
            totals[k] = totals.get(k, 0) + v
    if totals:
        lines.append("-- resilience events (total, per-query rate) --")
        for k in sorted(totals):
            lines.append(f"  {k}: {totals[k]} "
                         f"({totals[k] / len(entries):.2f}/query)")

    # histogram percentiles: the ledger stores per-query percentiles;
    # report the worst (max) p95/p99 seen — the tail a soak cares about
    hists = {}
    for e in entries:
        for name, h in (e.get("histograms") or {}).items():
            cur = hists.setdefault(name, {"count": 0, "p50": 0,
                                          "p95": 0, "p99": 0, "max": 0})
            cur["count"] += h.get("count", 0)
            for p in ("p50", "p95", "p99", "max"):
                cur[p] = max(cur[p], h.get(p) or 0)
    if hists:
        lines.append("-- distributions (worst per-query percentiles) --")
        for name in sorted(hists):
            h = hists[name]
            lines.append(f"  {name}: n={h['count']} p50<={h['p50']} "
                         f"p95<={h['p95']} p99<={h['p99']} max={h['max']}")

    # resource roll-ups (monitor.py counters the runner merges into each
    # ledger line): copy traffic by boundary, memory/spill high-water
    counters = [e.get("counters") or {} for e in entries]

    def csum(key):
        return sum(int(c.get(key, 0)) for c in counters)

    copied = {b: csum(f"bytes_copied_{b}")
              for b in ("serde", "ffi", "shuffle", "spill", "fallback")}
    if any(copied.values()) or csum("bytes_moved_total"):
        lines.append("-- resource roll-up (all queries) --")
        moved = csum("bytes_moved_total")
        total = csum("bytes_copied_total")
        pct = round(100.0 * total / moved) if moved else 0
        lines.append(f"  moved {human_bytes(moved)}, copied "
                     f"{human_bytes(total)} ({pct}%)")
        lines.append("  copied by boundary: " + "  ".join(
            f"{b}={human_bytes(n)}" for b, n in copied.items() if n))
        peak = max((int(c.get("peak_mem_bytes", 0)) for c in counters),
                   default=0)
        lines.append(f"  peak_mem={human_bytes(peak)} "
                     f"spill={human_bytes(csum('spill_bytes'))} "
                     f"({csum('spill_count')} spills) "
                     f"compile={csum('compile_ms')}ms")
    leaks = csum("resource_leaks")
    if leaks:
        lines.append(f"  RESOURCE LEAKS: {leaks} across "
                     f"{sum(1 for c in counters if c.get('resource_leaks'))}"
                     " queries")

    dropped = sum(e.get("dropped_events") or 0 for e in entries)
    lines.append(f"dropped_events: {dropped}")
    print("\n".join(lines))
    return 0


def prom_snapshot(path):
    """Dump this process's Prometheus registry to a file (or '-' for
    stdout) — the scrape payload without standing up the HTTP server."""
    from blaze_tpu.runtime import monitor

    text = monitor.prometheus_text()
    if path == "-":
        sys.stdout.write(text)
    else:
        with open(path, "w") as f:
            f.write(text)
        print(f"prometheus snapshot -> {path} "
              f"({len(text.splitlines())} lines)")
    return 0


# -- bench mode --------------------------------------------------------------


def validate_chrome_trace(path):
    """Structural checks on one exported trace; returns a problem list."""
    problems = []
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        return ["traceEvents missing or empty"]
    for ev in evs:
        ph = ev.get("ph")
        if ph not in ("X", "i", "M"):
            problems.append(f"unexpected phase {ph!r}")
        if ph in ("X", "i") and not isinstance(ev.get("ts"), (int, float)):
            problems.append("X/i event without numeric ts")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            problems.append("X event without numeric dur")
        if problems:
            break
    if not any(ev.get("ph") == "X" and ev.get("name") == "query"
               for ev in evs):
        problems.append("no query span in traceEvents")
    return problems


def bench(args):
    from blaze_tpu.config import conf
    from blaze_tpu.runtime import trace
    from blaze_tpu.spark import validator
    from blaze_tpu.spark.local_runner import run_plan

    tmpdir = tempfile.mkdtemp(prefix="trace_bench_tables_")
    trace_dir = tempfile.mkdtemp(prefix="trace_bench_out_")
    tables = validator.generate_tables(tmpdir, rows=args.rows)
    paths, frames = tables

    def catalogue():
        t0 = time.time()
        for query, mode in QUERIES:
            plan, _ = validator.QUERIES[query](paths, frames, mode)
            run_plan(plan, num_partitions=4, mesh_exchange="off")
        return round(time.time() - t0, 3)

    saved = {k: getattr(conf, k)
             for k in ("trace_enabled", "trace_export_dir")}
    try:
        catalogue()  # warm jit caches so the A/B measures the harness
        conf.trace_enabled = False
        t_off = catalogue()
        trace.reset()
        conf.trace_enabled = True
        conf.trace_export_dir = trace_dir
        t_on = catalogue()
        dropped = trace.TRACE.dropped
        records = len(trace.TRACE)
    finally:
        for k, v in saved.items():
            setattr(conf, k, v)
        trace.reset()

    ledger = load_ledger(trace_dir)
    traces = sorted(f for f in os.listdir(trace_dir)
                    if f.startswith("trace_") and f.endswith(".json"))
    problems = []
    if not ledger:
        problems.append("no ledger lines exported")
    if not traces:
        problems.append("no chrome traces exported")
    else:
        problems += validate_chrome_trace(os.path.join(trace_dir, traces[-1]))
    if dropped:
        problems.append(f"{dropped} events dropped at default buffer size")
    # noise gate, not a microbench: a short catalogue pass jitters tens
    # of percent on a shared CPU host, so the bound is deliberately loose
    # — it catches an accidental O(rows) cost, not a 5% regression
    if t_on > t_off * 1.5 + 1.0:
        problems.append(f"tracing overhead out of noise: "
                        f"on={t_on}s off={t_off}s")

    report = {
        "rows": args.rows,
        "catalogue_trace_off_s": t_off,
        "catalogue_trace_on_s": t_on,
        "overhead_pct": round(100 * (t_on - t_off) / t_off, 1) if t_off
        else None,
        "trace_records": records,
        "dropped_events": dropped,
        "queries_exported": len(ledger),
        "chrome_traces": len(traces),
        "problems": problems,
        "ok": not problems,
    }
    shutil.rmtree(tmpdir, ignore_errors=True)
    if not args.keep_trace_dir:
        shutil.rmtree(trace_dir, ignore_errors=True)
    else:
        report["trace_dir"] = trace_dir
    with open(args.json_out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"trace bench: off={t_off}s on={t_on}s dropped={dropped} "
          f"exports={len(ledger)}")
    print(f"trace bench {'OK' if report['ok'] else 'FAILED'} "
          f"-> {args.json_out}")
    if problems:
        for p in problems:
            print(f"  problem: {p}")
    return 0 if report["ok"] else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", nargs="?", default=None,
                    help="directory of trace_<qid>.json + ledger.jsonl "
                         "exports to summarize")
    ap.add_argument("--bench", action="store_true",
                    help="run the tracing-off vs tracing-on catalogue A/B "
                         "and emit the TRACE artifact")
    ap.add_argument("--rows", type=int, default=8000)
    ap.add_argument("--keep-trace-dir", action="store_true")
    ap.add_argument("--json-out", default="TRACE_r08.json")
    ap.add_argument("--prom-snapshot", default=None, metavar="PATH",
                    help="write this process's Prometheus registry dump "
                         "to PATH ('-' for stdout) and exit")
    args = ap.parse_args()
    if args.prom_snapshot:
        return prom_snapshot(args.prom_snapshot)
    if args.bench:
        return bench(args)
    if not args.trace_dir:
        print("usage: trace_report.py <trace_dir> | --bench", file=sys.stderr)
        return 2
    return summarize(args.trace_dir)


if __name__ == "__main__":
    sys.exit(main())
