"""Query-history reporting + regression gate (ISSUE 7: `HISTORY_r11.json`).

Three modes:

  summarize   `python tools/history_report.py <history_dir>` — digest the
              store runtime/history.py persists (sharded JSONL of run
              records): per-fingerprint stage costs and observed operator
              cardinalities via StatisticsFeed, the query-duration trend
              across runs, and any cross-run regressions the detector
              flags at the configured threshold.

  --bench     fold the committed BENCH_*.json artifacts (one per PR
              round, written by the snapshot driver around bench.py)
              into the same trend view — rc / parsed contract metric per
              round, so the single-number bench rides next to the
              per-fingerprint history.

  --gate      acceptance mode. Runs the validator mini-catalogue twice
              with the history store enabled (after a warm-up pass),
              then a third pass where the fault injector stalls one
              serde.encode call inside q2 — the detector must flag the
              slowed stage and NOTHING else (zero false positives on
              unperturbed stages), and the history-on catalogue must
              stay within noise of history-off. Emits `HISTORY_r11.json`.

    JAX_PLATFORMS=cpu python tools/history_report.py --gate \
        --json-out HISTORY_r11.json
"""

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERIES = [  # same coverage as tools/chaos_soak.py / trace_report.py
    ("q1_scan_filter_project", "bhj"),
    ("q2_q06_core_agg", "bhj"),
    ("q3_join_agg_sort", "smj"),
]

# the q2 stall the gate injects: one 400ms hang at the first
# serde.encode call — far above the detector's 100ms jitter grace, far
# below anything that could trip a watchdog
STALL_SPEC = {"seed": 7,
              "points": {"serde.encode": {"kind": "stall",
                                          "nth": 1, "ms": 400}}}


# -- summarize mode ----------------------------------------------------------


def summarize(history_dir):
    from blaze_tpu.runtime import history
    from blaze_tpu.runtime.trace import human_bytes

    store = history.HistoryStore(history_dir)
    records = store.records()
    if not records:
        print(f"no history records under {history_dir}")
        return 1
    feed = history.StatisticsFeed(records)
    lines = [f"== history report: {history_dir} "
             f"({len(records)} runs, {len(store.shards())} shards) =="]

    # query-duration trend, grouped by plan fingerprint (the whole point
    # of fingerprinting: literals change, the trend line doesn't)
    by_plan = {}
    for r in records:
        fp = r.get("plan_fingerprint") or "-"
        by_plan.setdefault(fp, []).append(r.get("duration_ms") or 0.0)
    lines.append("-- query trend (per plan fingerprint) --")
    for fp in sorted(by_plan):
        durs = by_plan[fp]
        spark = " ".join(f"{d:.0f}" for d in durs[-8:])
        lines.append(f"  {fp}  n={len(durs)}  last_ms=[{spark}]")

    # per-fingerprint stage costs
    stage_fps = [(feed.observed_stage_cost(fp), fp)
                 for fp in feed.fingerprints()["stages"]]
    stage_fps = [(c, fp) for c, fp in stage_fps if c]
    stage_fps.sort(key=lambda t: -t[0]["ms_p50"])
    lines.append("-- stage costs (observed, per fingerprint) --")
    for cost, fp in stage_fps[:12]:
        lines.append(
            f"  {fp}  {cost['kind']}[{cost['transport'] or '-'}]  "
            f"n={cost['n']} p50={cost['ms_p50']:.1f}ms "
            f"p95={cost['ms_p95']:.1f}ms "
            f"copied={human_bytes(int(cost['copied_p50']))}")

    # per-operator observed cardinalities (the statistics-feed payload
    # the fusion cost model will consume)
    lines.append("-- operator cardinalities (observed) --")
    op_fps = [(feed.observed_cardinality(fp), fp)
              for fp in feed.fingerprints()["ops"]]
    op_fps = [(c, fp) for c, fp in op_fps if c]
    op_fps.sort(key=lambda t: -t[0]["rows_p50"])
    for card, fp in op_fps[:12]:
        extra = ""
        if card.get("selectivity_p50") is not None:
            extra += f" sel={card['selectivity_p50']:.3f}"
        if card.get("groups_p50") is not None:
            extra += (f" groups={card['groups_p50']:.0f}"
                      f" dense={card['dense_ratio']:.0%}")
        lines.append(f"  {fp}  {card['op']:<18} n={card['n']} "
                     f"rows_p50={card['rows_p50']:.0f}{extra}")

    findings = history.detect_regressions(records)
    if findings:
        lines.append(f"-- REGRESSIONS ({len(findings)}) --")
        for f in findings:
            lines.append(
                f"  {f['fingerprint']} {f['metric']}: latest={f['latest']:.1f}"
                f" vs median={f['median']:.1f} "
                f"(threshold {f['threshold']:.1f}, x{f['ratio']:.2f}, "
                f"n={f['runs']}) query={f['query_id']}")
    else:
        lines.append("regressions: none")
    print("\n".join(lines))
    return 0


def bench_trend():
    """Fold the per-round BENCH_*.json artifacts into a trend table."""
    rows = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") or {}
        rows.append((doc.get("n"), os.path.basename(path),
                     doc.get("rc"), parsed))
    if not rows:
        print("no BENCH_*.json artifacts in repo root")
        return 1
    print(f"== bench trend ({len(rows)} rounds) ==")
    for n, name, rc, parsed in rows:
        if parsed:
            print(f"  r{n:02d} {name}: {parsed.get('metric')}="
                  f"{parsed.get('value')}{parsed.get('unit') or ''} "
                  f"vs_baseline={parsed.get('vs_baseline')}")
        else:
            print(f"  r{n:02d} {name}: rc={rc} (no contract line)")
    return 0


# -- gate mode ---------------------------------------------------------------


def gate(args):
    from blaze_tpu.config import conf
    from blaze_tpu.runtime import faults, history, trace
    from blaze_tpu.spark import validator
    from blaze_tpu.spark.local_runner import run_plan

    tmpdir = tempfile.mkdtemp(prefix="history_gate_tables_")
    hist_dir = tempfile.mkdtemp(prefix="history_gate_store_")
    paths, frames = validator.generate_tables(tmpdir, rows=args.rows)

    def run_one(query, mode):
        plan, _ = validator.QUERIES[query](paths, frames, mode)
        return run_plan(plan, num_partitions=4, mesh_exchange="off")

    def catalogue():
        t0 = time.time()
        for query, mode in QUERIES:
            run_one(query, mode)
        return round(time.time() - t0, 3)

    saved = {k: getattr(conf, k)
             for k in ("history_dir", "trace_enabled",
                       "fault_injection_spec")}
    problems = []
    try:
        catalogue()  # warm jit caches so the A/B measures the harness
        conf.update(history_dir="", trace_enabled=False)
        history.reset()
        t_off = catalogue()
        # two recorded baseline runs
        conf.update(history_dir=hist_dir, trace_enabled=True)
        t_on = catalogue()
        catalogue()
        # perturbed pass: stall q2's first serde.encode, then give the
        # other queries a clean third sample so the detector evaluates
        # them too (zero-false-positive check needs evaluated peers)
        faults.install(STALL_SPEC)
        slowed = run_one("q2_q06_core_agg", "bhj")
        faults.install(None)
        run_one("q1_scan_filter_project", "bhj")
        run_one("q3_join_agg_sort", "smj")

        records = history.store(hist_dir).records()
        feed = history.StatisticsFeed(records)
        findings = history.detect_regressions(records)
    finally:
        faults.install(None)
        for k, v in saved.items():
            setattr(conf, k, v)
        history.reset()
        trace.reset()

    n_stage_fps = len(feed.fingerprints()["stages"])
    n_op_fps = len(feed.fingerprints()["ops"])
    if len(records) != 3 * len(QUERIES):
        problems.append(f"expected {3 * len(QUERIES)} run records, "
                        f"got {len(records)}")
    if not n_stage_fps or not feed.observed_stage_cost(
            next(iter(feed.fingerprints()["stages"]), None)):
        problems.append("statistics feed has no stage costs")
    if not n_op_fps:
        problems.append("statistics feed has no operator cardinalities")

    slowed_qid = records[-3]["query_id"] if len(records) >= 3 else None
    true_pos = [f for f in findings if f["query_id"] == slowed_qid
                and f["metric"] == "wall_ms"]
    false_pos = [f for f in findings if f not in true_pos]
    if not true_pos:
        problems.append("detector missed the injected 400ms stall in q2")
    if false_pos:
        problems.append(
            f"{len(false_pos)} false positive(s) on unperturbed stages: "
            + "; ".join(f"{f['fingerprint']}/{f['metric']}@{f['query_id']}"
                        for f in false_pos))
    # noise gate, not a microbench (same posture as TRACE_r08): the
    # bound catches an accidental O(rows) ingest cost, not a 5% delta
    if t_on > t_off * 1.5 + 1.0:
        problems.append(f"history overhead out of noise: "
                        f"on={t_on}s off={t_off}s")

    report = {
        "rows": args.rows,
        "catalogue_history_off_s": t_off,
        "catalogue_history_on_s": t_on,
        "overhead_pct": round(100 * (t_on - t_off) / t_off, 1) if t_off
        else None,
        "runs_recorded": len(records),
        "stage_fingerprints": n_stage_fps,
        "operator_fingerprints": n_op_fps,
        "regressions_flagged": [
            {"fingerprint": f["fingerprint"], "metric": f["metric"],
             "latest": f["latest"], "median": f["median"],
             "ratio": f["ratio"], "query_id": f["query_id"]}
            for f in findings],
        "false_positives": len(false_pos),
        "slowed_query": slowed_qid,
        "problems": problems,
        "ok": not problems,
    }
    shutil.rmtree(tmpdir, ignore_errors=True)
    if args.keep_history_dir:
        report["history_dir"] = hist_dir
    else:
        shutil.rmtree(hist_dir, ignore_errors=True)
    with open(args.json_out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"history gate: off={t_off}s on={t_on}s runs={len(records)} "
          f"flagged={len(findings)} false_pos={len(false_pos)}")
    print(f"history gate {'OK' if report['ok'] else 'FAILED'} "
          f"-> {args.json_out}")
    for p in problems:
        print(f"  problem: {p}")
    return 0 if report["ok"] else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("history_dir", nargs="?", default=None,
                    help="history store directory (conf.history_dir) to "
                         "summarize")
    ap.add_argument("--bench", action="store_true",
                    help="fold the committed BENCH_*.json round artifacts "
                         "into the trend view")
    ap.add_argument("--gate", action="store_true",
                    help="run the record/record/perturb acceptance gate "
                         "and emit the HISTORY artifact")
    ap.add_argument("--rows", type=int, default=8000)
    ap.add_argument("--keep-history-dir", action="store_true")
    ap.add_argument("--json-out", default="HISTORY_r11.json")
    args = ap.parse_args()
    if args.gate:
        return gate(args)
    rc = 0
    ran = False
    if args.bench:
        rc = bench_trend()
        ran = True
    if args.history_dir:
        rc = summarize(args.history_dir) or rc
        ran = True
    if not ran:
        print("usage: history_report.py <history_dir> | --bench | --gate",
              file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
