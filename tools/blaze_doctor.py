"""blaze-doctor: query diagnosis CLI + acceptance gate (DOCTOR_r14.json).

Two modes over the pure rule engine in runtime/doctor.py:

  summarize   `python tools/blaze_doctor.py <trace_export_dir>` — doctor
              every ledger line in an export dir (the artifacts
              local_runner writes when conf.trace_export_dir is set):
              per-query critical-path breakdown, longest task chains,
              ranked findings with evidence + suggested knobs. Pass
              --history <dir> to enable the regression-vs-history rule.

  --gate      acceptance mode (`make check-doctor`). Runs the validator
              catalogue clean (after a warm-up pass) — every breakdown
              must sum to the measured wall time within 5% and NO query
              may produce a finding — then two seeded perturbations that
              the doctor must top-rank: a 400ms serde.encode stall
              (serde_bound) and a skewed-partition input where one hash
              partition holds ~97% of the rows (skewed_partition).
              Diagnosis runs three times over the same artifacts and
              must be byte-identical (the chaos-soak determinism
              contract). A mid-query Prometheus scrape must expose the
              blaze_slo_* gauges for the configured tenant. Emits
              `DOCTOR_r14.json`.

    JAX_PLATFORMS=cpu python tools/blaze_doctor.py --gate \
        --json-out DOCTOR_r14.json
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the full validator catalogue: every query shape the engine validates,
# one join mode each (the doctor reads timings, not answers)
CATALOGUE = [
    ("q1_scan_filter_project", "bhj"),
    ("q2_q06_core_agg", "bhj"),
    ("q3_join_agg_sort", "bhj"),
    ("q4_repartition_sort", "bhj"),
    ("q5_multijoin_limit", "bhj"),
    ("q6_semi_join", "smj"),
    ("q7_left_outer_join", "bhj"),
    ("q8_category_like", "bhj"),
    ("q9_substr_group", "bhj"),
]

# seeded perturbation 1: one 400ms hang at the first serde.encode call —
# the serde_encode timing window opens before the injection point, so
# the stall lands squarely in the serde term the doctor ranks
STALL_MS = 400
STALL_SPEC = {"seed": 7,
              "points": {"serde.encode": {"kind": "stall",
                                          "nth": 1, "ms": STALL_MS}}}

# seeded perturbation 2: the fault injector has no per-task targeting
# (rules fire on global call counts), so skew comes from DATA — a
# shuffle key where ~97% of rows share one value, leaving one hash
# partition (and its reduce task) holding nearly the whole table
SKEW_HOT_FRAC = 0.97

SUM_TOLERANCE = 0.05  # |sum(terms) - total_ms| <= 5% of total_ms


# -- summarize mode ----------------------------------------------------------


def summarize(trace_dir, history_dir=None):
    from blaze_tpu.runtime import doctor

    entries = doctor.diagnose_dir(trace_dir, history_dir=history_dir)
    if not entries:
        print(f"no ledger under {trace_dir} (need ledger.jsonl — set "
              f"conf.trace_export_dir when running queries)")
        return 1
    lines = [f"== blaze doctor: {trace_dir} ({len(entries)} queries) =="]
    for e in entries:
        cp = e["critical_path"]
        head = f"-- {e['query_id']}"
        if e.get("tenant_id"):
            head += f" tenant={e['tenant_id']}"
        head += f" total={cp['total_ms']:.1f}ms"
        if cp.get("top_term"):
            head += f" top={cp['top_term']}"
        lines.append(head + " --")
        lines.extend(doctor.render_critical_path(cp))
        if e["findings"]:
            findings = [doctor.Finding(**f) for f in e["findings"]]
            lines.extend(doctor.render_findings(findings))
        else:
            lines.append("  findings: none")
    print("\n".join(lines))
    return 0


# -- gate mode ---------------------------------------------------------------


def _make_skew_table(tmpdir, rows):
    """Parquet with a pathological shuffle key: SKEW_HOT_FRAC of the rows
    share k=3, the rest spread over 64 other keys — after
    shuffle_exchange on k, one partition holds nearly everything."""
    import numpy as np
    import pandas as pd
    import pyarrow.parquet as pq

    from blaze_tpu.columnar import types as T
    from blaze_tpu.spark import validator

    rng = np.random.default_rng(11)
    k = np.where(rng.random(rows) < SKEW_HOT_FRAC, 3,
                 rng.integers(4, 68, rows)).astype(np.int64)
    df = pd.DataFrame({"k": k, "v": rng.random(rows) * 1000.0})
    schema = T.Schema([T.Field("k", T.INT64), T.Field("v", T.FLOAT64)])
    path = os.path.join(tmpdir, "skewed.parquet")
    pq.write_table(validator._to_arrow_typed(df, schema), path,
                   row_group_size=65536)
    return path, schema


def _skew_plan(path, schema):
    """shuffle on the skewed key, then per-partition sort + arithmetic —
    the non-root sort keeps the O(n log n) work INSIDE the reduce task
    (a root sort would merge on the driver and hide the skew)."""
    from blaze_tpu.columnar import types as T
    from blaze_tpu.exprs import ir
    from blaze_tpu.exprs.ir import BinOp, col
    from blaze_tpu.spark import plan_model as P

    sc = P.scan(schema, [(path, [])])
    x = P.shuffle_exchange(sc, [col("k")], 4)
    srt = P.sort(x, [(col("v"), True, True), (col("k"), True, True)])
    return P.project(
        srt,
        [col("k"), ir.Binary(BinOp.ADD,
                             ir.Binary(BinOp.MUL, col("v"), col("v")),
                             col("v"))],
        ["k", "score"],
        T.Schema([T.Field("k", T.INT64), T.Field("score", T.FLOAT64)]))


def _sum_gap_pct(cp):
    total = cp.get("total_ms") or 0.0
    s = sum((cp.get("terms") or {}).values())
    if total <= 0:
        return 0.0 if s == 0 else 100.0
    return 100.0 * abs(s - total) / total


def _top_code(entry):
    return entry["findings"][0]["code"] if entry["findings"] else None


def gate(args):
    from blaze_tpu.config import conf
    from blaze_tpu.runtime import doctor, faults, history, monitor, \
        service, trace
    from blaze_tpu.spark import validator
    from blaze_tpu.spark.local_runner import run_plan

    tmpdir = tempfile.mkdtemp(prefix="doctor_gate_tables_")
    clean_dir = tempfile.mkdtemp(prefix="doctor_gate_clean_")
    stall_dir = tempfile.mkdtemp(prefix="doctor_gate_stall_")
    skew_dir = tempfile.mkdtemp(prefix="doctor_gate_skew_")
    slo_dir = tempfile.mkdtemp(prefix="doctor_gate_slo_")
    paths, frames = validator.generate_tables(tmpdir, rows=args.rows)

    def run_one(query, mode):
        plan, _ = validator.QUERIES[query](paths, frames, mode)
        return run_plan(plan, num_partitions=4, mesh_exchange="off")

    saved = {k: getattr(conf, k)
             for k in ("trace_enabled", "trace_export_dir",
                       "monitor_enabled", "doctor_enabled",
                       "history_dir", "history_retention_runs",
                       "fault_injection_spec", "tenant_slo_spec")}
    problems = []
    report = {"rows": args.rows, "skew_rows": args.skew_rows}
    try:
        # warm pass: jit + compile caches, instrumentation off — the
        # measured passes must not see first-run compile storms
        conf.update(trace_enabled=False, monitor_enabled=False,
                    history_dir="", fault_injection_spec=None,
                    tenant_slo_spec=None)
        for query, mode in CATALOGUE:
            run_one(query, mode)
        skew_path, skew_schema = _make_skew_table(tmpdir, args.skew_rows)
        run_plan(_skew_plan(skew_path, skew_schema), num_partitions=4,
                 mesh_exchange="off")

        conf.update(trace_enabled=True, monitor_enabled=True,
                    doctor_enabled=True,
                    history_retention_runs=4 * len(CATALOGUE))

        # cell 1: clean catalogue — additive breakdowns, zero findings
        conf.update(trace_export_dir=clean_dir)
        t0 = time.time()
        for query, mode in CATALOGUE:
            run_one(query, mode)
        report["catalogue_s"] = round(time.time() - t0, 3)
        clean = doctor.diagnose_dir(clean_dir)
        if len(clean) != len(CATALOGUE):
            problems.append(f"expected {len(CATALOGUE)} clean ledger "
                            f"lines, got {len(clean)}")
        gaps = [_sum_gap_pct(e["critical_path"]) for e in clean]
        report["max_sum_gap_pct"] = round(max(gaps), 3) if gaps else None
        for e, gap in zip(clean, gaps):
            if gap > 100.0 * SUM_TOLERANCE:
                problems.append(
                    f"{e['query_id']}: breakdown sums {gap:.1f}% away "
                    f"from wall time (tolerance {100 * SUM_TOLERANCE}%)")
        false_pos = [(e["query_id"], f["code"])
                     for e in clean for f in e["findings"]]
        report["clean_false_positives"] = [
            f"{q}:{c}" for q, c in false_pos]
        if false_pos:
            problems.append(
                f"{len(false_pos)} finding(s) on clean queries: "
                + "; ".join(f"{c}@{q}" for q, c in false_pos))

        # cell 2: determinism — same artifacts in, same bytes out, x3
        blobs = {json.dumps(doctor.diagnose_dir(clean_dir),
                            sort_keys=True) for _ in range(3)}
        report["deterministic"] = len(blobs) == 1
        if len(blobs) != 1:
            problems.append("diagnose_dir is not deterministic: "
                            f"{len(blobs)} distinct outputs over 3 runs")

        # cell 3: seeded serde stall must top-rank as serde_bound
        conf.update(trace_export_dir=stall_dir)
        faults.install(STALL_SPEC)
        try:
            run_one("q2_q06_core_agg", "bhj")
        finally:
            faults.install(None)
        stalled = doctor.diagnose_dir(stall_dir)
        top = _top_code(stalled[0]) if stalled else None
        report["stall_top_finding"] = top
        report["stall_findings"] = [
            f["code"] for e in stalled for f in e["findings"]]
        if top != "serde_bound":
            problems.append(
                f"seeded {STALL_MS}ms serde stall diagnosed as "
                f"{top!r}, expected serde_bound")

        # cell 4: skewed input must top-rank as skewed_partition
        conf.update(trace_export_dir=skew_dir)
        run_plan(_skew_plan(skew_path, skew_schema), num_partitions=4,
                 mesh_exchange="off")
        skewed = doctor.diagnose_dir(skew_dir)
        top = _top_code(skewed[0]) if skewed else None
        report["skew_top_finding"] = top
        report["skew_findings"] = [
            f["code"] for e in skewed for f in e["findings"]]
        if skewed and skewed[0]["findings"]:
            report["skew_evidence"] = skewed[0]["findings"][0]["evidence"]
        if top != "skewed_partition":
            problems.append(
                f"seeded skewed partition diagnosed as {top!r}, "
                f"expected skewed_partition")

        # cell 5: per-tenant SLO gauges visible in a MID-QUERY scrape
        conf.update(trace_export_dir=slo_dir,
                    tenant_slo_spec={"gate-tenant": {"latency_ms": 5.0,
                                                     "target": 0.9}})
        service.reset_slo()
        plan, _ = validator.QUERIES["q1_scan_filter_project"](
            paths, frames, "bhj")
        with service.QueryService() as svc:
            fut = svc.submit(plan, tenant_id="gate-tenant",
                             num_partitions=4, mesh_exchange="off")
            mid = monitor.prometheus_text()  # scraped while the query runs
            fut.result(timeout=120)
        final = monitor.prometheus_text()
        want = [n + '{tenant="gate-tenant"}' for n in
                ("blaze_slo_objective_ms", "blaze_slo_attainment",
                 "blaze_slo_burn_rate", "blaze_slo_breaches_total")]
        missing = [w for w in want if w not in mid]
        report["slo_gauges_mid_query"] = not missing
        if missing:
            problems.append("mid-query scrape missing SLO gauges: "
                            + ", ".join(missing))
        # the 5ms objective is unmeetable, so the completed query must
        # register as a breach in the final scrape
        breach_line = next(
            (ln for ln in final.splitlines()
             if ln.startswith('blaze_slo_breaches_total{tenant='
                              '"gate-tenant"}')), "")
        breaches = float(breach_line.rsplit(" ", 1)[-1]) \
            if breach_line else 0.0
        report["slo_breaches_recorded"] = breaches
        if breaches < 1:
            problems.append("completed query missed its 5ms objective "
                            "but no SLO breach was recorded")
    finally:
        faults.install(None)
        service.reset_slo()
        for k, v in saved.items():
            setattr(conf, k, v)
        history.reset()
        monitor.reset()
        trace.reset()

    report["problems"] = problems
    report["ok"] = not problems
    for d in (tmpdir, clean_dir, stall_dir, skew_dir, slo_dir):
        shutil.rmtree(d, ignore_errors=True)
    with open(args.json_out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"doctor gate: clean={report.get('max_sum_gap_pct')}% max gap, "
          f"false_pos={len(report.get('clean_false_positives') or [])}, "
          f"stall={report.get('stall_top_finding')}, "
          f"skew={report.get('skew_top_finding')}, "
          f"deterministic={report.get('deterministic')}")
    print(f"doctor gate {'OK' if report['ok'] else 'FAILED'} "
          f"-> {args.json_out}")
    for p in problems:
        print(f"  problem: {p}")
    return 0 if report["ok"] else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", nargs="?", default=None,
                    help="trace export dir (conf.trace_export_dir) "
                         "holding ledger.jsonl + trace_<qid>.json")
    ap.add_argument("--history", default=None,
                    help="history store dir — enables the "
                         "regression-vs-history rule")
    ap.add_argument("--gate", action="store_true",
                    help="run the seeded-perturbation acceptance gate "
                         "and emit the DOCTOR artifact")
    ap.add_argument("--rows", type=int, default=8000)
    ap.add_argument("--skew-rows", type=int, default=160_000,
                    help="rows in the skew cell's table (sized so the "
                         "hot reduce task clears the doctor's 50ms "
                         "finding floor)")
    ap.add_argument("--json-out", default="DOCTOR_r14.json")
    args = ap.parse_args()
    if args.gate:
        return gate(args)
    if not args.trace_dir:
        print("usage: blaze_doctor.py <trace_export_dir> | --gate",
              file=sys.stderr)
        return 2
    return summarize(args.trace_dir, history_dir=args.history)


if __name__ == "__main__":
    sys.exit(main())
